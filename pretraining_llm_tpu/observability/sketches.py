"""Mergeable streaming percentile sketches for the live SLO engine.

The offline analyzer (scripts/obs_report.py) computes exact percentiles
because it holds the whole run in memory; a live fleet cannot. This
module provides the streaming replacement: a t-digest-style centroid
sketch that is

  fixed-size       — at most ~2x the compression parameter centroids,
                     independent of stream length, so a long-lived
                     server's memory is bounded;
  deterministic    — compression is a pure function of the centroid
                     multiset: sorted totally by (mean, weight), merged
                     greedily under the k-scale bound. Same observations
                     (in any order, once compressed from the same
                     multiset) -> byte-identical centroids. No RNG, no
                     wall clock;
  mergeable        — ``DigestSketch.merge_all([s0, s1, ...])`` flattens
                     every input's centroids into one multiset and
                     compresses ONCE, so the fleet-wide digest is
                     invariant under any permutation of the replica list
                     (the property tests/test_observability.py pins).
                     Pairwise a.merge(b) chains are NOT order-invariant
                     (each intermediate compression is lossy) — the
                     router always aggregates via merge_all;
  serializable     — ``to_dict``/``from_dict`` round-trip exactly, so a
                     worker can ship its sketch inside a ``health_pull``
                     reply and the router merges it without re-observing.

Accuracy: centroid weight is capped at ``4 * W * q * (1-q) / compression``
(the k0-style scale function), so tails hold singleton centroids —
p99/p999 stay sharp while the median trades a little resolution. The
rank error at quantile q is bounded by half the covering centroid's
weight fraction, i.e. <= 2 * q * (1-q) / compression.

``WindowedSketch`` wraps a ring of digests bucketed on an injectable
clock: observations land in the current bucket, queries merge the
buckets inside the window, and expired buckets fall off wholesale — a
rolling-window distribution with O(buckets) memory and deterministic
behavior under a fake clock (the SLO engine's alert tests depend on it).

Pure stdlib + host-side only: importable without jax, nothing here can
touch a device.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# Flat centroid representation: (mean, weight). Kept as tuples, not a
# class — sketches are merged/serialized constantly and tuples sort
# totally with no key function.
Centroid = Tuple[float, float]

DEFAULT_COMPRESSION = 64


class DigestSketch:
    """Fixed-size deterministic t-digest-style quantile sketch."""

    __slots__ = (
        "compression", "_centroids", "_buffer", "count", "sum",
        "min", "max",
    )

    def __init__(self, compression: int = DEFAULT_COMPRESSION) -> None:
        if compression < 8:
            raise ValueError(
                f"compression must be >= 8, got {compression}"
            )
        self.compression = int(compression)
        self._centroids: List[Centroid] = []
        # Incoming observations buffer (amortizes compression); flushed
        # at 4x compression, on query, and on serialize.
        self._buffer: List[Centroid] = []
        self.count = 0.0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- ingest -------------------------------------------------------

    def observe(self, value: float, weight: float = 1.0) -> None:
        value = float(value)
        weight = float(weight)
        if not math.isfinite(value) or weight <= 0:
            return  # a NaN latency must not poison every later quantile
        self._buffer.append((value, weight))
        self.count += weight
        self.sum += value * weight
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if len(self._buffer) >= 4 * self.compression:
            self._compress()

    # -- compression --------------------------------------------------

    def _compress(self) -> None:
        """Greedy neighbor merge over the totally-sorted centroid list.

        Deterministic: sorted input (ties broken by weight — identical
        (mean, weight) pairs are interchangeable), left-to-right sweep,
        merge allowed while the candidate's weight stays under the
        k-scale bound at its midpoint quantile. Singletons are always
        representable (the bound is floored at 1 observation-weight).
        """
        if not self._buffer and len(self._centroids) <= 2 * self.compression:
            return
        pts = sorted(self._centroids + self._buffer)
        self._buffer = []
        if not pts:
            return
        total = sum(w for _, w in pts)
        out: List[Centroid] = []
        cur_mean, cur_w = pts[0]
        done_w = 0.0  # weight fully emitted before the current centroid
        for mean, w in pts[1:]:
            q = (done_w + cur_w + w / 2.0) / total
            limit = max(1.0, 4.0 * total * q * (1.0 - q) / self.compression)
            if cur_w + w <= limit:
                merged = cur_w + w
                cur_mean += (mean - cur_mean) * (w / merged)
                cur_w = merged
            else:
                out.append((cur_mean, cur_w))
                done_w += cur_w
                cur_mean, cur_w = mean, w
        out.append((cur_mean, cur_w))
        self._centroids = out

    # -- query --------------------------------------------------------

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1]; NaN when empty.

        Linear interpolation between adjacent centroid midpoints,
        clamped to the exact observed min/max at the tails (a sketch
        must never report a value outside the data's range).
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        self._compress()
        cents = self._centroids
        if not cents:
            return math.nan
        if len(cents) == 1:
            return cents[0][0]
        total = self.count
        target = q * total
        # Midpoint rank of each centroid: cum + w/2.
        cum = 0.0
        prev_rank = 0.0
        prev_val = self.min
        for mean, w in cents:
            rank = cum + w / 2.0
            if target <= rank:
                span = rank - prev_rank
                frac = (target - prev_rank) / span if span > 0 else 0.0
                return prev_val + (mean - prev_val) * frac
            prev_rank, prev_val = rank, mean
            cum += w
        # Past the last midpoint: interpolate toward the exact max.
        span = total - prev_rank
        frac = (target - prev_rank) / span if span > 0 else 1.0
        return prev_val + (self.max - prev_val) * min(1.0, frac)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else math.nan

    def centroids(self) -> List[Centroid]:
        """The compressed centroid list (flushes the buffer first)."""
        self._compress()
        return list(self._centroids)

    # -- merge --------------------------------------------------------

    @classmethod
    def merge_all(
        cls,
        sketches: Iterable["DigestSketch"],
        compression: Optional[int] = None,
    ) -> "DigestSketch":
        """Merge any number of sketches into a fresh one.

        Order-invariant: the union of centroid multisets is flattened
        and compressed exactly once, so any permutation of ``sketches``
        yields identical centroids (and therefore identical quantiles).
        """
        sketches = list(sketches)
        if compression is None:
            compression = max(
                (s.compression for s in sketches), default=DEFAULT_COMPRESSION
            )
        out = cls(compression)
        for s in sketches:
            out._buffer.extend(s._centroids)
            out._buffer.extend(s._buffer)
            out.count += s.count
            out.sum += s.sum
            if s.min < out.min:
                out.min = s.min
            if s.max > out.max:
                out.max = s.max
        out._compress()
        return out

    # -- wire ---------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe representation (rides inside health_pull replies)."""
        self._compress()
        return {
            "compression": self.compression,
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "centroids": [[m, w] for m, w in self._centroids],
        }

    @classmethod
    def from_dict(cls, payload: Dict[str, Any]) -> "DigestSketch":
        out = cls(int(payload.get("compression", DEFAULT_COMPRESSION)))
        out._centroids = [
            (float(m), float(w)) for m, w in payload.get("centroids", [])
        ]
        out.count = float(payload.get("count", 0.0))
        out.sum = float(payload.get("sum", 0.0))
        mn = payload.get("min")
        mx = payload.get("max")
        out.min = float(mn) if mn is not None else math.inf
        out.max = float(mx) if mx is not None else -math.inf
        return out

    def summary(
        self, quantiles: Sequence[float] = (0.5, 0.9, 0.95, 0.99)
    ) -> Dict[str, Any]:
        """The snapshot shape GET /slo serves per metric."""
        out: Dict[str, Any] = {"count": int(self.count)}
        if not self.count:
            return out
        out["mean"] = self.mean
        out["min"] = self.min
        out["max"] = self.max
        for q in quantiles:
            out[f"p{str(q)[2:].ljust(2, '0')}"] = self.quantile(q)
        return out


class WindowedSketch:
    """Rolling-window digest: a ring of per-bucket sketches on a clock.

    ``buckets`` sub-sketches each covering ``window_s / buckets``
    seconds; ``observe`` lands in the bucket the injected clock says is
    current, ``merged()``/``quantile()`` see only buckets newer than the
    window. Expiry is wholesale bucket drop — O(1), no re-weighting.
    Thread-safe (the bus delivers from whatever thread emitted).
    """

    def __init__(
        self,
        *,
        window_s: float = 60.0,
        buckets: int = 6,
        compression: int = DEFAULT_COMPRESSION,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if buckets < 1:
            raise ValueError(f"buckets must be >= 1, got {buckets}")
        self.window_s = float(window_s)
        self.buckets = int(buckets)
        self.bucket_s = self.window_s / self.buckets
        self.compression = int(compression)
        self._clock = clock
        self._lock = threading.Lock()
        # bucket index (floor(t / bucket_s)) -> sketch for that slice.
        self._ring: Dict[int, DigestSketch] = {}
        self.total_count = 0.0  # lifetime, survives bucket expiry

    def _bucket_id(self) -> int:
        return int(self._clock() // self.bucket_s)

    def _prune_locked(self, now_id: int) -> None:
        dead = [b for b in self._ring if b <= now_id - self.buckets]
        for b in dead:
            del self._ring[b]

    def observe(self, value: float, weight: float = 1.0) -> None:
        now_id = self._bucket_id()
        with self._lock:
            self._prune_locked(now_id)
            sk = self._ring.get(now_id)
            if sk is None:
                sk = self._ring[now_id] = DigestSketch(self.compression)
            sk.observe(value, weight)
            self.total_count += weight

    def merged(self) -> DigestSketch:
        """One digest over the live window (order-invariant merge)."""
        now_id = self._bucket_id()
        with self._lock:
            self._prune_locked(now_id)
            live = [self._ring[b] for b in sorted(self._ring)]
        return DigestSketch.merge_all(live, compression=self.compression)

    def quantile(self, q: float) -> float:
        return self.merged().quantile(q)

    @property
    def count(self) -> float:
        """Observations inside the live window."""
        now_id = self._bucket_id()
        with self._lock:
            self._prune_locked(now_id)
            return sum(s.count for s in self._ring.values())

    def summary(
        self, quantiles: Sequence[float] = (0.5, 0.9, 0.95, 0.99)
    ) -> Dict[str, Any]:
        out = self.merged().summary(quantiles)
        out["window_s"] = self.window_s
        return out


class WindowedCounts:
    """Rolling event tallies on the same bucket ring as WindowedSketch.

    The burn-rate rules need "good / bad events in the last N seconds"
    for several N at once, so buckets are sized by the FINEST window and
    ``sums(last_s)`` folds however many buckets a coarser window spans.
    Lifetime totals survive expiry (they are the error-budget ledger).
    """

    def __init__(
        self,
        *,
        horizon_s: float,
        bucket_s: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if bucket_s <= 0 or horizon_s < bucket_s:
            raise ValueError(
                f"need horizon_s >= bucket_s > 0, got "
                f"horizon_s={horizon_s} bucket_s={bucket_s}"
            )
        self.horizon_s = float(horizon_s)
        self.bucket_s = float(bucket_s)
        self._n_buckets = int(math.ceil(self.horizon_s / self.bucket_s))
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: Dict[int, Dict[str, float]] = {}
        self.totals: Dict[str, float] = {}

    def _bucket_id(self) -> int:
        return int(self._clock() // self.bucket_s)

    def add(self, key: str, n: float = 1.0) -> None:
        now_id = self._bucket_id()
        with self._lock:
            dead = [b for b in self._ring if b <= now_id - self._n_buckets]
            for b in dead:
                del self._ring[b]
            bucket = self._ring.setdefault(now_id, {})
            bucket[key] = bucket.get(key, 0.0) + n
            self.totals[key] = self.totals.get(key, 0.0) + n

    def sums(self, last_s: float) -> Dict[str, float]:
        """Tallies over the trailing ``last_s`` seconds (bucket-aligned:
        includes every bucket that overlaps the interval, so a window
        reads at worst one bucket_s wide — deterministic either way)."""
        now_id = self._bucket_id()
        span = int(math.ceil(float(last_s) / self.bucket_s))
        lo = now_id - min(span, self._n_buckets) + 1
        out: Dict[str, float] = {}
        with self._lock:
            for b, bucket in self._ring.items():
                if lo <= b <= now_id:
                    for k, v in bucket.items():
                        out[k] = out.get(k, 0.0) + v
        return out
