"""Live SLO engine: rolling latency distributions, error budgets, and
multi-window burn-rate alerting over the serving event stream.

Everything the engine knows arrives through the typed event bus — the
``req_*`` terminal events already carry the lifecycle latencies
(``queue_wait_s`` / ``ttft_s`` / ``e2e_s`` / ``tpot_s``) and, in fleet
mode, a ``replica`` index (process-mode workers forward their events
over the wire, so ONE router-side bus sees the whole fleet). The SLO
engine subscribes once and maintains:

  distributions   a ``WindowedSketch`` per latency metric, fleet-wide
                  and per replica (sketches.py: deterministic, mergeable,
                  bounded memory);
  error budgets   per ``SLOClass``: each eligible terminal is classified
                  good or bad against the class's objectives (latency
                  over threshold, or a non-``done`` terminal for the
                  availability objective); lifetime totals are the
                  budget ledger, rolling windows feed the burn rates;
  burn-rate rules Google-SRE-style multi-window alerts: a rule fires
                  when the bad-fraction / budget ratio exceeds its
                  threshold over BOTH its short and long window (the
                  short window makes the alert reset fast; the long one
                  keeps one stray slow request from paging). ``fast_burn``
                  pages on budget-torching incidents, ``slow_burn``
                  tickets on sustained leaks.

Alert lifecycle: on the good->bad edge the engine emits one
``slo_alert`` event (state="firing") carrying a monotonically-numbered
``alert_id`` AND records an ``slo_alert`` decision with the same id —
the event stream is the replayable timeline, the decision log is the
queryable ledger, and the shared id is the lineage join the tests pin.
When the burn drops back under threshold the engine emits the matching
state="resolved" event (no decision: resolution costs nobody anything).

Clocks: every window and alert decision runs on the injected ``clock``
(default ``time.monotonic``), so a test driving a fake clock gets
deterministic bucket rotation and alert edges. Evaluation happens
inline on each observed terminal — no background thread, no polling.

Cancelled terminals (``req_cancelled``) contribute to the latency
sketches but are EXCLUDED from good/bad classification: a client
hanging up is not server unavailability, and counting it either way
would let clients spend (or launder) the error budget.

Client-visible rejects (``req_rejected`` with ``fleet=True``, or with
no replica tag — a single-loop deployment) ARE availability-bad: a 429
the fleet could not absorb burns budget, which is how an injected
``reject_storm`` covering every replica trips ``fast_burn``. Internal
replica-tagged refusals the router spills to a peer are not counted —
the request may still succeed elsewhere.

Pure stdlib + host-side; importable without jax.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from pretraining_llm_tpu.observability.sketches import (
    DigestSketch,
    WindowedCounts,
    WindowedSketch,
)

# Latency fields lifted off terminal events into sketches. "queue_age"
# in the issue's terms is the admission-to-dispatch wait the engine
# already measures as queue_wait_s.
LATENCY_METRICS = ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s")

# Terminal kinds. Availability counts done vs expired/error; cancelled
# is sketched but not classified (see module docstring).
TERMINAL_KINDS = ("req_done", "req_expired", "req_error", "req_cancelled")
_CLASSIFIED_KINDS = ("req_done", "req_expired", "req_error")

# Client-visible rejects burn availability budget too: a 429 the fleet
# could not absorb is unavailability from the caller's seat (this is
# what makes an injected reject_storm trip the fast-burn rule). A
# replica-tagged reject WITHOUT the fleet flag is an internal refusal
# the router spills to a peer — the request may still succeed, so only
# the router's fleet-level reject (or a single-loop reject, which has
# no replica tag) counts.
_REJECT_KIND = "req_rejected"


@dataclass(frozen=True)
class SLOObjective:
    """One measurable promise inside an SLO class.

    ``metric`` is a latency field name (threshold_s applies) or
    ``"availability"`` (a non-done terminal is bad, threshold ignored).
    """

    metric: str
    target: float  # fraction of eligible events that must be good
    threshold_s: float = 0.0

    def __post_init__(self) -> None:
        if self.metric not in LATENCY_METRICS + ("availability",):
            raise ValueError(
                f"unknown SLO metric {self.metric!r}; expected one of "
                f"{LATENCY_METRICS + ('availability',)}"
            )
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target}"
            )
        if self.metric != "availability" and self.threshold_s <= 0:
            raise ValueError(
                f"latency objective {self.metric} needs threshold_s > 0"
            )


@dataclass(frozen=True)
class BurnRateRule:
    """Fire when burn >= threshold over BOTH windows (short <= long)."""

    name: str
    short_s: float
    long_s: float
    threshold: float
    severity: str = "page"

    def __post_init__(self) -> None:
        if not 0 < self.short_s <= self.long_s:
            raise ValueError(
                f"need 0 < short_s <= long_s, got "
                f"short_s={self.short_s} long_s={self.long_s}"
            )
        if self.threshold <= 1.0:
            raise ValueError(
                f"threshold must be > 1 (1.0 = exactly on budget), got "
                f"{self.threshold}"
            )


# The classic SRE pairing, scaled to serving-test time horizons by the
# caller via ``window_scale`` on SLOEngine (production keeps the
# defaults; a test passes a small scale and a fake clock).
DEFAULT_RULES = (
    BurnRateRule("fast_burn", short_s=60.0, long_s=300.0,
                 threshold=14.0, severity="page"),
    BurnRateRule("slow_burn", short_s=300.0, long_s=3600.0,
                 threshold=3.0, severity="ticket"),
)


@dataclass(frozen=True)
class SLOClass:
    """A named bundle of objectives sharing one error budget."""

    name: str
    objectives: Tuple[SLOObjective, ...]
    rules: Tuple[BurnRateRule, ...] = DEFAULT_RULES

    def __post_init__(self) -> None:
        if not self.objectives:
            raise ValueError(f"SLO class {self.name!r} has no objectives")

    @property
    def target(self) -> float:
        """The class target is the strictest objective's."""
        return max(o.target for o in self.objectives)

    @property
    def budget(self) -> float:
        """Error budget: tolerated bad fraction (1 - target)."""
        return 1.0 - self.target


def default_slo_classes(
    *,
    ttft_s: float = 2.0,
    e2e_s: float = 30.0,
    target: float = 0.99,
) -> Tuple[SLOClass, ...]:
    """The out-of-the-box class serve.py installs: interactive traffic
    promised a TTFT and e2e bound plus availability at ``target``."""
    return (
        SLOClass(
            "interactive",
            objectives=(
                SLOObjective("availability", target=target),
                SLOObjective("ttft_s", target=target, threshold_s=ttft_s),
                SLOObjective("e2e_s", target=target, threshold_s=e2e_s),
            ),
        ),
    )


class SLOEngine:
    """Bus subscriber maintaining sketches, budgets, and alerts."""

    def __init__(
        self,
        *,
        classes: Optional[Sequence[SLOClass]] = None,
        bus: Optional[Any] = None,
        decisions: Optional[Any] = None,
        clock: Callable[[], float] = time.monotonic,
        window_s: float = 60.0,
        window_buckets: int = 6,
        compression: int = 64,
        window_scale: float = 1.0,
    ) -> None:
        """``window_s`` sizes the latency sketches; ``window_scale``
        multiplies every rule's short/long window (tests shrink hours to
        seconds without redefining the rules). ``bus`` is subscribed to
        immediately when given; alerts are emitted back into the SAME
        bus (emit is re-entrant: subscribers run outside its lock)."""
        if window_scale <= 0:
            raise ValueError(f"window_scale must be > 0, got {window_scale}")
        self.classes: Tuple[SLOClass, ...] = tuple(
            classes if classes is not None else default_slo_classes()
        )
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO class names: {names}")
        self.bus = bus
        self.decisions = decisions
        self._clock = clock
        self.window_s = float(window_s)
        self.window_scale = float(window_scale)
        self._lock = threading.Lock()

        def make_windowed() -> Dict[str, WindowedSketch]:
            return {
                m: WindowedSketch(
                    window_s=self.window_s,
                    buckets=window_buckets,
                    compression=compression,
                    clock=clock,
                )
                for m in LATENCY_METRICS
            }

        self._make_windowed = make_windowed
        self._fleet = make_windowed()
        self._per_replica: Dict[int, Dict[str, WindowedSketch]] = {}

        # One counts ring per class, bucketed at the finest rule window
        # (quartered so a "short" window spans >= 4 buckets and rotates
        # smoothly), horizoned at the coarsest.
        self._counts: Dict[str, WindowedCounts] = {}
        for cls in self.classes:
            scaled = [
                (r.short_s * self.window_scale, r.long_s * self.window_scale)
                for r in cls.rules
            ] or [(self.window_s, self.window_s)]
            finest = min(s for s, _ in scaled)
            horizon = max(l for _, l in scaled)
            self._counts[cls.name] = WindowedCounts(
                horizon_s=max(horizon, finest),
                bucket_s=max(finest / 4.0, 1e-9),
                clock=clock,
            )

        # Alert state: (class, rule) -> firing record; plus a bounded
        # history tail and lifetime counters for /slo + metrics.
        self._active: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._history: List[Dict[str, Any]] = []
        self._alert_seq = 0
        self.alerts_fired = 0
        self.events_seen = 0

        if bus is not None:
            bus.subscribe(self.observe)

    # -- ingest -------------------------------------------------------

    def observe(self, record: Dict[str, Any]) -> None:
        """Bus subscriber: terminal req events feed sketches + budgets."""
        kind = record.get("event")
        if kind == _REJECT_KIND:
            if record.get("fleet") or "replica" not in record:
                self._observe_reject(record)
            return
        if kind not in TERMINAL_KINDS:
            return
        replica = record.get("replica")
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self.events_seen += 1
            for metric in LATENCY_METRICS:
                val = record.get(metric)
                if isinstance(val, (int, float)):
                    self._fleet[metric].observe(float(val))
                    if replica is not None:
                        per = self._per_replica.get(int(replica))
                        if per is None:
                            per = self._per_replica[int(replica)] = (
                                self._make_windowed()
                            )
                        per[metric].observe(float(val))
            if kind in _CLASSIFIED_KINDS:
                for cls in self.classes:
                    bad_objective = self._classify_locked(cls, kind, record)
                    counts = self._counts[cls.name]
                    counts.add("events")
                    if bad_objective is not None:
                        counts.add("bad")
                        counts.add(f"bad_{bad_objective}")
                transitions = self._evaluate_locked(record)
        # Emission happens OUTSIDE the lock: the bus will call us back
        # re-entrantly for the slo_alert event we emit.
        for rec in transitions:
            self._announce(rec)

    def _observe_reject(self, record: Dict[str, Any]) -> None:
        """A client-visible 429: availability-bad for every class that
        promises availability; no latency fields to sketch."""
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self.events_seen += 1
            for cls in self.classes:
                if not any(
                    o.metric == "availability" for o in cls.objectives
                ):
                    continue
                counts = self._counts[cls.name]
                counts.add("events")
                counts.add("bad")
                counts.add("bad_availability")
            transitions = self._evaluate_locked(record)
        for rec in transitions:
            self._announce(rec)

    @staticmethod
    def _classify_locked(
        cls: SLOClass, kind: str, record: Dict[str, Any]
    ) -> Optional[str]:
        """First violated objective's metric name, or None when good."""
        for obj in cls.objectives:
            if obj.metric == "availability":
                if kind != "req_done":
                    return "availability"
            else:
                val = record.get(obj.metric)
                if isinstance(val, (int, float)) and val > obj.threshold_s:
                    return obj.metric
        return None

    # -- burn-rate evaluation -----------------------------------------

    def _burn(
        self, counts: WindowedCounts, budget: float, last_s: float
    ) -> Tuple[float, float, float]:
        """(burn_rate, bad, events) over the trailing window."""
        sums = counts.sums(last_s)
        events = sums.get("events", 0.0)
        bad = sums.get("bad", 0.0)
        if events <= 0:
            return 0.0, bad, events
        return (bad / events) / budget, bad, events

    def _evaluate_locked(
        self, record: Dict[str, Any]
    ) -> List[Dict[str, Any]]:
        """Recompute every rule; return fire/resolve transition records."""
        now = self._clock()
        out: List[Dict[str, Any]] = []
        for cls in self.classes:
            counts = self._counts[cls.name]
            for rule in cls.rules:
                short_s = rule.short_s * self.window_scale
                long_s = rule.long_s * self.window_scale
                burn_short, bad_s, ev_s = self._burn(
                    counts, cls.budget, short_s
                )
                burn_long, bad_l, ev_l = self._burn(
                    counts, cls.budget, long_s
                )
                firing = (
                    burn_short >= rule.threshold
                    and burn_long >= rule.threshold
                )
                key = (cls.name, rule.name)
                active = self._active.get(key)
                if firing and active is None:
                    self._alert_seq += 1
                    self.alerts_fired += 1
                    alert = {
                        "alert_id": f"slo-{self._alert_seq}",
                        "state": "firing",
                        "slo_class": cls.name,
                        "rule": rule.name,
                        "severity": rule.severity,
                        "threshold": rule.threshold,
                        "burn_short": round(burn_short, 4),
                        "burn_long": round(burn_long, 4),
                        "window_short_s": short_s,
                        "window_long_s": long_s,
                        "bad_short": bad_s,
                        "events_short": ev_s,
                        "bad_long": bad_l,
                        "events_long": ev_l,
                        "budget": cls.budget,
                        "t_fired_s": now,
                    }
                    # The event that tipped the burn over: its trace_id
                    # (when present) is the alert->request lineage.
                    tid = record.get("trace_id")
                    if tid:
                        alert["trigger_trace_id"] = tid
                    if record.get("replica") is not None:
                        alert["trigger_replica"] = record["replica"]
                    self._active[key] = alert
                    self._push_history_locked(alert)
                    out.append(dict(alert))
                elif not firing and active is not None:
                    resolved = {
                        "alert_id": active["alert_id"],
                        "state": "resolved",
                        "slo_class": cls.name,
                        "rule": rule.name,
                        "severity": rule.severity,
                        "threshold": rule.threshold,
                        "burn_short": round(burn_short, 4),
                        "burn_long": round(burn_long, 4),
                        "t_fired_s": active["t_fired_s"],
                        "t_resolved_s": now,
                        "dur_s": now - active["t_fired_s"],
                    }
                    del self._active[key]
                    self._push_history_locked(resolved)
                    out.append(resolved)
        return out

    def _push_history_locked(self, rec: Dict[str, Any]) -> None:
        self._history.append(dict(rec))
        if len(self._history) > 256:
            del self._history[: len(self._history) - 256]

    def _announce(self, rec: Dict[str, Any]) -> None:
        if self.bus is not None:
            self.bus.emit("slo_alert", **rec)
        if self.decisions is not None and rec["state"] == "firing":
            self.decisions.record(
                "slo_alert",
                trace_id=rec.get("trigger_trace_id"),
                alert_id=rec["alert_id"],
                slo_class=rec["slo_class"],
                rule=rec["rule"],
                severity=rec["severity"],
                burn_short=rec["burn_short"],
                burn_long=rec["burn_long"],
                threshold=rec["threshold"],
            )

    # -- evaluation without traffic -----------------------------------

    def tick(self) -> List[Dict[str, Any]]:
        """Clock-driven re-evaluation (resolves alerts when traffic
        stops arriving; serve.py calls it from the health loop). Returns
        the transition records it announced."""
        with self._lock:
            transitions = self._evaluate_locked({})
        for rec in transitions:
            self._announce(rec)
        return transitions

    # -- surfaces -----------------------------------------------------

    def merged_sketch(self, metric: str) -> DigestSketch:
        with self._lock:
            return self._fleet[metric].merged()

    def snapshot(self) -> Dict[str, Any]:
        """The GET /slo body: distributions, budgets, alerts.

        Ticks first so a poller sees alerts resolve even when traffic
        has stopped arriving (no separate health thread required).
        """
        self.tick()
        with self._lock:
            now = self._clock()
            latency: Dict[str, Any] = {
                "fleet": {
                    m: self._fleet[m].summary() for m in LATENCY_METRICS
                },
                "replicas": {
                    str(i): {m: per[m].summary() for m in LATENCY_METRICS}
                    for i, per in sorted(self._per_replica.items())
                },
            }
            classes: Dict[str, Any] = {}
            for cls in self.classes:
                counts = self._counts[cls.name]
                totals = dict(counts.totals)
                events = totals.get("events", 0.0)
                bad = totals.get("bad", 0.0)
                bad_frac = bad / events if events else 0.0
                burn: Dict[str, Any] = {}
                for rule in cls.rules:
                    short_s = rule.short_s * self.window_scale
                    long_s = rule.long_s * self.window_scale
                    bs, _, _ = self._burn(counts, cls.budget, short_s)
                    bl, _, _ = self._burn(counts, cls.budget, long_s)
                    burn[rule.name] = {
                        "short": round(bs, 4),
                        "long": round(bl, 4),
                        "threshold": rule.threshold,
                        "window_short_s": short_s,
                        "window_long_s": long_s,
                        "firing": (cls.name, rule.name) in self._active,
                    }
                classes[cls.name] = {
                    "target": cls.target,
                    "budget": cls.budget,
                    "objectives": [
                        {
                            "metric": o.metric,
                            "target": o.target,
                            **(
                                {"threshold_s": o.threshold_s}
                                if o.metric != "availability" else {}
                            ),
                        }
                        for o in cls.objectives
                    ],
                    "events": int(events),
                    "bad": int(bad),
                    "bad_frac": round(bad_frac, 6),
                    "budget_spent_frac": round(
                        min(1.0, bad_frac / cls.budget), 6
                    ) if cls.budget else 1.0,
                    "bad_by_objective": {
                        k[len("bad_"):]: int(v)
                        for k, v in sorted(totals.items())
                        if k.startswith("bad_")
                    },
                    "burn": burn,
                }
            return {
                "t_mono": now,
                "window_s": self.window_s,
                "events_seen": self.events_seen,
                "latency": latency,
                "classes": classes,
                "alerts": {
                    "active": [dict(a) for a in self._active.values()],
                    "fired_total": self.alerts_fired,
                    "history_tail": [dict(r) for r in self._history[-32:]],
                },
            }
