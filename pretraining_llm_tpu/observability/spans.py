"""Host-side nested spans -> Chrome trace-event JSON (Perfetto-viewable).

The XLA profiler (utils/profiling.py) answers "what is the DEVICE doing";
these spans answer "what is the HOST loop doing" — where a wall-clock minute
went when it wasn't step time: eval, checkpoint IO, rollback restores,
supervisor gaps. The exported file uses the Chrome trace-event format, so it
opens in Perfetto (ui.perfetto.dev) alongside the xplane dumps from
``--profile`` and lines up on wall time.

Cost model: ``span()`` does two ``perf_counter`` reads and one list append —
no device syncs, no allocation beyond the tuple — so it is safe to use
anywhere on the host, though the trainer only brackets off-path work (the
per-step path records nothing). Memory is bounded: past ``max_events`` new
spans are counted as dropped instead of recorded.

Spans nest per-thread: each records its thread id and stack depth, and the
"X" (complete) Chrome events reconstruct the nesting from time containment.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple


class SpanRecorder:
    def __init__(self, max_events: int = 200_000) -> None:
        self.max_events = max_events
        # (name, t_start perf_counter s, dur s, thread id, depth, meta)
        self._events: List[Tuple[str, float, float, int, int, Dict[str, Any]]] = []
        self._dropped = 0
        self._lock = threading.Lock()
        self._local = threading.local()
        # Anchor for converting perf_counter timestamps to epoch us at
        # export: one wall/perf pair read together at construction.
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()

    @contextlib.contextmanager
    def span(self, name: str, **meta: Any) -> Iterator[Dict[str, Any]]:
        """Record a span; ``meta`` (plus anything the body adds to the
        yielded dict) lands in the Chrome trace event's ``args``, so
        per-span counters — e.g. the serving scheduler's host-blocked
        seconds per decode window — are inspectable in Perfetto. Values
        must be JSON-serializable."""
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        t0 = time.perf_counter()
        out: Dict[str, Any] = dict(meta)
        try:
            yield out
        finally:
            dur = time.perf_counter() - t0
            self._local.depth = depth
            with self._lock:
                if len(self._events) < self.max_events:
                    self._events.append(
                        (name, t0, dur, threading.get_ident(), depth, out)
                    )
                else:
                    self._dropped += 1

    def record(
        self,
        name: str,
        t0: float,
        dur: float,
        *,
        meta: Optional[Dict[str, Any]] = None,
        track: Optional[str] = None,
    ) -> None:
        """Record a COMPLETED span with explicit timestamps (perf_counter
        seconds). The request tracer needs this because its spans start
        and end on different threads — a context manager cannot bracket
        them. ``track`` places the span on a named virtual track in the
        Chrome trace export (per-request waterfalls) instead of the
        calling thread's row."""
        m = dict(meta) if meta else {}
        if track is not None:
            m["_track"] = track
        with self._lock:
            if len(self._events) < self.max_events:
                self._events.append(
                    (name, t0, dur, threading.get_ident(), 0, m)
                )
            else:
                self._dropped += 1

    def drain(self) -> Tuple[List[Tuple[str, float, float, int, int, Dict[str, Any]]], int]:
        """Pop every recorded span plus the drop count accumulated since
        the last drain. This is the worker-side export path: the bounded
        ``_events`` list doubles as the span-export buffer, spans ship
        exactly once, and resetting the drop counter makes the returned
        count an increment the parent can feed a monotonic counter."""
        with self._lock:
            events, self._events = self._events, []
            dropped, self._dropped = self._dropped, 0
        return events, dropped

    # -- aggregate views ----------------------------------------------

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name count + total/max seconds (host accounting). ``max_s``
        singles out the straggler occurrence — for the serving reap span
        that is the window where the host actually blocked on the device."""
        with self._lock:
            events = list(self._events)
        out: Dict[str, Dict[str, float]] = {}
        for name, _t0, dur, _tid, _depth, _meta in events:
            agg = out.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += dur
            agg["max_s"] = max(agg["max_s"], dur)
        return out

    @property
    def dropped(self) -> int:
        return self._dropped

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON object ("X" complete events, us units).

        Spans recorded with a ``track`` (the request tracer's waterfalls)
        render on synthetic tids with a thread_name metadata event each,
        so Perfetto shows one named row per request next to the real host
        threads. A nonzero dropped count is surfaced as an explicit
        instant event IN the trace — a saturated recorder must not look
        like a complete one."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        pid = os.getpid()
        trace = []
        track_tids: Dict[str, int] = {}
        t_last = 0.0
        for name, t0, dur, tid, depth, meta in events:
            track = meta.get("_track")
            if track is not None:
                vt = track_tids.get(track)
                if vt is None:
                    # Virtual tids far above any real thread id's low bits
                    # collide with nothing Perfetto groups by.
                    vt = track_tids[track] = (1 << 22) + len(track_tids)
                    trace.append({
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": vt, "args": {"name": track},
                    })
                tid = vt
                meta = {k: v for k, v in meta.items() if k != "_track"}
            ts = (self._wall0 + (t0 - self._perf0)) * 1e6
            t_last = max(t_last, ts + dur * 1e6)
            trace.append({
                "name": name,
                "ph": "X",
                "ts": ts,
                "dur": dur * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {"depth": depth, **meta},
            })
        if dropped:
            trace.append({
                "name": "spans_dropped", "ph": "i", "s": "p", "pid": pid,
                "tid": 0, "ts": t_last, "args": {"dropped": dropped},
            })
        return {
            "traceEvents": trace,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": dropped},
        }

    def export(self, path: str) -> str:
        """Write the Chrome trace JSON atomically; returns the path."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0


# Module-level default recorder: layers without a hub reference (the
# checkpoint module) record into this; the trainer's hub adopts it so their
# spans land in the same export.
_default: Optional[SpanRecorder] = None


def get_recorder() -> SpanRecorder:
    global _default
    if _default is None:
        _default = SpanRecorder()
    return _default


def set_recorder(recorder: SpanRecorder) -> None:
    """Install `recorder` as the module default (the hub adopts its own so
    checkpoint-layer spans land in the exported trace)."""
    global _default
    _default = recorder


def span(name: str, **meta: Any):
    """Convenience: a span on the module-level default recorder."""
    return get_recorder().span(name, **meta)
