"""Per-request distributed tracing for the serving path.

A request's latency story crosses three thread domains — the gateway
handler that accepted it, the engine-loop thread that schedules it, and
the device windows it rides — so nested context-manager spans (spans.py)
cannot describe it: its queue wait STARTS on one thread and ENDS on
another, and its decode windows overlap each other under deep
pipelining. This module adds the request-scoped half:

  SpanContext       trace-id/span-id pair, parsed from / rendered to the
                    W3C ``traceparent`` header (an inbound id is honored,
                    so the gateway joins a caller's existing trace);
  RequestTrace      one request's span-tree builder: explicit-timestamp
                    child spans (queue, admission, prefill, each decode
                    window) parented under a single root ``req.request``
                    span, recorded into the shared SpanRecorder so
                    Perfetto shows gateway threads, the engine loop and
                    per-request waterfalls on ONE timeline (each request
                    renders on its own virtual track);
  Tracer            mints RequestTraces; per-request sampling happens
                    here — an unsampled request gets ``None`` and every
                    recording site guards on it, so disabled tracing
                    costs one attribute check.

Every span's args carry ``trace_id``/``span_id``/``parent_span_id``; the
EventBus ``req_*`` records carry the same ``trace_id``, which is the
cross-link scripts/obs_report.py --slo joins on.
"""

from __future__ import annotations

import random
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from pretraining_llm_tpu.observability import spans as _spans

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass(frozen=True)
class SpanContext:
    """An immutable (trace_id, span_id) pair plus the sampling decision."""

    trace_id: str
    span_id: str
    sampled: bool = True


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C ``traceparent`` header; returns None on anything
    malformed (the spec says: ignore and start a fresh trace — a broken
    client header must never 500 a generate call). All-zero trace or span
    ids are invalid per spec and also return None."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(
        trace_id=trace_id, span_id=span_id, sampled=bool(int(flags, 16) & 0x01)
    )


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


class RequestTrace:
    """One request's span tree. Child spans take EXPLICIT perf_counter
    timestamps because their endpoints live on different threads; all
    children parent directly under the root request span (a two-level
    tree — deep nesting would only restate the names). ``marks`` is a
    scratch dict the frontend/engine use to carry boundary timestamps
    (submit, admit) between the threads that observe them; the engine
    loop is the only writer after submit, so no lock is needed there.
    """

    __slots__ = (
        "trace_id", "root_id", "parent_id", "marks", "t0",
        "_recorder", "_track", "_rng", "_finished", "_lock",
        "finish_deferred",
    )

    def __init__(
        self,
        recorder: _spans.SpanRecorder,
        trace_id: str,
        *,
        parent_id: Optional[str] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._recorder = recorder
        self._rng = rng if rng is not None else random
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.root_id = self._new_span_id()
        self._track = f"req {trace_id[:12]}"
        self.t0 = time.perf_counter()
        self.marks: Dict[str, float] = {"start": self.t0}
        self._finished = False
        # When True, the terminal paths that normally finish() the trace
        # (EngineLoop._terminal / _rejected) record their spans but leave
        # the root open — the fleet router owns the root of a lineage
        # tree and finishes it exactly once, after redrives settle.
        self.finish_deferred = False
        self._lock = threading.Lock()

    def _new_span_id(self) -> str:
        return f"{self._rng.getrandbits(64) or 1:016x}"

    def new_span_id(self) -> str:
        """Mint a span id under this trace's RNG — used by the router to
        pre-allocate a placement-attempt span id so it can hand workers a
        ``traceparent`` pointing AT the attempt before the attempt span
        itself is recorded (spans are written at completion)."""
        return self._new_span_id()

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.root_id, sampled=True)

    def span(
        self,
        name: str,
        t0: float,
        t1: Optional[float] = None,
        *,
        span_id: Optional[str] = None,
        **meta: Any,
    ) -> None:
        """Record one completed child span [t0, t1] (perf_counter
        seconds); ``t1`` defaults to now. ``span_id`` lets a caller that
        pre-allocated the id (``new_span_id``, the router's attempt
        spans) record under it so grandchildren minted earlier still
        parent correctly."""
        end = time.perf_counter() if t1 is None else t1
        self._recorder.record(
            name,
            t0,
            max(0.0, end - t0),
            meta={
                "trace_id": self.trace_id,
                "span_id": span_id if span_id is not None else self._new_span_id(),
                "parent_span_id": self.root_id,
                **meta,
            },
            track=self._track,
        )

    def event(self, name: str, **meta: Any) -> None:
        """Zero-duration child span (a point on the waterfall). One
        clock read serves as both endpoints — two reads would make the
        instant negative-width after the exporter's subtraction."""
        now = time.perf_counter()
        self.span(name, now, now, **meta)

    def finish(self, status: str, **meta: Any) -> bool:
        """Record the terminal point and the root request span (t0 ->
        now). Idempotent: exactly one root per trace, whichever of the
        loop terminal / gateway rejection paths gets here first wins.
        Returns False if the trace was already finished."""
        with self._lock:
            if self._finished:
                return False
            self._finished = True
        end = time.perf_counter()
        self.span("req.terminal", end, end, status=status)
        root_meta: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": self.root_id,
            "status": status,
            **meta,
        }
        if self.parent_id is not None:
            root_meta["parent_span_id"] = self.parent_id
        self._recorder.record(
            "req.request",
            self.t0,
            max(0.0, end - self.t0),
            meta=root_meta,
            track=self._track,
        )
        return True

    @property
    def finished(self) -> bool:
        return self._finished


class Tracer:
    """Mints per-request traces into one SpanRecorder.

    ``sample`` is the head-sampling fraction for requests WITHOUT an
    inbound ``traceparent``; an inbound header's sampled flag is honored
    verbatim (the caller already decided). ``seed`` makes id generation
    and sampling deterministic for tests; production leaves it None.
    """

    def __init__(
        self,
        recorder: Optional[_spans.SpanRecorder] = None,
        *,
        sample: float = 1.0,
        seed: Optional[int] = None,
    ) -> None:
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self._recorder = recorder if recorder is not None else _spans.get_recorder()
        self.sample = float(sample)
        self._rng = random.Random(seed) if seed is not None else random.Random()
        self._lock = threading.Lock()

    @property
    def recorder(self) -> _spans.SpanRecorder:
        return self._recorder

    def begin_request(
        self, traceparent: Optional[str] = None
    ) -> Optional[RequestTrace]:
        """Start (or join) a trace for one request; None = unsampled,
        and every downstream site records nothing for this request."""
        inbound = parse_traceparent(traceparent)
        with self._lock:
            if inbound is not None:
                sampled = inbound.sampled
            else:
                sampled = self.sample > 0.0 and (
                    self.sample >= 1.0 or self._rng.random() < self.sample
                )
            if not sampled:
                return None
            trace_id = (
                inbound.trace_id
                if inbound is not None
                else f"{self._rng.getrandbits(128) or 1:032x}"
            )
            return RequestTrace(
                self._recorder,
                trace_id,
                parent_id=inbound.span_id if inbound is not None else None,
                rng=self._rng,
            )
