from pretraining_llm_tpu.ops.attention import multihead_attention, naive_attention  # noqa: F401
