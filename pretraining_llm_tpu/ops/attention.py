"""Attention ops: naive einsum path + implementation dispatch.

The reference computes attention one head at a time in a Python loop, fully
materializing (B, T, T) scores per head with a pre-registered tril mask buffer
(`/root/reference/src/models/attention.py:47-57,95`). TPU-first redesign:

  - All heads batch into single einsums so the MXU sees one large matmul
    (`bqhd,bkhd->bhqk`), not H small ones.
  - The causal mask is index arithmetic fused by XLA — never a materialized
    parameter buffer (the reference wastes ~1 GB on duplicate masks, SURVEY
    §A B10).
  - Softmax runs in fp32 regardless of compute dtype (bf16 exp/sum loses
    accuracy), matmuls accumulate fp32 via preferred_element_type.
  - `impl='flash'` routes to the Pallas blockwise kernel (ops.flash_attention);
    `impl='ring'` to sequence-parallel ring attention (parallel.ring_attention).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def naive_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
    causal: bool = True,
    segments: Optional[jax.Array] = None,
    window: int = 0,
) -> jax.Array:
    """Reference einsum attention. q: (B, Tq, H, Dh); k, v: (B, Tk, G, Dh).

    G (KV heads) may divide H (grouped-query attention): the grouped einsum
    attends each group of H/G query heads against its shared KV head without
    materializing repeated K/V — the GQA cache-bandwidth win.

    ``q_positions``/``kv_positions`` (shape (Tq,), (Tk,)) define causality for
    KV-cached decode where the query block sits at an offset; they default to
    aligned ranges. ``kv_mask`` (B, Tk) masks out unwritten cache slots.
    ``segments`` (B, T) int32 document ids (self-attention, Tq == Tk):
    attention never crosses a document boundary (packed-sequence training).
    """
    b, tq, h, dh = q.shape
    tk, g = k.shape[1], k.shape[2]
    scale = 1.0 / (dh**0.5)
    qg = q.reshape(b, tq, g, h // g, dh)
    scores = jnp.einsum(
        "bqgrd,bkgd->bgrqk", qg, k, preferred_element_type=jnp.float32
    ) * scale  # (B, G, H/G, Tq, Tk)
    if causal or window:
        if q_positions is None:
            q_positions = jnp.arange(tq) + (tk - tq)  # aligned suffix by default
        if kv_positions is None:
            kv_positions = jnp.arange(tk)
    if causal:
        causal_mask = q_positions[:, None] >= kv_positions[None, :]  # (Tq, Tk)
        scores = jnp.where(causal_mask[None, None, None, :, :], scores, -jnp.inf)
    if window:
        # Sliding window: a query sees only the last `window` positions —
        # the cached-decode form of Mistral-style attention (old cache
        # slots are masked, not evicted).
        w_ok = (q_positions[:, None] - kv_positions[None, :]) < window
        scores = jnp.where(w_ok[None, None, None, :, :], scores, -jnp.inf)
    if kv_mask is not None:
        # (B, Tk) masks unwritten cache slots uniformly; (B, Tq, Tk)
        # additionally varies by query — the multi-token paged verify's
        # per-row causal frontier (each of the Tq speculative tokens sees
        # a different prefix of its row's pool blocks).
        kv_mask_q = kv_mask if kv_mask.ndim == 3 else kv_mask[:, None, :]
        scores = jnp.where(kv_mask_q[:, None, None, :, :], scores, -jnp.inf)
    if segments is not None:
        if tq != tk:
            raise ValueError("segments requires self-attention (Tq == Tk)")
        seg_ok = segments[:, :, None] == segments[:, None, :]  # (B, Tq, Tk)
        scores = jnp.where(seg_ok[:, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    if kv_mask is not None:
        # A query slot whose EVERY key is masked (a dead left-pad slot in
        # ragged decode) softmaxes to NaN (0/0). Zero exactly those rows —
        # derived from the MASKS, not from isfinite(), so genuine NaNs from
        # corrupt weights still propagate loudly. Without this, downstream
        # layers' 0-weight attention to the dead slot contributes 0*NaN =
        # NaN, poisoning every real slot in the batch row.
        if causal:
            valid = causal_mask[None, :, :] & kv_mask_q  # (B,Tq,Tk)
        else:
            valid = jnp.broadcast_to(kv_mask_q, (b, tq, tk))
        dead = ~valid.any(axis=-1)  # (B, Tq)
        probs = jnp.where(dead[:, None, None, :, None], 0.0, probs)
    out = jnp.einsum(
        "bgrqk,bkgd->bqgrd", probs.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return out.reshape(b, tq, h, dh).astype(q.dtype)


def multihead_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "naive",
    causal: bool = True,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
    block_q: int = 0,
    block_kv: int = 0,
    ring_layout: str = "contiguous",
    segments: Optional[jax.Array] = None,
    window: int = 0,
    heads_major: bool = False,
) -> jax.Array:
    """Dispatch over attention implementations.

    ``heads_major=True`` is flash-only: operands (B, H|G, T, D), result
    (B, H, T, D) — the kernel-native layout (see ops.flash_attention).

    'ring' routes to `parallel.ring_attention` (shard_map over the active
    mesh's 'seq' axis, read from `parallel.sharding.current_mesh()` at trace
    time). Without a seq axis, or for KV-cached decode (kv_mask set), it
    degrades to the dense path — the correct single-shard form.
    ``ring_layout="zigzag"`` asserts the caller already zigzag-permuted the
    sequence dim (models.transformer.loss_fn does this).
    """
    if heads_major and (
        impl != "flash" or q_positions is not None or kv_positions is not None
        or kv_mask is not None
    ):
        raise ValueError(
            "heads_major is the flash TRAINING layout only (no cached-"
            "decode positions/masks, no other impls)"
        )
    if impl in ("ring", "ulysses"):
        if window:
            raise ValueError(
                "sliding-window attention is not supported by the "
                "ring/ulysses sequence-parallel attention paths"
            )
        if segments is not None:
            # The rotating-KV / all-to-all layouts would need segment ids
            # threaded through their collectives; config validation forbids
            # doc_mask with these impls — this is the backstop.
            raise ValueError(
                "segments (document masking) is not supported by the "
                "ring/ulysses sequence-parallel attention paths"
            )
        from pretraining_llm_tpu.parallel.sharding import current_mesh

        mesh = current_mesh()
        if mesh is not None and mesh.shape.get("seq", 1) > 1 and kv_mask is None:
            if impl == "ring":
                from pretraining_llm_tpu.parallel.ring_attention import ring_attention

                return ring_attention(
                    q, k, v, mesh, causal=causal, layout=ring_layout,
                    block_kv=block_kv or 512,
                )
            from pretraining_llm_tpu.parallel.ulysses import ulysses_attention

            return ulysses_attention(
                q, k, v, mesh, causal=causal, block_q=block_q, block_kv=block_kv
            )
        # No seq axis on the active mesh (or cached decode): the dense path is
        # the correct degenerate form.
        impl = "naive"
    if impl == "naive":
        return naive_attention(
            q,
            k,
            v,
            causal=causal,
            q_positions=q_positions,
            kv_positions=kv_positions,
            kv_mask=kv_mask,
            segments=segments,
            window=window,
        )
    if impl == "flash":
        if q_positions is not None or kv_positions is not None or kv_mask is not None:
            if segments is not None:
                # Loud, like the ring/ulysses backstop: silently dropping
                # the mask here would reintroduce the cross-document leak
                # the feature exists to prevent.
                raise ValueError(
                    "segments (document masking) is not supported on the "
                    "cached-decode attention path"
                )
            # Cached decode shapes are small; the flash kernel targets training.
            return naive_attention(
                q,
                k,
                v,
                causal=causal,
                window=window,
                q_positions=q_positions,
                kv_positions=kv_positions,
                kv_mask=kv_mask,
            )
        from pretraining_llm_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_kv=block_kv,
            segments=segments, window=window, heads_major=heads_major,
        )
    raise ValueError(f"unknown attention impl {impl!r}")
