"""Flash attention: blockwise online-softmax, O(T) memory.

Eliminates the reference's fully-materialized (B, H, T, T) score tensor
(`/root/reference/src/models/attention.py:51-57`) — the exact memory wall that
caps its context at 512. Two tiers:

  - `blockwise_attention` (this module, always available): FlashAttention-2
    schedule expressed in pure JAX — `lax.scan` over KV blocks with running
    (max, sum) renormalization, `jax.checkpoint` on the inner step so autodiff
    recomputes score blocks instead of storing them. XLA maps the per-block
    einsums onto the MXU; this is the correctness baseline and the fallback on
    CPU.
  - `ops.pallas_flash` (TPU): the hand-tiled Pallas kernel with fused masking
    and VMEM-resident blocks, selected automatically on TPU backends.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pick_block(t: int, requested: int, default: int) -> int:
    if requested > 0:
        block = requested
    else:
        block = default
    block = min(block, t)
    while t % block != 0:  # shapes in this framework are powers of two; be safe
        block //= 2
        if block == 0:
            return t
    return max(block, 1)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
) -> jax.Array:
    """Online-softmax attention. q, k, v: (B, T, H, Dh) -> (B, T, H, Dh)."""
    b, t, h, dh = q.shape
    bq = _pick_block(t, block_q, 512)
    bk = _pick_block(t, block_kv, 512)
    nq, nk = t // bq, t // bk
    scale = 1.0 / (dh**0.5)

    qb = q.reshape(b, nq, bq, h, dh)
    kb = k.reshape(b, nk, bk, h, dh)
    vb = v.reshape(b, nk, bk, h, dh)

    q_ids = jnp.arange(bq)
    k_ids = jnp.arange(bk)

    @jax.checkpoint
    def kv_step(carry, inputs):
        o, m, l, qi, q_block = carry
        kj, k_block, v_block = inputs
        s = (
            jnp.einsum("bqhd,bkhd->bhqk", q_block, k_block, preferred_element_type=jnp.float32)
            * scale
        )  # (B, H, bq, bk) fp32
        if causal:
            q_pos = qi * bq + q_ids  # (bq,)
            k_pos = kj * bk + k_ids  # (bk,)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # (B, H, bq)
        # exp(-inf - -inf) guard: rows of a fully-masked block keep m = -inf
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(jnp.isfinite(m) | jnp.isfinite(m_new), alpha, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_block.dtype), v_block,
            preferred_element_type=jnp.float32,
        )
        o = o * alpha.transpose(0, 2, 1)[..., None] + pv
        return (o, m_new, l, qi, q_block), None

    def q_block_fn(qi, q_block):
        o0 = jnp.zeros((b, bq, h, dh), jnp.float32)
        m0 = jnp.full((b, h, bq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, bq), jnp.float32)
        (o, m, l, _, _), _ = jax.lax.scan(
            kv_step, (o0, m0, l0, qi, q_block), (jnp.arange(nk), kb.swapaxes(0, 1), vb.swapaxes(0, 1))
        )
        return o / l.transpose(0, 2, 1)[..., None]

    out = jax.lax.map(lambda args: q_block_fn(*args), (jnp.arange(nq), qb.swapaxes(0, 1)))
    # out: (nq, B, bq, H, Dh) -> (B, T, H, Dh)
    return out.transpose(1, 0, 2, 3, 4).reshape(b, t, h, dh).astype(q.dtype)


@functools.lru_cache(maxsize=1)
def _pallas_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
) -> jax.Array:
    """Memory-efficient attention; Pallas kernel on TPU, blockwise JAX elsewhere.

    q: (B, T, H, D); k, v: (B, T, G, D) with G | H. The Pallas kernel handles
    GQA natively (query groups index shared KV blocks); the blockwise
    fallback expands K/V — correctness-only, it runs on CPU/test paths.
    """
    gqa = k.shape[2] != q.shape[2]
    if gqa and q.shape[2] % k.shape[2] != 0:
        # Same fail-fast the Pallas path gives; without it the CPU fallback
        # dies in an unrelated reshape.
        raise ValueError(f"kv heads ({k.shape[2]}) must divide query heads ({q.shape[2]})")
    if _pallas_available():
        try:
            from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention

            return pallas_flash_attention(
                q, k, v, causal=causal, block_q=block_q, block_kv=block_kv
            )
        except ImportError:
            pass  # kernel module not built yet; blockwise path is correct
    if gqa:
        n_rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    return blockwise_attention(q, k, v, causal=causal, block_q=block_q, block_kv=block_kv)
