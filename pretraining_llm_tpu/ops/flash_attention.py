"""Flash attention: blockwise online-softmax, O(T) memory.

Eliminates the reference's fully-materialized (B, H, T, T) score tensor
(`/root/reference/src/models/attention.py:51-57`) — the exact memory wall that
caps its context at 512. Two tiers:

  - `blockwise_attention` (this module, always available): FlashAttention-2
    schedule expressed in pure JAX — `lax.scan` over KV blocks with running
    (max, sum) renormalization, `jax.checkpoint` on the inner step so autodiff
    recomputes score blocks instead of storing them. XLA maps the per-block
    einsums onto the MXU; this is the correctness baseline and the fallback on
    CPU.
  - `ops.pallas_flash` (TPU): the hand-tiled Pallas kernel with fused masking
    and VMEM-resident blocks, selected automatically on TPU backends.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.utils import jax_compat


def _pick_block(t: int, requested: int, default: int) -> int:
    if requested > 0:
        block = requested
    else:
        block = default
    block = min(block, t)
    while t % block != 0:  # shapes in this framework are powers of two; be safe
        block //= 2
        if block == 0:
            return t
    return max(block, 1)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
    q_offset: Any = 0,
    k_offset: int = 0,
    segments: Any = None,
    window: int = 0,
) -> jax.Array:
    """Online-softmax attention. q: (B, Tq, H, Dh), k/v: (B, Tk, G, Dh)
    with G | H -> (B, Tq, H, Dh). Tq and Tk may differ.

    GQA-NATIVE: each group of H/G query heads attends its shared KV head
    through grouped einsums — K/V are never expanded to H heads (the
    cache-bandwidth win GQA exists for). G == H reduces to plain MHA.

    ``q_offset`` (python int or traced scalar) places the query block at
    positions [q_offset, q_offset+Tq) against keys at [0, Tk) for the
    causal mask — the rectangular form chunked prefill needs (each chunk
    attends the already-written cache prefix; keys above the frontier are
    causally excluded, so no explicit length mask is required).

    ``segments`` (B, T) int32 document ids (self-attention only, Tq == Tk):
    queries attend only keys of their own document — packed-sequence
    training without cross-document attention.

    ``window`` > 0: sliding-window attention (each query sees the last
    `window` positions only). ``k_offset`` places the KEYS at positions
    [k_offset, k_offset+Tk) — chunked windowed prefill passes a trimmed
    cache view whose below-window prefix was sliced off.
    """
    b, tq_len, h, dh = q.shape
    tk_len, g = k.shape[1], k.shape[2]
    r = h // g  # query heads per KV group
    bq = _pick_block(tq_len, block_q, 512)
    bk = _pick_block(tk_len, block_kv, 512)
    nq, nk = tq_len // bq, tk_len // bk
    scale = 1.0 / (dh**0.5)

    qb = q.reshape(b, nq, bq, g, r, dh)
    kb = k.reshape(b, nk, bk, g, dh)
    vb = v.reshape(b, nk, bk, g, dh)
    has_seg = segments is not None
    if has_seg:
        if tq_len != tk_len:
            raise ValueError("segments requires self-attention (Tq == Tk)")
        seg32 = segments.astype(jnp.int32)
        sqb = seg32.reshape(b, nq, bq)
        skb = seg32.reshape(b, nk, bk)

    q_ids = jnp.arange(bq)
    k_ids = jnp.arange(bk)

    @jax.checkpoint
    def kv_step(carry, inputs):
        o, m, l, qi, q_block, sq_block = carry
        kj, k_block, v_block, sk_block = inputs
        s = (
            jnp.einsum(
                "bqgrd,bkgd->bgrqk", q_block, k_block,
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (B, G, R, bq, bk) fp32
        if causal or window:
            q_pos = q_offset + qi * bq + q_ids  # (bq,)
            k_pos = k_offset + kj * bk + k_ids  # (bk,)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None, None], s, -jnp.inf)
        if window:
            w_ok = (q_pos[:, None] - k_pos[None, :]) < window
            s = jnp.where(w_ok[None, None, None], s, -jnp.inf)
        if has_seg:
            # True -inf: the existing isfinite() guards zero p/alpha for
            # fully cross-document blocks.
            seg_ok = sq_block[:, :, None] == sk_block[:, None, :]  # (B,bq,bk)
            s = jnp.where(seg_ok[:, None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # (B, G, R, bq)
        # exp(-inf - -inf) guard: rows of a fully-masked block keep m = -inf
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(jnp.isfinite(m) | jnp.isfinite(m_new), alpha, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bgrqk,bkgd->bqgrd", p.astype(v_block.dtype), v_block,
            preferred_element_type=jnp.float32,
        )
        o = o * alpha.transpose(0, 3, 1, 2)[..., None] + pv
        return (o, m_new, l, qi, q_block, sq_block), None

    def q_block_fn(qi, q_block, sq_block):
        o0 = jnp.zeros((b, bq, g, r, dh), jnp.float32)
        m0 = jnp.full((b, g, r, bq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, g, r, bq), jnp.float32)
        sk_scan = skb.swapaxes(0, 1) if has_seg else jnp.zeros((nk, b, 1), jnp.int32)
        (o, m, l, _, _, _), _ = jax.lax.scan(
            kv_step, (o0, m0, l0, qi, q_block, sq_block),
            (jnp.arange(nk), kb.swapaxes(0, 1), vb.swapaxes(0, 1), sk_scan)
        )
        return o / l.transpose(0, 3, 1, 2)[..., None]

    sq_map = sqb.swapaxes(0, 1) if has_seg else jnp.zeros((nq, b, 1), jnp.int32)
    out = jax.lax.map(
        lambda args: q_block_fn(*args), (jnp.arange(nq), qb.swapaxes(0, 1), sq_map)
    )
    # out: (nq, B, bq, G, R, Dh) -> (B, Tq, H, Dh)
    return out.transpose(1, 0, 2, 3, 4, 5).reshape(b, tq_len, h, dh).astype(q.dtype)


@functools.lru_cache(maxsize=1)
def _pallas_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def shard_mapped_kernel(kernel, q, k, v, mesh, *, batch_axes=("data", "fsdp"),
                        segments=None):
    """Run an attention kernel per-shard under a batch/head-sharded mesh.

    GSPMD cannot partition a pallas_call — traced directly on sharded
    operands it would REPLICATE the kernel, all-gathering the global batch
    onto every device. This wraps it in a shard_map over the batch axes
    (+ 'tensor' on the head dim when the head counts divide).

    Returns None when the layout isn't expressible per-shard (head counts
    not divisible by the tensor axis; seq/pipe-sharded activations belong to
    the ring/ulysses/pipeline paths) — caller falls back.
    """
    from jax.sharding import PartitionSpec as P

    if any(mesh.shape.get(ax, 1) > 1 for ax in ("seq", "pipe")):
        return None
    batch_shards = 1
    for ax in batch_axes:
        batch_shards *= mesh.shape.get(ax, 1)
    if q.shape[0] % batch_shards != 0:
        return None  # small/partial batch: let the caller's fallback handle it
    h, g = q.shape[2], k.shape[2]
    tp = mesh.shape.get("tensor", 1)
    if tp > 1 and (h % tp != 0 or g % tp != 0):
        return None
    head_ax = "tensor" if tp > 1 else None
    spec = P(batch_axes, None, head_ax, None)
    if segments is not None:
        seg_spec = P(batch_axes, None)
        return jax_compat.shard_map(
            lambda q_, k_, v_, s_: kernel(q_, k_, v_, segments=s_),
            mesh=mesh, in_specs=(spec, spec, spec, seg_spec), out_specs=spec,
            check_vma=False,
        )(q, k, v, segments)
    return jax_compat.shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
    segments: Any = None,
    window: int = 0,
    heads_major: bool = False,
) -> jax.Array:
    """Memory-efficient attention; Pallas kernel on TPU, blockwise JAX elsewhere.

    q: (B, T, H, D); k, v: (B, T, G, D) with G | H. The Pallas kernel handles
    GQA natively (query groups index shared KV blocks); the blockwise
    fallback is GQA-native too (grouped einsums, K/V never expanded).

    ``heads_major=True``: operands arrive (B, H|G, T, D) and the result is
    returned (B, H, T, D) — the kernel-native layout, letting the training
    path skip the per-layer transpose copies (see pallas_flash_attention).
    The single-device and fully-manual Pallas tiers consume it natively;
    the shard_map and blockwise tiers transpose at entry/exit (mesh/CPU
    paths — correctness over the last few percent there).

    ``segments`` (B, T) int32 document ids: packed-sequence training —
    attention (and its VJP) never crosses a document boundary. Threaded
    into whichever tier serves the call.
    """
    head_ax = 1 if heads_major else 2
    if q.shape[head_ax] % k.shape[head_ax] != 0:
        # Same fail-fast the Pallas path gives; without it the CPU fallback
        # dies in an unrelated reshape.
        raise ValueError(
            f"kv heads ({k.shape[head_ax]}) must divide query heads "
            f"({q.shape[head_ax]})"
        )

    def _to_btHD(x):
        return x.transpose(0, 2, 1, 3) if heads_major else x

    def _from_btHD(o):
        return o.transpose(0, 2, 1, 3) if heads_major else o

    if _pallas_available():
        try:
            from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention
            from pretraining_llm_tpu.parallel.sharding import current_mesh

            kernel = functools.partial(
                pallas_flash_attention, causal=causal, block_q=block_q,
                block_kv=block_kv, window=window,
            )
            mesh = current_mesh()
            if mesh is None or all(s == 1 for s in mesh.shape.values()):
                return kernel(q, k, v, segments=segments,
                              heads_major=heads_major)
            # Manual-region classification (ADVICE r2): the direct kernel
            # call is only correct when EVERY nontrivial mesh axis is manual
            # (ulysses' all-to-all body — operands are per-device local
            # arrays). In a PARTIAL-manual region (the pipeline: manual over
            # 'pipe' only) activations are still auto-sharded over
            # data/fsdp, so a direct pallas_call would be replicated by
            # GSPMD, all-gathering the global batch — and a nested shard_map
            # over the auto axes is not expressible either; use the
            # blockwise fallback there (GSPMD partitions plain JAX ops).
            abstract_mesh = jax_compat.get_abstract_mesh()
            manual_axes = jax_compat.manual_axis_names(abstract_mesh)
            nontrivial = {name for name, size in mesh.shape.items() if size > 1}
            if nontrivial <= manual_axes:
                return kernel(q, k, v, segments=segments,
                              heads_major=heads_major)  # fully manual region
            if not manual_axes:
                out = shard_mapped_kernel(
                    kernel, _to_btHD(q), _to_btHD(k), _to_btHD(v), mesh,
                    segments=segments,
                )
                if out is not None:
                    return _from_btHD(out)
            # Partial-manual region, or unexpressible per-shard layout
            # (seq/pipe-sharded activations, indivisible batch or heads):
            # blockwise fallback below. Loud (VERDICT r2 #9) — the user
            # configured the Pallas kernel and is getting the slower JAX
            # path; fires once per trace (warnings dedupe).
            import warnings

            why = (
                "inside a partial-manual shard_map region (e.g. the "
                "pipeline's pipe-only region)"
                if manual_axes
                else "the mesh/shape layout is not expressible per-shard "
                "(seq/pipe-sharded activations, or batch/head counts not "
                "divisible by the mesh axes)"
            )
            warnings.warn(
                f"flash attention falling back to blockwise JAX (no Pallas "
                f"kernel): {why}.",
                stacklevel=2,
            )
        except ImportError:
            pass  # kernel module not built yet; blockwise path is correct
    # blockwise_attention is GQA-native (grouped einsums) — no K/V expansion.
    return _from_btHD(blockwise_attention(
        _to_btHD(q), _to_btHD(k), _to_btHD(v), causal=causal,
        block_q=block_q, block_kv=block_kv, segments=segments, window=window,
    ))
