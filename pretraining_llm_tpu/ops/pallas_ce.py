"""Pallas fused cross-entropy head: hidden @ W -> per-token loss, no logits.

STATUS: EXPERIMENT, not a product path (VERDICT r4 weak #4). Interpret-mode
correct and fully tested, but on the axon v5e backend this kernel class
hung the chip three times across two remat configs (multi-hour backend
wedges — the Mosaic-level cause is not isolated; the grid/accumulator
pattern matches the proven flash kernels, so the trigger is suspected in
the V-innermost revisiting schedule's DMA pattern at 50304-wide vocab
tiles), and everywhere it DID complete it measured slower than the
chunked/dense XLA heads (29.9-31.5% vs 40+% MFU at 124M — the CE-scatter
fix moved the bottleneck out of the head entirely). It is excluded from
all capture campaigns as a wedge class (scripts/tpu_capture.py risky-
stage policy). The product CE heads are models.transformer's chunked and
dense implementations.

The CE head is the single largest matmul in GPT-2-class models (~24% of
step FLOPs at 124M: D=768 x V=50304) and the naive form is HBM-bound — the
(S, V) fp32 logits round-trip to HBM between the matmul, the logsumexp and
the backward. The chunked head (models.transformer._chunked_ce) bounds the
materialization to 1/n_chunks; this kernel eliminates it:

  - forward: grid (S tiles x V tiles), V innermost. Each step computes one
    logits tile `h_tile @ W_tile` in VMEM (bf16 MXU matmul, fp32
    accumulation) and folds it into running (max, sumexp) stats plus the
    label's logit — FlashAttention-style online softmax over the vocab dim.
    Per-token loss = lse - label_logit. Nothing of size V ever leaves VMEM.
  - backward: two kernels (same split as the flash dQ/dKV pair, and for the
    same reason — each gradient accumulates over a DIFFERENT grid dim, and
    scratch accumulators are only safe across the innermost one). Both
    recompute their logits tiles from (hidden, W), form
    p~ = g * (softmax - onehot), and contract: dH = p~ @ W^T (vocab dim
    inner), dW = H^T @ p~ (token dim inner).
  - custom VJP residuals: (hidden, W, labels, lse) — O(S + D*V), no logits.

Reference cost being removed: the reference computes full (B*T, V) logits
and hands them to F.cross_entropy (/root/reference/src/models/transformer.py:73-77).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Renamed upstream (TPUCompilerParams -> CompilerParams); accept either so the
# kernel builds on 0.4.x and current JAX alike.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

NEG_INF = -1e30

# Same halve-until-divides tiling rule as the flash kernels — one source.
from pretraining_llm_tpu.ops.flash_attention import _pick_block as _pick


def _tiles(s: int, v: int, block_s: int, block_v: int):
    bs = _pick(s, block_s, 256)
    v_pad = -(-v // 128) * 128
    bv = _pick(v_pad, block_v, 1024)
    return bs, bv, v_pad, s // bs, v_pad // bv


def _logits_tile(h, w, j, bv, v):
    """(bs, bv) fp32 logits tile with the padded vocab tail masked."""
    logits = jax.lax.dot_general(
        h, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    v_pos = j * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    return jnp.where(v_pos < v, logits, NEG_INF), v_pos


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    h_ref, w_ref, label_ref, loss_ref, lse_ref, m_ref, l_ref, gold_ref, *, bv, nv, v
):
    j = pl.program_id(1)
    logits, v_pos = _logits_tile(h_ref[...], w_ref[...], j, bv, v)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        gold_ref[...] = jnp.zeros_like(gold_ref)

    m_prev = m_ref[...]  # (bs, 1)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(
        jnp.exp(logits - m_new), axis=1, keepdims=True
    )
    m_ref[...] = m_new
    hit = (v_pos == label_ref[...]).astype(jnp.float32)  # one-hot in-tile
    gold_ref[...] = gold_ref[...] + jnp.sum(logits * hit, axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _finish():
        lse = m_ref[...] + jnp.log(l_ref[...])
        lse_ref[...] = lse
        loss_ref[...] = lse - gold_ref[...]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _scaled_p(h_ref, w_ref, label_ref, lse_ref, g_ref, j, bv, v):
    """p~ = g * (softmax - onehot) for one tile, fp32 (bs, bv)."""
    logits, v_pos = _logits_tile(h_ref[...], w_ref[...], j, bv, v)
    p = jnp.exp(logits - lse_ref[...])
    p = p - (v_pos == label_ref[...]).astype(jnp.float32)
    return p * g_ref[...]


def _bwd_dh_kernel(
    h_ref, w_ref, label_ref, lse_ref, g_ref, dh_ref, acc_ref, *, bv, nv, v
):
    """grid (S, V), V inner: dH tile accumulates across the vocab tiles."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p16 = _scaled_p(h_ref, w_ref, label_ref, lse_ref, g_ref, j, bv, v).astype(
        w_ref.dtype
    )
    # This contraction runs OVER the vocab tile — zero W's padded tail
    # columns explicitly (p is 0 there, but 0 * uninitialized can be NaN).
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, w_ref.shape, 1)
    w = jnp.where(col < v, w_ref[...], jnp.zeros_like(w_ref))
    acc_ref[...] += jax.lax.dot_general(
        p16, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(j == nv - 1)
    def _finish():
        dh_ref[...] = acc_ref[...]


def _bwd_dw_kernel(
    h_ref, w_ref, label_ref, lse_ref, g_ref, dw_ref, acc_ref, *, bv, ns, v
):
    """grid (V, S), S inner: dW tile accumulates across the token tiles."""
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p16 = _scaled_p(h_ref, w_ref, label_ref, lse_ref, g_ref, j, bv, v).astype(
        h_ref.dtype
    )
    acc_ref[...] += jax.lax.dot_general(
        h_ref[...], p16, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(i == ns - 1)
    def _finish():
        dw_ref[...] = acc_ref[...]


# ---------------------------------------------------------------------------
# custom VJP plumbing
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ce(h, w, labels, block_s, block_v, interpret):
    loss, _ = _ce_fwd(h, w, labels, block_s, block_v, interpret)
    return loss


def _ce_fwd(h, w, labels, block_s, block_v, interpret):
    s, d = h.shape
    v = w.shape[1]
    bs, bv, v_pad, ns, nv = _tiles(s, v, block_s, block_v)
    labels2 = labels.astype(jnp.int32).reshape(s, 1)

    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, nv=nv, v=v),
        grid=(ns, nv),
        in_specs=[
            pl.BlockSpec((bs, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bs, 1), jnp.float32),
            pltpu.VMEM((bs, 1), jnp.float32),
            pltpu.VMEM((bs, 1), jnp.float32),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, 1), jnp.float32),
            jax.ShapeDtypeStruct((s, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(h, w, labels2)
    return loss[:, 0], (h, w, labels2, lse)


def _ce_bwd(block_s, block_v, interpret, residuals, g):
    h, w, labels2, lse = residuals
    g2 = g.reshape(-1, 1).astype(jnp.float32)
    s, d = h.shape
    v = w.shape[1]
    bs, bv, v_pad, ns, nv = _tiles(s, v, block_s, block_v)

    in_specs_sv = [
        pl.BlockSpec((bs, d), lambda i, j: (i, 0)),
        pl.BlockSpec((d, bv), lambda i, j: (0, j)),
        pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
    ]
    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, bv=bv, nv=nv, v=v),
        grid=(ns, nv),
        in_specs=in_specs_sv,
        out_specs=pl.BlockSpec((bs, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((bs, d), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((s, d), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(h, w, labels2, lse, g2)

    in_specs_vs = [
        pl.BlockSpec((bs, d), lambda j, i: (i, 0)),
        pl.BlockSpec((d, bv), lambda j, i: (0, j)),
        pl.BlockSpec((bs, 1), lambda j, i: (i, 0)),
        pl.BlockSpec((bs, 1), lambda j, i: (i, 0)),
        pl.BlockSpec((bs, 1), lambda j, i: (i, 0)),
    ]
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, bv=bv, ns=ns, v=v),
        grid=(nv, ns),
        in_specs=in_specs_vs,
        out_specs=pl.BlockSpec((d, bv), lambda j, i: (0, j)),
        scratch_shapes=[pltpu.VMEM((d, bv), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((d, v_pad), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(h, w, labels2, lse, g2)
    return dh.astype(h.dtype), dw[:, :v].astype(w.dtype), None


_ce.defvjp(_ce_fwd, _ce_bwd)


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def fused_cross_entropy(
    hidden: jax.Array,
    w: jax.Array,
    labels: jax.Array,
    *,
    bias: Optional[jax.Array] = None,
    block_s: int = 0,
    block_v: int = 0,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-token CE loss of a tied/untied LM head without materializing logits.

    hidden: (S, D); w: (D, V); labels: (S,) int. Returns (S,) fp32 losses
    (= lse - label_logit). ``bias`` is unsupported (the kernel targets the
    framework's default biasless/tied head; the chunked-CE fallback handles
    bias) — passing one raises.

    `interpret=None` auto-selects: compiled on TPU, interpreter elsewhere
    (slow — tests only).
    """
    if bias is not None:
        raise ValueError("fused CE kernel does not support an lm_head bias")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _ce(hidden, w, labels, block_s, block_v, interpret)
