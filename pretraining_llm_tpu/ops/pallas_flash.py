"""Hand-tiled Pallas TPU flash attention (FlashAttention-2 schedule).

Forward and backward kernels with a custom VJP. Design (vs the reference's
fully-materialized (B,H,T,T) scores, /root/reference/src/models/attention.py:51-57):

  - Grid (batch, head, q_blocks, kv_blocks); the kv axis is innermost so the
    fp32 accumulator/stats live in VMEM scratch across kv steps and the output
    block is written once on the last step (standard TPU revisiting pattern).
  - Online softmax: running row-max m and row-sum l; score blocks (bq, bk)
    exist only in VMEM — O(T) memory in sequence length.
  - Causal masking by index arithmetic (broadcasted_iota); fully-masked kv
    blocks skip their matmuls entirely via pl.when (upper-triangle blocks cost
    no FLOPs).
  - QK^T and PV ride the MXU with fp32 accumulation (preferred_element_type);
    inputs stay bf16.
  - **GQA native**: k/v may carry G = n_kv_heads < H heads. The grid's head
    axis indexes QUERY heads; the k/v BlockSpec index maps divide down to the
    shared KV head (h // n_rep) so no repeated K/V ever exists in HBM — the
    bandwidth saving that motivates GQA. The dK/dV kernel grids over KV heads
    and accumulates across the group's n_rep query heads in VMEM scratch.
  - Backward = two kernels (FA2): dQ gridded over q blocks, dK/dV gridded over
    kv blocks, both re-building P from the saved logsumexp; D = rowsum(dO*O)
    is precomputed in plain XLA.

All kernels run under interpret mode on CPU for unit testing (tests compare
against the naive einsum path).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # avoid actual -inf inside kernels (exp/max edge cases)


def _heads_first(x: jax.Array) -> jax.Array:
    """(B, T, H, D) -> (B*H, T, D)"""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _heads_last(x: jax.Array, b: int, h: int) -> jax.Array:
    """(B*H, T, D) -> (B, T, H, D)"""
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _block_sizes(t: int, block_q: int, block_kv: int) -> Tuple[int, int]:
    # Auto default 1024: measured fastest on v5e at T=1024..8192 (s-block of
    # (1024, 1024) f32 = 4 MB VMEM); smaller blocks pay grid/stats overhead.
    bq = min(block_q or 1024, t)
    bk = min(block_kv or 1024, t)
    while t % bq:
        bq //= 2
    while t % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _run_ok(i0, j0, bq, bk, causal, window):
    """Block-skip predicate for a (bq, bk) score block at offsets (i0, j0):
    False only when NO (q, k) pair in the block can be valid. Shares a home
    with _mask_ok for the same reason — skip semantics must never diverge
    between the forward and backward kernels."""
    run = jnp.logical_or(not causal, j0 <= i0 + bq - 1)
    if window:
        run = jnp.logical_and(run, j0 + bk - 1 >= i0 - (window - 1))
    return run


def _mask_ok(i0, j0, bq, bk, causal, window, sq_ref, sk_ref):
    """Combined causal/window/segment validity mask for a (bq, bk) score
    block at absolute offsets (i0, j0), or None when nothing masks. ONE
    definition shared by the forward and all three backward kernels — a
    mask tweak must not silently diverge forward from backward."""
    ok = None
    if causal or window:
        q_pos = i0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = j0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if causal:
        ok = q_pos >= k_pos
    if window:
        w_ok = q_pos - k_pos < window
        ok = w_ok if ok is None else jnp.logical_and(ok, w_ok)
    if sq_ref is not None:
        seg_ok = sq_ref[0] == sk_ref[0]
        ok = seg_ok if ok is None else jnp.logical_and(ok, seg_ok)
    return ok


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, bq, bk, nk, seg, window):
    # `seg` (static) threads document-segment refs: sq (bq, 1) / sk (1, bk)
    # int32 blocks riding the proven trailing-singleton stats layouts; a
    # query may only attend keys of its own document. seg=False traces the
    # exact op sequence the measured kernels compiled — the proven class.
    if seg:
        sq_ref, sk_ref, o_ref, lse_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, lse_ref, acc, m_scr, l_scr = rest
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Causal: kv block strictly after the q block -> nothing to do.
    # Sliding window additionally skips blocks entirely BELOW the window
    # (every key older than window for every query): O(T*W) compute.
    run = _run_ok(i * bq, j * bk, bq, bk, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # (bq, d)
        k = k_ref[0]  # (bk, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk)
        ok = _mask_ok(i * bq, j * bk, bq, bk, causal, window,
                      sq_ref if seg else None, sk_ref if seg else None)
        if ok is not None:
            s = jnp.where(ok, s, NEG_INF)
        m_prev = m_scr[:]  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (bq, bk) f32
        if seg or window:
            # NEG_INF is finite: a row whose EVERY seen entry is masked
            # keeps m == NEG_INF, making exp(s - m_new) == 1 for masked
            # entries (plain causal never runs such a block; window/seg
            # rows can — early blocks fully below the window, or fully
            # cross-document). Zero p by the combined mask itself, not by
            # exp underflow.
            p = jnp.where(ok, p, 0.0)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc[:] = acc[:] * alpha + pv

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(safe_l)  # (bq, 1)


def _seg_views(segments: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(b, t) int32 document ids -> q-side (b, t, 1) and k-side (b, 1, t)
    views, each blockable with the proven trailing-singleton / single-
    sublane layouts (no in-kernel transpose)."""
    s32 = segments.astype(jnp.int32)
    return s32[:, :, None], s32[:, None, :]


def _fwd(
    q: jax.Array, k: jax.Array, v: jax.Array, h: int, g: int, *,
    causal: bool, block_q: int, block_kv: int, interpret: bool,
    segments: Optional[jax.Array] = None, window: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    bh, t, d = q.shape
    b = bh // h
    n_rep = h // g
    bq, bk = _block_sizes(t, block_q, block_kv)
    nq, nk = t // bq, t // bk
    scale = 1.0 / (d**0.5)

    seg = segments is not None
    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, bq=bq, bk=bk, nk=nk, seg=seg,
        window=window,
    )
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda bb, hh, i, j: (bb * h + hh, i, 0)),
        # GQA: the group's query heads share one KV head — index division,
        # never a materialized repeat.
        pl.BlockSpec((1, bk, d), lambda bb, hh, i, j: (bb * g + hh // n_rep, j, 0)),
        pl.BlockSpec((1, bk, d), lambda bb, hh, i, j: (bb * g + hh // n_rep, j, 0)),
    ]
    inputs = [q, k, v]
    if seg:
        sq3, sk3 = _seg_views(segments)
        in_specs += [
            pl.BlockSpec((1, bq, 1), lambda bb, hh, i, j: (bb, i, 0)),
            pl.BlockSpec((1, 1, bk), lambda bb, hh, i, j: (bb, 0, j)),
        ]
        inputs += [sq3, sk3]
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bb, hh, i, j: (bb * h + hh, i, 0)),
            # Stats ride in a trailing singleton lane dim: block (bq, 1) on
            # array (t, 1) satisfies Mosaic's (8, 128)-or-full-dim tiling rule
            # without the official kernel's 128-lane broadcast blowup.
            pl.BlockSpec((1, bq, 1), lambda bb, hh, i, j: (bb * h + hh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal, scale, bq, bk, nk, seg, window
):
    if seg:
        sq_ref, sk_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _run_ok(i * bq, j * bk, bq, bk, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        # All matmuls take bf16 inputs with fp32 accumulation (MXU-native);
        # only the elementwise dS math runs in fp32. Casting do/v up first
        # would silently demote dp to a multi-pass fp32 matmul.
        do = do_ref[0]
        lse = lse_ref[0]  # (bq, 1)
        delta = delta_ref[0]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        ok = _mask_ok(i * bq, j * bk, bq, bk, causal, window,
                      sq_ref if seg else None, sk_ref if seg else None)
        if ok is not None:
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk)
        if seg or window:
            # Explicit zero (not exp underflow): lse for a real row is
            # finite, but masked-s NEG_INF is finite too — exp stays ~0
            # there; the guard is for degenerate all-masked rows where
            # lse == NEG_INF would give exp(0) == 1 (see _fwd_kernel).
            p = jnp.where(ok, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal, scale, bq, bk, nq, n_inner, seg, window
):
    if seg:
        sq_ref, sk_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    j = pl.program_id(2)  # kv block (outer)
    ri = pl.program_id(3)  # inner: (q head within group) * nq + q block
    i = ri % nq

    @pl.when(ri == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _run_ok(i * bq, j * bk, bq, bk, causal, window)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        # bf16 matmul inputs, fp32 accumulation (see _bwd_dq_kernel).
        do = do_ref[0]
        lse = lse_ref[0]  # (bq, 1)
        delta = delta_ref[0]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        ok = _mask_ok(i * bq, j * bk, bq, bk, causal, window,
                      sq_ref if seg else None, sk_ref if seg else None)
        if ok is not None:
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk)
        if seg or window:
            p = jnp.where(ok, p, 0.0)  # see _bwd_dq_kernel
        # dV += P^T dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale  # (bq, bk)
        # dK += dS^T Q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ri == n_inner - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal, scale, n_rep, seg, window
):
    if seg:
        sq_ref, sk_ref, dq_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dq_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    """Single-block backward (t <= one block): dQ, dK, dV in ONE pass.

    The two-kernel FA2 split exists because dQ accumulates over kv blocks
    while dK/dV accumulate over q blocks — with one block each there is
    nothing to accumulate across, so S and P are computed once (5 matmuls vs
    the split's 7) and q/k/v/do are read from HBM once instead of twice.
    GQA: grid is (batch, kv_head, n_rep); dk/dv accumulate the group's query
    heads in scratch across the innermost axis.
    """
    r = pl.program_id(2)  # query head within the kv group
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    # bf16 matmul inputs, fp32 accumulation (see _bwd_dq_kernel).
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    tq, dd = q.shape
    tk = k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    ok = _mask_ok(0, 0, tq, tk, causal, window,
                  sq_ref if seg else None, sk_ref if seg else None)
    if ok is not None:
        s = jnp.where(ok, s, NEG_INF)
    p = jnp.exp(s - lse)
    if seg or window:
        p = jnp.where(ok, p, 0.0)  # see _bwd_dq_kernel
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta) * scale
    dq_ref[0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dq_ref.dtype)
    dv_part = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dk_part = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(r == 0)
    def _init():
        dk_acc[:] = dk_part
        dv_acc[:] = dv_part

    @pl.when(r != 0)
    def _accum():
        dk_acc[:] += dk_part
        dv_acc[:] += dv_part

    @pl.when(r == n_rep - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(
    h: int, g: int, causal: bool, block_q: int, block_kv: int, interpret: bool, residuals, grad,
    segments: Optional[jax.Array] = None, window: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    q, k, v, o, lse2 = residuals
    lse = lse2[..., None]
    do = grad
    bh, t, d = q.shape
    b = bh // h
    n_rep = h // g
    bq, bk = _block_sizes(t, block_q, block_kv)
    nq, nk = t // bq, t // bk
    scale = 1.0 / (d**0.5)

    seg = segments is not None
    seg_inputs: list = []
    if seg:
        sq3, sk3 = _seg_views(segments)
        seg_inputs = [sq3, sk3]

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True)  # (bh, t, 1)

    if nq == 1 and nk == 1:
        in_specs = [
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * h + hh * n_rep + r, 0, 0)),  # q
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * g + hh, 0, 0)),  # k
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * g + hh, 0, 0)),  # v
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * h + hh * n_rep + r, 0, 0)),  # do
                pl.BlockSpec((1, t, 1), lambda bb, hh, r: (bb * h + hh * n_rep + r, 0, 0)),  # lse
                pl.BlockSpec((1, t, 1), lambda bb, hh, r: (bb * h + hh * n_rep + r, 0, 0)),  # delta
        ]
        if seg:
            in_specs += [
                pl.BlockSpec((1, t, 1), lambda bb, hh, r: (bb, 0, 0)),  # seg q-side
                pl.BlockSpec((1, 1, t), lambda bb, hh, r: (bb, 0, 0)),  # seg k-side
            ]
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_fused_kernel, causal=causal, scale=scale, n_rep=n_rep,
                seg=seg, window=window,
            ),
            grid=(b, g, n_rep),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * h + hh * n_rep + r, 0, 0)),
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * g + hh, 0, 0)),
                pl.BlockSpec((1, t, d), lambda bb, hh, r: (bb * g + hh, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                jax.ShapeDtypeStruct((b * g, t, d), k.dtype),
                jax.ShapeDtypeStruct((b * g, t, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((t, d), jnp.float32),
                pltpu.VMEM((t, d), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, do, lse, delta, *seg_inputs)
        return dq, dk, dv

    dq_in_specs = [
            pl.BlockSpec((1, bq, d), lambda bb, hh, i, j: (bb * h + hh, i, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda bb, hh, i, j: (bb * g + hh // n_rep, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda bb, hh, i, j: (bb * g + hh // n_rep, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda bb, hh, i, j: (bb * h + hh, i, 0)),  # do
            pl.BlockSpec((1, bq, 1), lambda bb, hh, i, j: (bb * h + hh, i, 0)),  # lse
            pl.BlockSpec((1, bq, 1), lambda bb, hh, i, j: (bb * h + hh, i, 0)),  # delta
    ]
    if seg:
        dq_in_specs += [
            pl.BlockSpec((1, bq, 1), lambda bb, hh, i, j: (bb, i, 0)),  # seg q-side
            pl.BlockSpec((1, 1, bk), lambda bb, hh, i, j: (bb, 0, j)),  # seg k-side
        ]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, scale=scale, bq=bq, bk=bk, nk=nk,
            seg=seg, window=window,
        ),
        grid=(b, h, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda bb, hh, i, j: (bb * h + hh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_inputs)

    # dK/dV: grid over KV heads; the inner axis walks the group's n_rep query
    # heads x nq q-blocks, accumulating into one (bk, d) scratch per kv block.
    n_inner = n_rep * nq

    def q_row(bb, hh, j, ri):
        return bb * h + hh * n_rep + ri // nq

    dkv_in_specs = [
            pl.BlockSpec((1, bq, d), lambda bb, hh, j, ri: (q_row(bb, hh, j, ri), ri % nq, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda bb, hh, j, ri: (bb * g + hh, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda bb, hh, j, ri: (bb * g + hh, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda bb, hh, j, ri: (q_row(bb, hh, j, ri), ri % nq, 0)),  # do
            pl.BlockSpec((1, bq, 1), lambda bb, hh, j, ri: (q_row(bb, hh, j, ri), ri % nq, 0)),  # lse
            pl.BlockSpec((1, bq, 1), lambda bb, hh, j, ri: (q_row(bb, hh, j, ri), ri % nq, 0)),  # delta
    ]
    if seg:
        dkv_in_specs += [
            pl.BlockSpec((1, bq, 1), lambda bb, hh, j, ri: (bb, ri % nq, 0)),  # seg q-side
            pl.BlockSpec((1, 1, bk), lambda bb, hh, j, ri: (bb, 0, j)),  # seg k-side
        ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, scale=scale, bq=bq, bk=bk, nq=nq,
            n_inner=n_inner, seg=seg, window=window,
        ),
        grid=(b, g, nk, n_inner),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bb, hh, j, ri: (bb * g + hh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bb, hh, j, ri: (bb * g + hh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * g, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * g, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper (heads-first layout), public (B, T, H, D) entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, h, g, causal, block_q, block_kv, interpret, window):
    o, _ = _fwd(q, k, v, h, g, causal=causal, block_q=block_q, block_kv=block_kv,
                interpret=interpret, window=window)
    return o


def _flash_fwd(q, k, v, h, g, causal, block_q, block_kv, interpret, window):
    o, lse = _fwd(q, k, v, h, g, causal=causal, block_q=block_q, block_kv=block_kv,
                  interpret=interpret, window=window)
    # Remat tags: under the 'save_qkv_attn'/'save_big' policies the VJP
    # residuals themselves are saved, so the backward never re-runs this
    # kernel (plain 'save_attn' only tags the merged output downstream,
    # which cannot reconstruct lse — the fwd kernel reruns there).
    # lse is squeezed to 2-D for the residual: a trailing-singleton (bh, t, 1)
    # buffer saved across the layer scan provokes pathological XLA layout
    # handling (observed as a compile hang with these residuals saved).
    o_res = checkpoint_name(o, "attn_o_res")
    lse2 = checkpoint_name(lse[..., 0], "attn_lse")
    return o, (q, k, v, o_res, lse2)


def _flash_bwd(h, g, causal, block_q, block_kv, interpret, window, residuals, grad):
    return _bwd(h, g, causal, block_q, block_kv, interpret, residuals, grad,
                window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Segment-masked variant: identical kernels with the document-mask refs
# threaded (seg=True). A separate custom_vjp keeps the measured non-segment
# path's trace byte-identical. `segments` is an int32 primal whose
# cotangent space is float0 (non-differentiable by construction).
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_seg(q, k, v, segments, h, g, causal, block_q, block_kv, interpret, window):
    o, _ = _fwd(q, k, v, h, g, causal=causal, block_q=block_q,
                block_kv=block_kv, interpret=interpret, segments=segments,
                window=window)
    return o


def _flash_seg_fwd(q, k, v, segments, h, g, causal, block_q, block_kv, interpret, window):
    o, lse = _fwd(q, k, v, h, g, causal=causal, block_q=block_q,
                  block_kv=block_kv, interpret=interpret, segments=segments,
                  window=window)
    o_res = checkpoint_name(o, "attn_o_res")
    lse2 = checkpoint_name(lse[..., 0], "attn_lse")
    return o, (q, k, v, o_res, lse2, segments)


def _flash_seg_bwd(h, g, causal, block_q, block_kv, interpret, window, residuals, grad):
    *res, segments = residuals
    dq, dk, dv = _bwd(h, g, causal, block_q, block_kv, interpret, tuple(res),
                      grad, segments=segments, window=window)
    dseg = np.zeros(segments.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dseg


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
    interpret: Optional[bool] = None,
    segments: Optional[jax.Array] = None,
    window: int = 0,
    heads_major: bool = False,
) -> jax.Array:
    """Flash attention. q: (B, T, H, Dh); k, v: (B, T, G, Dh) with G | H
    (grouped-query attention — G < H never materializes repeated K/V).
    Returns (B, T, H, Dh).

    ``heads_major=True``: q is (B, H, T, Dh) and k/v (B, G, T, Dh), and the
    output comes back (B, H, T, Dh). The kernel's internal layout IS
    heads-major ((B*H, T, D) folds), so this entry makes the fold a free
    reshape instead of a transpose — callers that produce q/k/v heads-major
    straight from their projection einsum (the training flash path) shed
    the per-layer relayout copies the op-level profile showed around every
    custom call (~6% of the gpt2-124m step, 2026-08-01 capture). Same
    pallas_call either way — no new kernel-config class.

    ``segments`` (B, T) int32 document ids restricts attention to keys of
    the query's own document (packed-sequence training; composed with the
    causal mask inside the kernel — cross-document pairs never contribute
    to the online softmax or its VJP).

    ``window`` > 0 enables SLIDING-WINDOW attention (Mistral-style): each
    query attends only the last `window` positions. Blocks entirely below
    the window are skipped (pl.when), so compute is O(T*window).

    `interpret=None` auto-selects: compiled on TPU, interpreter elsewhere
    (slow — tests only).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if heads_major:
        b, h, t, d = q.shape
        g = k.shape[1]
    else:
        b, t, h, d = q.shape
        g = k.shape[2]
    if h % g != 0:
        raise ValueError(f"kv heads ({g}) must divide query heads ({h})")
    if heads_major:
        qf = q.reshape(b * h, t, d)
        kf = k.reshape(b * g, t, d)
        vf = v.reshape(b * g, t, d)
    else:
        qf, kf, vf = _heads_first(q), _heads_first(k), _heads_first(v)
    if segments is not None:
        if segments.shape != (b, t):
            raise ValueError(
                f"segments must be (batch, seq) = ({b}, {t}), got {segments.shape}"
            )
        of = _flash_seg(qf, kf, vf, segments.astype(jnp.int32), h, g, causal,
                        block_q, block_kv, interpret, int(window))
    else:
        of = _flash(qf, kf, vf, h, g, causal, block_q, block_kv, interpret,
                    int(window))
    if heads_major:
        return of.reshape(b, h, t, d)
    return _heads_last(of, b, h)
