"""Pallas TPU paged-attention decode kernel (gather-free block tables).

The XLA paged-decode path (models/transformer.py paged branch) assembles
each row's logical KV sequence with a `pool[tables]` gather before a
masked einsum — three full passes over the row's KV bytes per layer step
(read pool, write gathered copy, read it again in attention). This kernel
reads the pool blocks DIRECTLY: the block table is a scalar-prefetch
operand, and the K/V BlockSpec index maps use it to DMA exactly the
row's pages into VMEM — vLLM's PagedAttention memory model expressed as
Pallas index maps instead of CUDA pointer chasing (SURVEY §2.2; the
reference has no serving/paged path at all,
/root/reference/src/models/transformer.py:96-114).

Design:
  - Grid (batch, max_blocks), block axis innermost; fp32 accumulator and
    online-softmax stats (m, l) live in VMEM scratch across block steps,
    the output block written once on the last step — the same revisiting
    schedule as ops/pallas_flash.py.
  - Dead table entries (beyond a row's pages) are 0 = the reserved
    scratch block: consecutive identical block indices elide their DMA
    in the Pallas pipeline, so a row's dead tail costs one block fetch,
    and its compute is skipped entirely via pl.when.
  - GQA native: a static Python loop over the G KV heads computes each
    group's (n_rep, block_size) score panel from the SHARED (bs, Dh) key
    block — no repeated K/V in HBM or VMEM, matching the flash kernel's
    index-division discipline.
  - Forward only: decode never differentiates, so there is no VJP and
    no saved stats output.

Used by the model when ``cfg.paged_attention_impl == "kernel"`` (int8
pools keep the gather path — quantized blocks need their scale pages
dequantized first, which the gather already fuses).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite: exp/max edge cases (same constant as pallas_flash)


def _paged_kernel(
    tbl_ref,  # (B, nb) int32 scalar-prefetch (SMEM)
    seq_ref,  # (B,) int32 scalar-prefetch (SMEM)
    q_ref,  # (1, H*T, Dh) — heads-major fold, query t at row h*T + t
    k_ref,  # (1, bs, G, Dh) — the page tbl[b, j]
    v_ref,  # (1, bs, G, Dh)
    o_ref,  # (1, H*T, Dh)
    acc,  # VMEM (H*T, Dh) f32
    m_scr,  # VMEM (H*T, 1) f32
    l_scr,  # VMEM (H*T, 1) f32
    *,
    bs: int,
    nb: int,
    g: int,
    n_rep: int,
    t: int,
    scale: float,
    window: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    seq = seq_ref[b]
    # Block liveness: any linear slot in [j*bs, j*bs+bs) visible to any
    # of the T queries — query t's frontier is seq + t (slot seq + t
    # holds its just-written token: inclusive, exactly the gather path's
    # per-query mask). Sliding window kills blocks entirely below the
    # OLDEST query's window.
    run = j * bs <= seq + (t - 1)
    if window:
        run = jnp.logical_and(run, j * bs + bs - 1 > seq - window)

    @pl.when(run)
    def _compute():
        rows = n_rep * t
        # Per-row frontier: row r within a group is query (r % t) of head
        # (r // t) — the heads-major fold keeps each GQA group's rows
        # contiguous so the static slice below works, at the price of
        # this tiny modulo iota.
        t_of_row = jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) % t
        lin = j * bs + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        valid = lin <= seq + t_of_row  # (n_rep*T, bs)
        if window:
            valid = jnp.logical_and(valid, lin > seq + t_of_row - window)
        q = q_ref[0]  # (H*T, Dh)
        k = k_ref[0]  # (bs, G, Dh)
        v = v_ref[0]
        for grp in range(g):
            sl = slice(grp * rows, (grp + 1) * rows)
            qg = q[sl]  # (n_rep*T, Dh)
            kg = k[:, grp]  # (bs, Dh)
            vg = v[:, grp]
            s = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (n_rep*T, bs)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_scr[sl]  # (n_rep*T, 1)
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            # A fully-masked row keeps m == NEG_INF -> exp(s-m)=1 for
            # masked entries; zero by the mask itself (flash kernel
            # discipline).
            p = jnp.where(valid, p, 0.0)
            l_scr[sl] = l_scr[sl] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_scr[sl] = m_new
            pv = jax.lax.dot_general(
                p.astype(vg.dtype), vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc[sl] = acc[sl] * alpha + pv

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("t", "window", "interpret"))
def _paged_call(q, k_pool, v_pool, block_tables, seq_lens, t, window,
                interpret):
    b, ht, d = q.shape  # ht == H * T, heads-major fold
    n_blocks, bs, g, _ = k_pool.shape
    nb = block_tables.shape[1]
    n_rep = ht // (g * t)
    kernel = functools.partial(
        _paged_kernel, bs=bs, nb=nb, g=g, n_rep=n_rep, t=t,
        scale=1.0 / (d**0.5), window=window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, ht, d), lambda bb, j, tbl, seq: (bb, 0, 0)),
            pl.BlockSpec(
                (1, bs, g, d),
                lambda bb, j, tbl, seq: (tbl[bb, j], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, bs, g, d),
                lambda bb, j, tbl, seq: (tbl[bb, j], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, ht, d), lambda bb, j, tbl, seq: (bb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((ht, d), jnp.float32),
            pltpu.VMEM((ht, 1), jnp.float32),
            pltpu.VMEM((ht, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, ht, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pool, v_pool)


def paged_decode_attention(
    q: jax.Array,  # (B, H, Dh) or (B, T, H, Dh) — T queries per row
    k_pool: jax.Array,  # (n_blocks, block_size, G, Dh)
    v_pool: jax.Array,
    block_tables: jax.Array,  # (B, max_blocks) int32, 0-padded tails
    seq_lens: jax.Array,  # (B,) int32 — slot seq_len + t holds query t's K/V
    *,
    window: int = 0,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Paged decode attention straight off the block pool.

    (B, H, Dh) is the serving decode step (one query per row); a 4-dim
    (B, T, H, Dh) q is the multi-token form (the speculative verify):
    query t sits at logical slot seq + t and sees slots <= seq + t —
    exactly the gather path's per-query frontier masks. Returns q's
    shape. Numerics match the gather path to accumulation-order
    tolerance; the HBM win is structural — the row's KV bytes are read
    ONCE, no gathered copy is ever written. `interpret=None`
    auto-selects: compiled on TPU, interpreter elsewhere (tests).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    multi = q.ndim == 4
    if multi:
        b, t, h, d = q.shape
        # Heads-major fold (H*T rows, query t of head h at row h*T + t):
        # keeps each GQA group's rows CONTIGUOUS so the kernel's static
        # group slices work; the transpose is B*T*H*D elements (tiny at
        # decode shapes).
        qf = q.transpose(0, 2, 1, 3).reshape(b, h * t, d)
    else:
        b, h, d = q.shape
        t = 1
        qf = q
    g = k_pool.shape[2]
    if h % g != 0:
        raise ValueError(f"kv heads ({g}) must divide query heads ({h})")
    if k_pool.shape != v_pool.shape:
        raise ValueError(f"k/v pool mismatch: {k_pool.shape} vs {v_pool.shape}")
    if block_tables.shape[0] != b or seq_lens.shape != (b,):
        raise ValueError(
            f"tables {block_tables.shape} / seq_lens {seq_lens.shape} do not "
            f"match batch {b}"
        )
    out = _paged_call(
        qf, k_pool, v_pool, block_tables, seq_lens, t, int(window),
        bool(interpret),
    )
    if multi:
        return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out
