"""Ragged paged-attention Pallas kernel: heterogeneous query counts per row.

`ops/pallas_paged.py` serves a batch where every row carries the SAME
number of query tokens (1 at decode, k+1 at the speculative verify).
Chunked prefill breaks that symmetry: one launch now mixes decode rows
(q_len 1..spec_depth) with prefill-chunk rows (q_len up to
`serving.prefill_chunk_tokens`), each row's queries starting at its own
committed offset `seq_lens[b]`. This kernel is the uniform kernel
generalized by ONE extra scalar-prefetch operand, `q_lens (B,)`:

  - Block liveness becomes per-row: page j is fetched/computed only when
    ``j*bs <= seq + (q_len - 1)`` — a decode row (q_len 1) stops at its
    frontier page while a chunk row in the same launch scans up to its
    chunk end. Dead table entries stay 0 (the reserved scratch block), so
    consecutive identical indices elide their DMA exactly as in
    pallas_paged.py.
  - The causal mask gains a query-validity term: query t of row b is
    real only when ``t < q_lens[b]``; pad queries (the static T bound
    minus the row's true count) are fully masked and finalize to zeros
    via the safe-l division — they cost VPU lanes, never HBM traffic
    beyond the row's live pages.
  - Online-softmax f32 accumulators in VMEM and the GQA-native shared
    K/V blocks are inherited unchanged (heads-major fold keeps each
    group's rows contiguous for the static group slices).

Two speed layers sit on top of the correctness core (both off by
default, both pinned against `ragged_gather_attention`):

  - **KV-split work partitioning** (``kv_splits > 1``, FA2 /
    flash-decoding style): a third grid dimension splits each row's page
    range into ``kv_splits`` partitions walked by parallel grid lanes.
    Each partition runs the same online softmax into its own VMEM
    scratch and flushes *unnormalized* partials — (acc, m, l) — to HBM;
    a small XLA combine then merges partitions with the standard
    log-sum-exp weights ``w_p = exp(m_p - max_p m_p)`` and finalizes.
    One 8k-context row no longer serializes a whole launch while decode
    rows idle. ``kv_splits=None`` auto-tunes the partition count from
    (max_pages, B) — enough lanes to fill the core grid, never slicing
    below ~2 pages per partition.
  - **AMLA rescaling** (``amla=True``): the online softmax runs in base
    2 with an *integer-quantized* running max (``m = ceil(max(s·log2e))``),
    so the per-page correction ``alpha = 2^(m_prev - m_new)`` has an
    integer exponent and the acc/l rescale becomes an ADD to the f32
    exponent field (bitcast + integer add, guarded against underflow and
    zero) instead of a vector multiply — MUL-by-ADD. On int8 pools the
    dequant scales are absorbed into the same restructure: K's scale
    multiplies the (rows, bs) score columns after the dot and V's scale
    multiplies the probability columns before the PV dot, so the
    quantized path stops paying a (bs, Dh) elementwise dequant multiply
    per page.

`ragged_gather_attention` below is the XLA fallback: the same
pool-gather + per-query masked softmax the model's gather branch runs,
extended with the q_len validity mask. CPU tier-1 tests pin the kernel
against it (interpret mode) across the split/AMLA grid, and
chunked-vs-monolithic bit-identity on CPU rides the model's gather
branch, which ignores q_lens entirely — pad-query outputs are computed
and discarded there, so real-query numerics are untouched by
construction.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite: exp/max edge cases (same constant as pallas_paged)
LOG2E = 1.4426950408889634  # log2(e): converts nat-domain scores to base 2


def _exp2_mul_add(x: jax.Array, k: jax.Array) -> jax.Array:
    """``x * 2^k`` for integer ``k <= 0`` as an exponent-field ADD.

    The AMLA trick: because the running max is integer-quantized, the
    online-softmax correction is a power of two, and multiplying an f32
    by 2^k is an integer add of ``k << 23`` to its bit pattern — one VPU
    integer add per element instead of a float multiply. Guards:
    ``exp_field == 0`` (zeros/subnormals stay zero) and
    ``exp_field + k <= 0`` (underflow flushes to zero instead of
    borrowing into the sign bit). ``k`` must already be clamped to
    ``[-126, 0]``.
    """
    bits = jax.lax.bitcast_convert_type(x, jnp.int32)
    exp_field = jnp.bitwise_and(jnp.right_shift(bits, 23), 0xFF)
    shifted = bits + jnp.left_shift(k, 23)
    ok = jnp.logical_and(exp_field > 0, exp_field + k > 0)
    return jnp.where(
        ok, jax.lax.bitcast_convert_type(shifted, jnp.float32), 0.0
    )


def _attend_page(
    j,  # dynamic page index within the row's table
    seq,
    qlen,
    q_ref,
    k_ref,
    v_ref,
    ks_ref,
    vs_ref,
    acc,
    m_scr,
    l_scr,
    *,
    bs: int,
    g: int,
    n_rep: int,
    t: int,
    scale: float,
    window: int,
    quantized: bool,
    amla: bool,
):
    """One page's online-softmax update, shared by both kernels.

    Classic form: nat-domain scores, float-multiply rescale, elementwise
    int8 dequant of the K/V page. AMLA form: base-2 scores with an
    integer-quantized running max, exponent-add rescale, and the int8
    scales absorbed as column multiplies on the score/probability
    matrices (never touching the (bs, Dh) page elementwise).
    """
    rows = n_rep * t
    # Row r within a group is query (r % t) of head (r // t); the
    # heads-major fold keeps each GQA group's rows contiguous so the
    # static slice below works.
    t_of_row = jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) % t
    lin = j * bs + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
    # Causal frontier per query PLUS query validity: queries at or
    # past the row's true count are padding (fully masked; finalize
    # zeros them via safe_l).
    valid = jnp.logical_and(lin <= seq + t_of_row, t_of_row < qlen)
    if window:
        valid = jnp.logical_and(valid, lin > seq + t_of_row - window)
    q = q_ref[0]  # (H*T, Dh)
    k = k_ref[0]  # (bs, G, Dh)
    v = v_ref[0]
    if quantized:
        ks = ks_ref[0]  # (bs, G, 1)
        vs = vs_ref[0]
    for grp in range(g):
        sl = slice(grp * rows, (grp + 1) * rows)
        qg = q[sl]  # (n_rep*T, Dh)
        kg = k[:, grp]  # (bs, Dh)
        vg = v[:, grp]
        if quantized and not amla:
            # Fused page dequant — the transformer._kv_dequantize
            # numerics (int8 * fp32-upcast scale / 127), done HERE so
            # only int8 bytes + scale pages cross HBM. The s/pv dots
            # below then run in f32 either way (bf16 accumulation
            # semantics are preserved by preferred_element_type=f32).
            kg = kg.astype(jnp.float32) * (
                ks[:, grp].astype(jnp.float32) * (1.0 / 127.0)
            )
            vg = vg.astype(jnp.float32) * (
                vs[:, grp].astype(jnp.float32) * (1.0 / 127.0)
            )
        if amla:
            s = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * (scale * LOG2E)  # (n_rep*T, bs), base-2 domain
            if quantized:
                # Absorbed K dequant: one (1, bs) column multiply on the
                # score matrix replaces the (bs, Dh) elementwise page
                # dequant (dot-then-scale == scale-then-dot).
                s = s * (
                    ks[:, grp].astype(jnp.float32).reshape(1, bs)
                    * (1.0 / 127.0)
                )
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_scr[sl]  # (n_rep*T, 1)
            # Integer-quantized running max: ceil makes m_prev - m_new an
            # integer <= 0, so alpha = 2^delta is a pure exponent add.
            m_new = jnp.maximum(
                m_prev, jnp.ceil(jnp.max(s, axis=-1, keepdims=True))
            )
            delta = jnp.clip(m_prev - m_new, -126.0, 0.0).astype(jnp.int32)
            p = jnp.exp2(s - m_new)
            p = jnp.where(valid, p, 0.0)
            l_scr[sl] = _exp2_mul_add(l_scr[sl], delta) + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_scr[sl] = m_new
            if quantized:
                # Absorbed V dequant: scale the probability columns
                # ((rows, bs)) instead of the V page ((bs, Dh)).
                pv_p = p * (
                    vs[:, grp].astype(jnp.float32).reshape(1, bs)
                    * (1.0 / 127.0)
                )
                vg = vg.astype(jnp.float32)
            else:
                pv_p = p
            pv = jax.lax.dot_general(
                pv_p.astype(vg.dtype), vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc[sl] = _exp2_mul_add(acc[sl], delta) + pv
        else:
            s = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (n_rep*T, bs)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_scr[sl]  # (n_rep*T, 1)
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            # Fully-masked rows keep m == NEG_INF -> exp(s-m)=1 on masked
            # entries; zeroed by the mask itself (flash kernel discipline).
            p = jnp.where(valid, p, 0.0)
            l_scr[sl] = l_scr[sl] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_scr[sl] = m_new
            pv = jax.lax.dot_general(
                p.astype(vg.dtype), vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc[sl] = acc[sl] * alpha + pv


def _ragged_kernel(
    tbl_ref,  # (B, nb) int32 scalar-prefetch (SMEM)
    seq_ref,  # (B,) int32 scalar-prefetch (SMEM)
    qlen_ref,  # (B,) int32 scalar-prefetch (SMEM) — true queries per row
    q_ref,  # (1, H*T, Dh) — heads-major fold, query t at row h*T + t
    k_ref,  # (1, bs, G, Dh) — the page tbl[b, j]
    v_ref,  # (1, bs, G, Dh)
    *rest,  # quantized: ks_ref, vs_ref (1, bs, G, 1) scale pages, then
    #         o_ref + the three VMEM scratch refs; exact: o_ref + scratch
    bs: int,
    nb: int,
    g: int,
    n_rep: int,
    t: int,
    scale: float,
    window: int,
    quantized: bool = False,
    amla: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, acc, m_scr, l_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    seq = seq_ref[b]
    qlen = qlen_ref[b]
    # Per-row block liveness: the LAST real query of this row sits at
    # slot seq + qlen - 1; pages past it are dead for this row even when
    # another row in the launch reaches further (the uniform kernel's
    # static (t-1) bound made every row pay the longest row's scan).
    # qlen == 0 rows (pure padding) run no block at all.
    run = j * bs <= seq + (qlen - 1)
    if window:
        run = jnp.logical_and(run, j * bs + bs - 1 > seq - window)

    @pl.when(run)
    def _compute():
        _attend_page(
            j, seq, qlen, q_ref, k_ref, v_ref, ks_ref, vs_ref,
            acc, m_scr, l_scr, bs=bs, g=g, n_rep=n_rep, t=t, scale=scale,
            window=window, quantized=quantized, amla=amla,
        )

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)


def _ragged_split_kernel(
    tbl_ref,
    seq_ref,
    qlen_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,  # quantized: ks_ref, vs_ref, then acc/m/l partial outputs +
    #         the three VMEM scratch refs; exact: partials + scratch
    bs: int,
    nb: int,
    nb_split: int,  # pages per partition (ceil(nb / kv_splits))
    g: int,
    n_rep: int,
    t: int,
    scale: float,
    window: int,
    quantized: bool = False,
    amla: bool = False,
):
    """KV-split variant: grid (B, kv_splits, nb_split); partition p of
    row b walks pages [p*nb_split, (p+1)*nb_split) ∩ [0, nb) and flushes
    UNNORMALIZED partials (acc, m, l) for the XLA log-sum-exp combine in
    `_ragged_call`. Same page math as `_ragged_kernel` via
    `_attend_page`."""
    if quantized:
        ks_ref, vs_ref, oa_ref, om_ref, ol_ref, acc, m_scr, l_scr = rest
    else:
        ks_ref = vs_ref = None
        oa_ref, om_ref, ol_ref, acc, m_scr, l_scr = rest
    b = pl.program_id(0)
    part = pl.program_id(1)
    jj = pl.program_id(2)
    j = part * nb_split + jj

    @pl.when(jj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    seq = seq_ref[b]
    qlen = qlen_ref[b]
    # Per-row liveness as in the single-pass kernel, PLUS the partition
    # bound: the last partition's tail blocks past nb are dead (their
    # index map clamps to the last table entry, so the repeated index
    # elides the DMA).
    run = jnp.logical_and(j < nb, j * bs <= seq + (qlen - 1))
    if window:
        run = jnp.logical_and(run, j * bs + bs - 1 > seq - window)

    @pl.when(run)
    def _compute():
        _attend_page(
            j, seq, qlen, q_ref, k_ref, v_ref, ks_ref, vs_ref,
            acc, m_scr, l_scr, bs=bs, g=g, n_rep=n_rep, t=t, scale=scale,
            window=window, quantized=quantized, amla=amla,
        )

    @pl.when(jj == nb_split - 1)
    def _flush():
        # Partials, not normalized output: empty partitions flush
        # (acc=0, m=NEG_INF, l=0) and drop out of the combine naturally.
        oa_ref[0, 0] = acc[:]
        om_ref[0, 0] = m_scr[:]
        ol_ref[0, 0] = l_scr[:]


def _auto_kv_splits(nb: int, b: int) -> int:
    """Partition-count heuristic (TPU ragged-paged-attention style).

    The (B, splits) product is the parallel grid surface; target ~8
    lanes (fills a TPU core's sequencer comfortably without shredding
    page locality), never slice a row below 2 pages per partition, and
    a batch that already fills the grid gets no splits at all.
    """
    target = max(1, 8 // max(b, 1))
    p = 1
    while p * 2 <= target and nb // (p * 2) >= 2:
        p *= 2
    return p


@functools.partial(
    jax.jit,
    static_argnames=("t", "window", "interpret", "kv_splits", "amla"),
)
def _ragged_call(q, k_pool, v_pool, block_tables, seq_lens, q_lens, t,
                 window, interpret, kv_splits=1, amla=False,
                 k_scale=None, v_scale=None):
    b, ht, d = q.shape  # ht == H * T, heads-major fold
    n_blocks, bs, g, _ = k_pool.shape
    nb = block_tables.shape[1]
    n_rep = ht // (g * t)
    quantized = k_scale is not None
    tables = block_tables.astype(jnp.int32)
    prefetch = (tables, seq_lens.astype(jnp.int32), q_lens.astype(jnp.int32))
    operands = [q, k_pool, v_pool]
    if quantized:
        operands += [k_scale, v_scale]
    scratch = [
        pltpu.VMEM((ht, d), jnp.float32),
        pltpu.VMEM((ht, 1), jnp.float32),
        pltpu.VMEM((ht, 1), jnp.float32),
    ]

    def _params(dims):
        # dimension_semantics lets Mosaic parallelize the batch/partition
        # dims; guarded so interpret mode (and older shims) keep working.
        if interpret:
            return None
        try:
            return pltpu.TPUCompilerParams(dimension_semantics=dims)
        except Exception:  # pragma: no cover - compiler-param shim gaps
            return None

    if kv_splits <= 1:
        kernel = functools.partial(
            _ragged_kernel, bs=bs, nb=nb, g=g, n_rep=n_rep, t=t,
            scale=1.0 / (d**0.5), window=window, quantized=quantized,
            amla=amla,
        )
        page_spec = pl.BlockSpec(
            (1, bs, g, d),
            lambda bb, j, tbl, seq, ql: (tbl[bb, j], 0, 0, 0),
        )
        in_specs = [
            pl.BlockSpec(
                (1, ht, d), lambda bb, j, tbl, seq, ql: (bb, 0, 0)
            ),
            page_spec,
            page_spec,
        ]
        if quantized:
            # Scale pages ride the SAME block-table index map as their
            # K/V pages — a dead table entry elides all four DMAs
            # together.
            scale_spec = pl.BlockSpec(
                (1, bs, g, 1),
                lambda bb, j, tbl, seq, ql: (tbl[bb, j], 0, 0, 0),
            )
            in_specs += [scale_spec, scale_spec]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, nb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, ht, d), lambda bb, j, tbl, seq, ql: (bb, 0, 0)
            ),
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, ht, d), q.dtype),
            compiler_params=_params(("parallel", "arbitrary")),
            interpret=interpret,
        )(*prefetch, *operands)

    # --- KV-split path: partials per partition + XLA combine ---------
    splits = kv_splits
    nb_split = -(-nb // splits)  # ceil: last partition may run short
    kernel = functools.partial(
        _ragged_split_kernel, bs=bs, nb=nb, nb_split=nb_split, g=g,
        n_rep=n_rep, t=t, scale=1.0 / (d**0.5), window=window,
        quantized=quantized, amla=amla,
    )

    def _page_idx(bb, part, jj, tbl, seq, ql):
        # Clamp the tail of the last partition back to a real table
        # entry: repeated indices elide the DMA, and liveness (j < nb)
        # keeps the compute off.
        j = part * nb_split + jj
        return (tbl[bb, jnp.minimum(j, nb - 1)], 0, 0, 0)

    page_spec = pl.BlockSpec((1, bs, g, d), _page_idx)
    in_specs = [
        pl.BlockSpec(
            (1, ht, d), lambda bb, part, jj, tbl, seq, ql: (bb, 0, 0)
        ),
        page_spec,
        page_spec,
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs, g, 1), _page_idx),
            pl.BlockSpec((1, bs, g, 1), _page_idx),
        ]
    part_map = lambda bb, part, jj, tbl, seq, ql: (bb, part, 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, splits, nb_split),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, ht, d), part_map),
            pl.BlockSpec((1, 1, ht, 1), part_map),
            pl.BlockSpec((1, 1, ht, 1), part_map),
        ],
        scratch_shapes=scratch,
    )
    acc_p, m_p, l_p = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, splits, ht, d), jnp.float32),
            jax.ShapeDtypeStruct((b, splits, ht, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, splits, ht, 1), jnp.float32),
        ],
        compiler_params=_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*prefetch, *operands)
    # Log-sum-exp combine across partitions. Empty partitions carry
    # (acc=0, m=NEG_INF, l=0): against a live sibling their weight
    # underflows to 0; an all-empty row keeps w=1 but l_tot=0, so the
    # safe-l division returns the pad-query zeros contract.
    m_tot = jnp.max(m_p, axis=1, keepdims=True)  # (b, 1, ht, 1)
    w = jnp.exp2(m_p - m_tot) if amla else jnp.exp(m_p - m_tot)
    l_tot = jnp.sum(l_p * w, axis=1)  # (b, ht, 1)
    acc_tot = jnp.sum(acc_p * w, axis=1)  # (b, ht, d)
    safe_l = jnp.where(l_tot == 0.0, 1.0, l_tot)
    return (acc_tot / safe_l).astype(q.dtype)


def ragged_paged_attention(
    q: jax.Array,  # (B, T, H, Dh) — T is the batch's MAX query count
    k_pool: jax.Array,  # (n_blocks, block_size, G, Dh)
    v_pool: jax.Array,
    block_tables: jax.Array,  # (B, max_blocks) int32, 0-padded tails
    seq_lens: jax.Array,  # (B,) int32 — row b's committed offset
    q_lens: jax.Array,  # (B,) int32 — row b's TRUE query count, <= T
    *,
    window: int = 0,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,  # (n_blocks, block_size, G, 1)
    v_scale: Optional[jax.Array] = None,
    kv_splits: Optional[int] = None,  # None = auto heuristic; 1 = off
    amla: bool = False,
) -> jax.Array:
    """Ragged paged attention straight off the block pool.

    ``k_scale``/``v_scale`` (both or neither) mark int8 pools: K/V pages
    hold int8 codes and the scale pools hold each (slot, head)'s amax
    scale (fp32 or bf16); the kernel dequantizes inside its page loop
    (transformer._kv_dequantize numerics, fp32 math), so quantized
    serving never materializes a dequantized pool copy.

    One launch serves rows with heterogeneous query counts: row b's
    query t sits at logical slot ``seq_lens[b] + t`` and sees slots
    ``<= seq_lens[b] + t`` (its own just-written K/V inclusive —
    identical to the gather path's per-query frontier), but only
    queries ``t < q_lens[b]`` are real; the rest are padding whose
    outputs come back as zeros and must be discarded by the caller.
    A decode row rides with q_len 1, a prefill chunk with its chunk
    length — the mixed batch costs each row only ITS OWN live pages
    (per-row DMA elision), not the longest row's scan.

    ``kv_splits`` partitions every row's page range across that many
    parallel grid lanes (FA2 work partitioning; partials merged by a
    log-sum-exp combine). ``None`` auto-tunes from (max_pages, B);
    ``1`` keeps the single-pass kernel. ``amla=True`` switches the
    online softmax to the exp2 MUL-by-ADD rescale (int8 scales absorbed
    into the same restructure). Both default to the single-pass classic
    form — bit-compatible with the pre-split kernel.

    Invariant (caller-enforced, unchecked under jit): 0 <= q_lens <= T
    and seq_lens + q_lens <= max_blocks * block_size. Returns q's
    shape. `interpret=None` auto-selects: compiled on TPU, interpreter
    elsewhere (tests).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if q.ndim != 4:
        raise ValueError(
            f"ragged attention takes (B, T, H, Dh) queries, got {q.shape} "
            f"(single-token decode belongs to paged_decode_attention)"
        )
    b, t, h, d = q.shape
    # Heads-major fold (H*T rows, query t of head h at row h*T + t):
    # keeps each GQA group's rows CONTIGUOUS for the kernel's static
    # group slices — same fold as the uniform multi-token kernel.
    qf = q.transpose(0, 2, 1, 3).reshape(b, h * t, d)
    g = k_pool.shape[2]
    if h % g != 0:
        raise ValueError(f"kv heads ({g}) must divide query heads ({h})")
    if k_pool.shape != v_pool.shape:
        raise ValueError(f"k/v pool mismatch: {k_pool.shape} vs {v_pool.shape}")
    if block_tables.shape[0] != b or seq_lens.shape != (b,):
        raise ValueError(
            f"tables {block_tables.shape} / seq_lens {seq_lens.shape} do not "
            f"match batch {b}"
        )
    if q_lens.shape != (b,):
        raise ValueError(f"q_lens {q_lens.shape} does not match batch {b}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if k_scale is not None:
        want = k_pool.shape[:-1] + (1,)
        if k_scale.shape != want or v_scale.shape != want:
            raise ValueError(
                f"scale pools must be {want}, got {k_scale.shape} / "
                f"{v_scale.shape}"
            )
    nb = block_tables.shape[1]
    if kv_splits is None:
        kv_splits = _auto_kv_splits(nb, b)
    kv_splits = int(kv_splits)
    if kv_splits < 1:
        raise ValueError(f"kv_splits must be >= 1 (or None for auto), "
                         f"got {kv_splits}")
    kv_splits = min(kv_splits, nb)
    out = _ragged_call(
        qf, k_pool, v_pool, block_tables, seq_lens, q_lens, t, int(window),
        bool(interpret), kv_splits=kv_splits, amla=bool(amla),
        k_scale=k_scale, v_scale=v_scale,
    )
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def ragged_gather_attention(
    q: jax.Array,  # (B, T, H, Dh)
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    q_lens: jax.Array,
    *,
    window: int = 0,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """XLA gather fallback: materialize ``pool[tables]`` and run the
    per-query masked softmax — the model's gather branch math with the
    ragged validity term added. ONE source of truth for what the kernel
    must compute; tier-1 CPU tests pin the kernel (interpret mode)
    against this across the kv_splits × amla grid. Pad queries
    (t >= q_lens[b]) return zeros, matching the kernel's safe-l
    finalize. ``k_scale``/``v_scale`` mirror `ragged_paged_attention`:
    int8 pools dequantized after the gather."""
    b, t, h, d = q.shape
    g = k_pool.shape[2]
    n_rep = h // g
    bs = k_pool.shape[1]
    kv_len = block_tables.shape[1] * bs
    ck = k_pool[block_tables].reshape(b, kv_len, g, d)
    cv = v_pool[block_tables].reshape(b, kv_len, g, d)
    if k_scale is not None:
        cks = k_scale[block_tables].reshape(b, kv_len, g, 1)
        cvs = v_scale[block_tables].reshape(b, kv_len, g, 1)
        ck = ck.astype(jnp.float32) * (
            cks.astype(jnp.float32) * (1.0 / 127.0)
        )
        cv = cv.astype(jnp.float32) * (
            cvs.astype(jnp.float32) * (1.0 / 127.0)
        )
    if n_rep > 1:
        ck = jnp.repeat(ck, n_rep, axis=2)
        cv = jnp.repeat(cv, n_rep, axis=2)
    lin = jnp.arange(kv_len)
    pos = seq_lens[:, None] + jnp.arange(t)[None, :]  # (B, T)
    mask = lin[None, None, :] <= pos[:, :, None]  # (B, T, kv_len)
    if window:
        mask = mask & (lin[None, None, :] > pos[:, :, None] - window)
    qvalid = jnp.arange(t)[None, :] < q_lens[:, None]  # (B, T)
    mask = mask & qvalid[:, :, None]
    s = jnp.einsum(
        "bthd,bkhd->bthk", q.astype(jnp.float32), ck.astype(jnp.float32)
    ) / (d**0.5)
    s = jnp.where(mask[:, :, None, :], s, NEG_INF)
    # Pad queries are fully masked: a plain softmax would spread 1/kv_len
    # everywhere; zero them like the kernel's safe-l division does.
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask[:, :, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bthk,bkhd->bthd", p, cv.astype(jnp.float32))
    return out.astype(q.dtype)
