"""Ragged paged-attention Pallas kernel: heterogeneous query counts per row.

`ops/pallas_paged.py` serves a batch where every row carries the SAME
number of query tokens (1 at decode, k+1 at the speculative verify).
Chunked prefill breaks that symmetry: one launch now mixes decode rows
(q_len 1..spec_depth) with prefill-chunk rows (q_len up to
`serving.prefill_chunk_tokens`), each row's queries starting at its own
committed offset `seq_lens[b]`. This kernel is the uniform kernel
generalized by ONE extra scalar-prefetch operand, `q_lens (B,)`:

  - Block liveness becomes per-row: page j is fetched/computed only when
    ``j*bs <= seq + (q_len - 1)`` — a decode row (q_len 1) stops at its
    frontier page while a chunk row in the same launch scans up to its
    chunk end. Dead table entries stay 0 (the reserved scratch block), so
    consecutive identical indices elide their DMA exactly as in
    pallas_paged.py.
  - The causal mask gains a query-validity term: query t of row b is
    real only when ``t < q_lens[b]``; pad queries (the static T bound
    minus the row's true count) are fully masked and finalize to zeros
    via the safe-l division — they cost VPU lanes, never HBM traffic
    beyond the row's live pages.
  - Online-softmax f32 accumulators in VMEM and the GQA-native shared
    K/V blocks are inherited unchanged (heads-major fold keeps each
    group's rows contiguous for the static group slices).

`ragged_gather_attention` below is the XLA fallback: the same
pool-gather + per-query masked softmax the model's gather branch runs,
extended with the q_len validity mask. CPU tier-1 tests pin the kernel
against it (interpret mode), and chunked-vs-monolithic bit-identity on
CPU rides the model's gather branch, which ignores q_lens entirely —
pad-query outputs are computed and discarded there, so real-query
numerics are untouched by construction.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite: exp/max edge cases (same constant as pallas_paged)


def _ragged_kernel(
    tbl_ref,  # (B, nb) int32 scalar-prefetch (SMEM)
    seq_ref,  # (B,) int32 scalar-prefetch (SMEM)
    qlen_ref,  # (B,) int32 scalar-prefetch (SMEM) — true queries per row
    q_ref,  # (1, H*T, Dh) — heads-major fold, query t at row h*T + t
    k_ref,  # (1, bs, G, Dh) — the page tbl[b, j]
    v_ref,  # (1, bs, G, Dh)
    *rest,  # quantized: ks_ref, vs_ref (1, bs, G, 1) scale pages, then
    #         o_ref + the three VMEM scratch refs; exact: o_ref + scratch
    bs: int,
    nb: int,
    g: int,
    n_rep: int,
    t: int,
    scale: float,
    window: int,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    seq = seq_ref[b]
    qlen = qlen_ref[b]
    # Per-row block liveness: the LAST real query of this row sits at
    # slot seq + qlen - 1; pages past it are dead for this row even when
    # another row in the launch reaches further (the uniform kernel's
    # static (t-1) bound made every row pay the longest row's scan).
    # qlen == 0 rows (pure padding) run no block at all.
    run = j * bs <= seq + (qlen - 1)
    if window:
        run = jnp.logical_and(run, j * bs + bs - 1 > seq - window)

    @pl.when(run)
    def _compute():
        rows = n_rep * t
        # Row r within a group is query (r % t) of head (r // t); the
        # heads-major fold keeps each GQA group's rows contiguous so the
        # static slice below works.
        t_of_row = jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) % t
        lin = j * bs + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        # Causal frontier per query PLUS query validity: queries at or
        # past the row's true count are padding (fully masked; finalize
        # zeros them via safe_l).
        valid = jnp.logical_and(lin <= seq + t_of_row, t_of_row < qlen)
        if window:
            valid = jnp.logical_and(valid, lin > seq + t_of_row - window)
        q = q_ref[0]  # (H*T, Dh)
        k = k_ref[0]  # (bs, G, Dh)
        v = v_ref[0]
        if quantized:
            ks = ks_ref[0]  # (bs, G, 1)
            vs = vs_ref[0]
        for grp in range(g):
            sl = slice(grp * rows, (grp + 1) * rows)
            qg = q[sl]  # (n_rep*T, Dh)
            kg = k[:, grp]  # (bs, Dh)
            vg = v[:, grp]
            if quantized:
                # Fused page dequant — the transformer._kv_dequantize
                # numerics (int8 * fp32-upcast scale / 127), done HERE so
                # only int8 bytes + scale pages cross HBM. The s/pv dots
                # below then run in f32 either way (bf16 accumulation
                # semantics are preserved by preferred_element_type=f32).
                kg = kg.astype(jnp.float32) * (
                    ks[:, grp].astype(jnp.float32) * (1.0 / 127.0)
                )
                vg = vg.astype(jnp.float32) * (
                    vs[:, grp].astype(jnp.float32) * (1.0 / 127.0)
                )
            s = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (n_rep*T, bs)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_scr[sl]  # (n_rep*T, 1)
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            # Fully-masked rows keep m == NEG_INF -> exp(s-m)=1 on masked
            # entries; zeroed by the mask itself (flash kernel discipline).
            p = jnp.where(valid, p, 0.0)
            l_scr[sl] = l_scr[sl] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_scr[sl] = m_new
            pv = jax.lax.dot_general(
                p.astype(vg.dtype), vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc[sl] = acc[sl] * alpha + pv

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("t", "window", "interpret"))
def _ragged_call(q, k_pool, v_pool, block_tables, seq_lens, q_lens, t,
                 window, interpret, k_scale=None, v_scale=None):
    b, ht, d = q.shape  # ht == H * T, heads-major fold
    n_blocks, bs, g, _ = k_pool.shape
    nb = block_tables.shape[1]
    n_rep = ht // (g * t)
    quantized = k_scale is not None
    kernel = functools.partial(
        _ragged_kernel, bs=bs, nb=nb, g=g, n_rep=n_rep, t=t,
        scale=1.0 / (d**0.5), window=window, quantized=quantized,
    )
    page_spec = pl.BlockSpec(
        (1, bs, g, d),
        lambda bb, j, tbl, seq, ql: (tbl[bb, j], 0, 0, 0),
    )
    in_specs = [
        pl.BlockSpec(
            (1, ht, d), lambda bb, j, tbl, seq, ql: (bb, 0, 0)
        ),
        page_spec,
        page_spec,
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        # Scale pages ride the SAME block-table index map as their K/V
        # pages — a dead table entry elides all four DMAs together.
        scale_spec = pl.BlockSpec(
            (1, bs, g, 1),
            lambda bb, j, tbl, seq, ql: (tbl[bb, j], 0, 0, 0),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, ht, d), lambda bb, j, tbl, seq, ql: (bb, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((ht, d), jnp.float32),
            pltpu.VMEM((ht, 1), jnp.float32),
            pltpu.VMEM((ht, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, ht, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q_lens.astype(jnp.int32), *operands)


def ragged_paged_attention(
    q: jax.Array,  # (B, T, H, Dh) — T is the batch's MAX query count
    k_pool: jax.Array,  # (n_blocks, block_size, G, Dh)
    v_pool: jax.Array,
    block_tables: jax.Array,  # (B, max_blocks) int32, 0-padded tails
    seq_lens: jax.Array,  # (B,) int32 — row b's committed offset
    q_lens: jax.Array,  # (B,) int32 — row b's TRUE query count, <= T
    *,
    window: int = 0,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,  # (n_blocks, block_size, G, 1)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Ragged paged attention straight off the block pool.

    ``k_scale``/``v_scale`` (both or neither) mark int8 pools: K/V pages
    hold int8 codes and the scale pools hold each (slot, head)'s amax
    scale (fp32 or bf16); the kernel dequantizes inside its page loop
    (transformer._kv_dequantize numerics, fp32 math), so quantized
    serving never materializes a dequantized pool copy.

    One launch serves rows with heterogeneous query counts: row b's
    query t sits at logical slot ``seq_lens[b] + t`` and sees slots
    ``<= seq_lens[b] + t`` (its own just-written K/V inclusive —
    identical to the gather path's per-query frontier), but only
    queries ``t < q_lens[b]`` are real; the rest are padding whose
    outputs come back as zeros and must be discarded by the caller.
    A decode row rides with q_len 1, a prefill chunk with its chunk
    length — the mixed batch costs each row only ITS OWN live pages
    (per-row DMA elision), not the longest row's scan.

    Invariant (caller-enforced, unchecked under jit): 0 <= q_lens <= T
    and seq_lens + q_lens <= max_blocks * block_size. Returns q's
    shape. `interpret=None` auto-selects: compiled on TPU, interpreter
    elsewhere (tests).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if q.ndim != 4:
        raise ValueError(
            f"ragged attention takes (B, T, H, Dh) queries, got {q.shape} "
            f"(single-token decode belongs to paged_decode_attention)"
        )
    b, t, h, d = q.shape
    # Heads-major fold (H*T rows, query t of head h at row h*T + t):
    # keeps each GQA group's rows CONTIGUOUS for the kernel's static
    # group slices — same fold as the uniform multi-token kernel.
    qf = q.transpose(0, 2, 1, 3).reshape(b, h * t, d)
    g = k_pool.shape[2]
    if h % g != 0:
        raise ValueError(f"kv heads ({g}) must divide query heads ({h})")
    if k_pool.shape != v_pool.shape:
        raise ValueError(f"k/v pool mismatch: {k_pool.shape} vs {v_pool.shape}")
    if block_tables.shape[0] != b or seq_lens.shape != (b,):
        raise ValueError(
            f"tables {block_tables.shape} / seq_lens {seq_lens.shape} do not "
            f"match batch {b}"
        )
    if q_lens.shape != (b,):
        raise ValueError(f"q_lens {q_lens.shape} does not match batch {b}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if k_scale is not None:
        want = k_pool.shape[:-1] + (1,)
        if k_scale.shape != want or v_scale.shape != want:
            raise ValueError(
                f"scale pools must be {want}, got {k_scale.shape} / "
                f"{v_scale.shape}"
            )
    out = _ragged_call(
        qf, k_pool, v_pool, block_tables, seq_lens, q_lens, t, int(window),
        bool(interpret), k_scale=k_scale, v_scale=v_scale,
    )
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def ragged_gather_attention(
    q: jax.Array,  # (B, T, H, Dh)
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    q_lens: jax.Array,
    *,
    window: int = 0,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """XLA gather fallback: materialize ``pool[tables]`` and run the
    per-query masked softmax — the model's gather branch math with the
    ragged validity term added. ONE source of truth for what the kernel
    must compute; tier-1 CPU tests pin the kernel (interpret mode)
    against this. Pad queries (t >= q_lens[b]) return zeros, matching
    the kernel's safe-l finalize. ``k_scale``/``v_scale`` mirror
    `ragged_paged_attention`: int8 pools dequantized after the gather."""
    b, t, h, d = q.shape
    g = k_pool.shape[2]
    n_rep = h // g
    bs = k_pool.shape[1]
    kv_len = block_tables.shape[1] * bs
    ck = k_pool[block_tables].reshape(b, kv_len, g, d)
    cv = v_pool[block_tables].reshape(b, kv_len, g, d)
    if k_scale is not None:
        cks = k_scale[block_tables].reshape(b, kv_len, g, 1)
        cvs = v_scale[block_tables].reshape(b, kv_len, g, 1)
        ck = ck.astype(jnp.float32) * (
            cks.astype(jnp.float32) * (1.0 / 127.0)
        )
        cv = cv.astype(jnp.float32) * (
            cvs.astype(jnp.float32) * (1.0 / 127.0)
        )
    if n_rep > 1:
        ck = jnp.repeat(ck, n_rep, axis=2)
        cv = jnp.repeat(cv, n_rep, axis=2)
    lin = jnp.arange(kv_len)
    pos = seq_lens[:, None] + jnp.arange(t)[None, :]  # (B, T)
    mask = lin[None, None, :] <= pos[:, :, None]  # (B, T, kv_len)
    if window:
        mask = mask & (lin[None, None, :] > pos[:, :, None] - window)
    qvalid = jnp.arange(t)[None, :] < q_lens[:, None]  # (B, T)
    mask = mask & qvalid[:, :, None]
    s = jnp.einsum(
        "bthd,bkhd->bthk", q.astype(jnp.float32), ck.astype(jnp.float32)
    ) / (d**0.5)
    s = jnp.where(mask[:, :, None, :], s, NEG_INF)
    # Pad queries are fully masked: a plain softmax would spread 1/kv_len
    # everywhere; zero them like the kernel's safe-l division does.
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask[:, :, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bthk,bkhd->bthd", p, cv.astype(jnp.float32))
    return out.astype(q.dtype)
