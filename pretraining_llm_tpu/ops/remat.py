"""Rematerialization policies: one name -> jax.checkpoint wrapper mapping.

Single source of truth for what each `ModelConfig.remat` value saves, shared
by the scanned-layer path (models.transformer.forward) and the pipelined path
(parallel.pipeline.pipeline_apply) so the same config string always means the
same backward-pass schedule.

Policies (cheapest memory -> cheapest recompute):
  - "full":          save nothing; backward re-runs the whole block.
  - "dots_saveable": save every matmul output (XLA default-ish middle ground).
  - "save_attn":     save only the merged attention output ("attn_out" tag);
                     backward re-runs QKV projection + the flash forward.
  - "save_attn_res": save the flash kernel's OUTPUT residuals ("attn_o_res",
                     "attn_lse") instead: the attention VJP starts from its
                     saved (o, lse) — the flash forward never reruns — while
                     the QKV projection (plain matmuls the VJP needs as
                     inputs anyway) still recomputes. Same memory class as
                     save_attn (+lse, 4 bytes/token/head); kills the double
                     flash-forward the 2026-08-01 profile showed under
                     save_attn. (Distinct from the LOSING save_qkv_attn,
                     which additionally saved the q/k/v INPUTS.)
  - "save_qkv_attn": additionally save post-RoPE q/k/v ("qkv") and the flash
                     VJP residuals ("attn_o_res", "attn_lse") — the attention
                     backward starts directly from its residuals, so neither
                     the QKV projection nor the flash forward kernel reruns.
  - "save_big":      save_qkv_attn + the MLP hidden ("mlp_hidden"); recompute
                     is just LN/residual elementwise math.
  - "none":          no checkpointing (autodiff saves everything it needs).
"""

from __future__ import annotations

from typing import Callable

import jax

# Tag names referenced by checkpoint_name() calls in models/transformer.py,
# models/moe.py and ops/pallas_flash.py. Keep these lists in sync with the
# tag sites — a policy naming a tag that no longer exists silently saves
# nothing for it.
_SAVE_ATTN = ("attn_out",)
_SAVE_ATTN_RES = ("attn_o_res", "attn_lse")
_SAVE_QKV_ATTN = ("qkv",) + _SAVE_ATTN_RES
_SAVE_BIG = _SAVE_QKV_ATTN + ("mlp_hidden",)

POLICIES = ("none", "full", "dots_saveable", "save_attn", "save_attn_res",
            "save_qkv_attn", "save_big")


def checkpoint_wrap(fn: Callable, remat: str) -> Callable:
    """Wrap a per-layer body with the checkpoint policy named by ``remat``."""
    if remat == "none":
        return fn
    if remat == "full":
        return jax.checkpoint(fn)
    if remat == "dots_saveable":
        return jax.checkpoint(fn, policy=jax.checkpoint_policies.dots_saveable)
    if remat == "save_attn":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_only_these_names(*_SAVE_ATTN)
        )
    if remat == "save_attn_res":
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.save_only_these_names(*_SAVE_ATTN_RES),
        )
    if remat == "save_qkv_attn":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_only_these_names(*_SAVE_QKV_ATTN)
        )
    if remat == "save_big":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_only_these_names(*_SAVE_BIG)
        )
    raise ValueError(f"unknown remat policy {remat!r}")
