from pretraining_llm_tpu.parallel.mesh import build_mesh, initialize_distributed  # noqa: F401
from pretraining_llm_tpu.parallel.sharding import (  # noqa: F401
    batch_pspec,
    named_sharding_tree,
    param_pspec_tree,
)
