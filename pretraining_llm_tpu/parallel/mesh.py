"""Device mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's DDP process-group setup
(`/root/reference/scripts/train_transformer.py:15-29`, which reads
RANK/LOCAL_RANK/WORLD_SIZE and calls `dist.init_process_group`). On TPU the
runtime owns transport: one process per host calls
`jax.distributed.initialize()`, and all parallelism is expressed as shardings
over a named `jax.sharding.Mesh` whose axes ride ICI within a slice and DCN
across slices. There is no NCCL analog to manage.

Axes (sized by `MeshConfig`):
  data   — pure data parallelism (gradient all-reduce)
  fsdp   — data parallelism + param/optimizer-state sharding (ZeRO-3 style)
  tensor — Megatron-style tensor parallelism (heads / mlp hidden / vocab)
  seq    — sequence/context parallelism (ring attention, Megatron-SP)
  expert — expert parallelism (MoE expert FFNs sharded one-per-group)
  pipe   — pipeline parallelism (layer stages, microbatch schedule)
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from pretraining_llm_tpu.config import MeshConfig


def initialize_distributed() -> None:
    """Initialize the multi-host JAX runtime when running under a launcher.

    MUST be called before anything touches a device (jax.distributed.initialize
    refuses to run once the XLA backend exists) — entry points call it first.
    A single-process run (no coordinator address in the environment) is a
    no-op. Mirrors the reference's `if 'RANK' in os.environ` gate
    (train_transformer.py:15) in spirit, keyed on JAX's own coordination env
    vars.
    """
    if "JAX_COORDINATOR_ADDRESS" in os.environ or "COORDINATOR_ADDRESS" in os.environ:
        try:
            jax.distributed.initialize()
        except RuntimeError:
            pass  # already initialized (e.g. called twice)


def needs_mesh(mesh_config) -> bool:
    """Whether training must build a device mesh: more than one device, or
    any configured mesh axis > 1 (single source of truth — the Trainer and
    scripts/train.py --compile-only must agree, or the preflight validates a
    different program than the run executes)."""
    import jax as _jax

    return _jax.device_count() > 1 or any(
        s > 1
        for s in (
            mesh_config.fsdp,
            mesh_config.tensor,
            mesh_config.seq,
            mesh_config.expert,
            mesh_config.pipe,
        )
    )


def build_mesh(
    mesh_config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the named device mesh.

    Devices are laid out so that the fastest-varying axes (tensor, seq) map to
    physically adjacent devices — XLA's default device order enumerates ICI
    neighbors contiguously, so putting the most communication-heavy axes last
    keeps their collectives on the shortest ICI paths.
    """
    if devices is None:
        devices = jax.devices()
    sizes = mesh_config.sizes(len(devices))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, mesh_config.axis_names)


def single_device_mesh() -> Mesh:
    """An all-ones mesh on the first device — for tests and CPU smoke runs."""
    names = MeshConfig().axis_names
    return Mesh(np.asarray(jax.devices()[:1]).reshape((1,) * len(names)), names)
