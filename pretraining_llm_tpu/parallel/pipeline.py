"""Pipeline parallelism: layer stages over the 'pipe' mesh axis.

SURVEY §2.2 lists PP as absent from the reference (whose model lives on one
device, train_transformer.py:116) and asks the framework to leave a mesh axis
for it. This is the TPU-native design — no per-stage processes, no send/recv
threads, no schedule executor; the whole pipeline is ONE jitted SPMD program:

  - The stacked block params (leading n_layers dim, scanned by the model)
    reshard so each pipe rank holds a contiguous slice of layers
    (`PartitionSpec('pipe', ...)` on the stacked dim — stage assignment is a
    sharding decision, not a code structure).
  - A GPipe schedule runs inside `jax.shard_map`: each tick, stage 0 injects
    the next microbatch, every stage applies its local layers, and activations
    hop to the next stage with a single `jax.lax.ppermute` (one ICI neighbor
    hop). n_micro + n_stages - 1 ticks drain the pipe.
  - `interleave=V>1` upgrades this to the Megatron interleaved (virtual
    stage) schedule: each rank hosts V round-robin depth chunks, microbatches
    lap the ring V times (the ppermute gains a wrap edge), and the bubble
    fraction drops V-fold to (S-1)/(V*n_micro + S-1).
  - The backward pass needs no schedule of its own: `jax.grad` transposes the
    whole loop (ppermute transposes to the reverse hop), so the 1F1B-style
    reverse traffic falls out of autodiff.
  - Embeddings / final norm / lm-head stay outside the region under plain
    GSPMD, replicated over 'pipe' (they are a tiny fraction of compute).

Composes with the other mesh axes: the shard_map region is manual over
'pipe' ONLY (jax partial-manual mode), so the batch dims stay auto-sharded
over 'data'/'fsdp' and each stage's weights keep their tensor/fsdp/expert
specs with GSPMD inserting the TP/EP collectives inside the stage body —
PP x TP x DP 3-D parallelism from one schedule.

Why there is no 1F1B schedule (deliberate): 1F1B's advantage over GPipe is
peak ACTIVATION memory — it caps in-flight microbatches at n_stages by
interleaving each microbatch's backward right after its forward, which
requires hand-scheduling the backward. Here the backward is the autodiff
TRANSPOSE of the tick loop (`jax.grad` through `lax.scan` + `ppermute`),
so forward and backward cannot interleave per-microbatch — but the same
memory lever exists one level down: the remat policy on the STAGE BODY
(`checkpoint_wrap(block_fn, remat)`) decides what each tick stores for the
transposed pass, from everything (`none`) to boundary activations only
(`full`). Measured AOT (gpt2-124m, 2 stages x V=2, 4 microbatches, tp=2,
8 virtual devices): temp memory 4,408 MiB (remat=none) -> 1,397 MiB
(remat=full), a 3.2x drop — the bubble fraction is already 1F1B-equal
(schedule_ticks), and activation memory is a config knob instead of a
second schedule.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pretraining_llm_tpu.utils import jax_compat

BlockFn = Callable[[Any, jax.Array], Tuple[jax.Array, jax.Array]]


def schedule_ticks(n_micro: int, n_stages: int, interleave: int = 1) -> int:
    """Ticks the schedule runs: interleave*n_micro + n_stages - 1.

    With interleave=1 this is the GPipe minimum, n_micro + n_stages - 1, and
    bubble fraction = (n_stages - 1) / ticks — identical to 1F1B's (1F1B's
    win over GPipe is peak activation memory, ~n_stages instead of n_micro
    microbatches in flight, not bubble; here activation memory is governed by
    the remat policy on the stage body instead).

    With interleave=V>1 (Megatron-style interleaved virtual stages: each rank
    hosts V depth chunks of n_layers/(V*n_stages) layers, so a microbatch
    laps the ring V times), a tick costs 1/V of a GPipe tick — the fill/drain
    bubble is paid in chunk-times, shrinking the bubble fraction V-fold:
    (S-1)/(V*n_micro + S - 1). Raise pipeline_microbatches and/or
    pipeline_interleave to shrink the bubble.
    """
    return interleave * n_micro + n_stages - 1


def bubble_fraction(n_micro: int, n_stages: int, interleave: int = 1) -> float:
    return (n_stages - 1) / schedule_ticks(n_micro, n_stages, interleave)


def interleave_layout(blocks: Any, n_stages: int, interleave: int) -> Any:
    """Permute stacked block params depth-major -> rank-major chunk order.

    Depth chunk j = v*S + r lives on rank r under the interleaved schedule;
    rank-major order (r, v, k) makes the contiguous P('pipe') shards hold
    exactly each rank's V chunks. Baked ONCE into the train state
    (train_step.shard_train_state) instead of per step, which removes the
    cross-rank reshard + the XLA "[SPMD] involuntary full rematerialization"
    warnings (VERDICT r2 next #5). Checkpoints stay canonical depth-major:
    the trainer de-interleaves on save and re-interleaves on load.
    """
    if interleave <= 1:
        return blocks

    def perm(a):
        lpc = a.shape[0] // (n_stages * interleave)
        return (
            a.reshape(interleave, n_stages, lpc, *a.shape[1:])
            .swapaxes(0, 1)
            .reshape(a.shape)
        )

    return jax.tree.map(perm, blocks)


def deinterleave_layout(blocks: Any, n_stages: int, interleave: int) -> Any:
    """Inverse of `interleave_layout`: rank-major -> canonical depth-major."""
    if interleave <= 1:
        return blocks

    def inv(a):
        lpc = a.shape[0] // (n_stages * interleave)
        return (
            a.reshape(n_stages, interleave, lpc, *a.shape[1:])
            .swapaxes(0, 1)
            .reshape(a.shape)
        )

    return jax.tree.map(inv, blocks)


def pipeline_apply(
    blocks: Any,
    x: jax.Array,
    mesh: Mesh,
    block_fn: BlockFn,
    *,
    n_micro: int,
    remat: str = "none",
    interleave: int = 1,
    baked: bool = False,
    pipe_axis: str = "pipe",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
) -> Tuple[jax.Array, jax.Array]:
    """Run the stacked layer stack as a pipeline.

    blocks: stacked block params, leading dim n_layers sharded over 'pipe'
    COMPOSED with per-weight expert/tensor/fsdp dims (parallel.sharding
    composes them): the shard_map region is manual over 'pipe' ONLY, so
    GSPMD keeps inserting the TP/FSDP/EP collectives inside each stage —
    PP x TP x DP 3-D parallelism from one schedule.
    x: (B, T, D) embedded activations; B divides into n_micro microbatches.
    block_fn: (block_params, x) -> (x, aux) for ONE layer.
    interleave: virtual stages per rank (V). V=1 is plain GPipe. V>1 splits
    each rank's layers into V depth chunks laid out round-robin (rank r hosts
    chunks r, S+r, 2S+r, ...), so every microbatch laps the ring V times and
    the fill/drain bubble shrinks V-fold (see schedule_ticks). Costs one
    static permutation of the stacked layer dim per step (a cross-stage
    collective copy — at production scale you'd bake the permuted layout into
    the train state instead) plus V x the activation hop volume.
    Returns (y (B, T, D), aux_sum) — aux summed over layers, averaged over
    microbatches (matching the non-pipelined scan semantics).
    """
    n_stages = mesh.shape[pipe_axis]
    b = x.shape[0]
    # Microbatching happens on the GLOBAL batch (the batch dims stay
    # auto-sharded over the data axes inside the region); each microbatch
    # must still split evenly over the data shards.
    batch_shards = 1
    for ax in batch_axes:
        batch_shards *= mesh.shape.get(ax, 1)
    if b % n_micro != 0 or (b // n_micro) % batch_shards != 0:
        raise ValueError(
            f"global batch {b} must split into pipeline_microbatches="
            f"{n_micro} of a size divisible by the {batch_shards} data shards"
        )
    if x.shape[1] % n_stages != 0:
        raise ValueError(
            f"sequence length {x.shape[1]} must divide by n_stages="
            f"{n_stages} (the output reduce-scatter slices the sequence dim)"
        )
    if interleave > 1 and n_micro < n_stages:
        # Feasibility of the breadth-first interleaved schedule: microbatch m
        # finishes lap v at tick v*n_micro + m + n_stages - 1 and must be back
        # at rank 0 by tick (v+1)*n_micro + m, i.e. n_micro >= n_stages.
        raise ValueError(
            f"pipeline_interleave={interleave} needs pipeline_microbatches "
            f">= pipeline_stages ({n_micro} < {n_stages})"
        )

    from pretraining_llm_tpu.ops.remat import checkpoint_wrap

    body = checkpoint_wrap(block_fn, remat)
    n_layers = jax.tree.leaves(blocks)[0].shape[0]
    lpc = n_layers // (n_stages * interleave)  # layers per chunk

    if interleave > 1 and not baked:
        # Chunk j = v*S + r (depth order) must live on rank r; the schedule
        # needs rank-major (r, v, k) order. The TRAINING path bakes this
        # layout into the state once (``baked=True``, no per-step cost); this
        # in-line permute is the compatibility path for depth-major params
        # (tests, ad-hoc loss_fn calls) — an inherently cross-rank reshard
        # of the layer stack paid every step.
        blocks = interleave_layout(blocks, n_stages, interleave)

    # The XLA CPU emitter check-fails ("Invalid binary instruction opcode
    # copy") on any bf16 all-reduce-family collective inside a partial-manual
    # region. Two such collectives exist here: the output reduce-scatter and
    # the IMPLICIT psum that transposes the replicated-x input in backward.
    # On CPU route both through fp32 by widening x at the region boundary
    # (TPU runs bf16 collectives natively and skips all of this).
    act_dtype = x.dtype
    boundary_f32 = jax.default_backend() == "cpu" and x.dtype == jnp.bfloat16
    if boundary_f32:
        x = x.astype(jnp.float32)

    def local(blocks_local: Any, x_global: jax.Array):
        # Manual over 'pipe' only: blocks_local is this rank's layer slice
        # (leading dim n_layers/n_stages = V*lpc, chunk-ordered when
        # interleave>1) but x_global is the full (B, T, D) batch — its data/
        # tensor sharding stays under GSPMD (auto axes).
        rank = jax.lax.axis_index(pipe_axis)
        x_global = x_global.astype(act_dtype)
        mb = b // n_micro
        mbs = x_global.reshape(n_micro, mb, *x_global.shape[1:])
        chunks = jax.tree.map(
            lambda a: a.reshape(interleave, lpc, *a.shape[1:]), blocks_local
        )

        def apply_chunk(chunk: Any, a: jax.Array) -> Tuple[jax.Array, jax.Array]:
            def layer(carry, blk):
                h, aux = carry
                h, aux_i = body(blk, h)
                return (h, aux + aux_i), None

            (y, aux), _ = jax.lax.scan(layer, (a, jnp.zeros((), jnp.float32)), chunk)
            return y, aux

        if interleave > 1:
            # Ring: rank S-1 wraps around to feed rank 0 the next lap.
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        else:
            # Chain: stage s sends to s+1; stage 0 receives only injections.
            perm = [(i, i + 1) for i in range(n_stages - 1)]

        n_items = interleave * n_micro

        def tick(carry, t):
            recv, wrap_buf, out_buf, aux_sum = carry
            # Work item at this rank this tick: u-th of the m-major (m, v)
            # stream; m = microbatch, v = lap (chunk index on this rank).
            u = t - rank
            m = jnp.clip(jnp.mod(u, n_micro), 0, n_micro - 1)
            v = jnp.clip(u // n_micro, 0, interleave - 1)
            valid = (u >= 0) & (u < n_items)

            if interleave > 1:
                # Rank 0 banks the wrapped activation that arrived this tick:
                # rank S-1's output from tick t-1, item u_w = t - S. It is
                # needed at tick (v_w+1)*n_micro + m_w >= its arrival (the
                # n_micro >= S check above), so bank-then-read is safe.
                u_w = t - n_stages
                m_w = jnp.clip(jnp.mod(u_w, n_micro), 0, n_micro - 1)
                bank = (rank == 0) & (u_w >= 0) & (u_w // n_micro < interleave - 1)
                wrap_buf = jnp.where(
                    bank,
                    jax.lax.dynamic_update_index_in_dim(wrap_buf, recv, m_w, 0),
                    wrap_buf,
                )
                inject = jax.lax.dynamic_index_in_dim(mbs, m, 0, keepdims=False)
                lapped = jax.lax.dynamic_index_in_dim(wrap_buf, m, 0, keepdims=False)
                first = jnp.where(v == 0, inject, lapped)
            else:
                first = jax.lax.dynamic_index_in_dim(mbs, m, 0, keepdims=False)
            a = jnp.where(rank == 0, first, recv)

            chunk = jax.tree.map(
                lambda c: jax.lax.dynamic_index_in_dim(c, v, 0, keepdims=False), chunks
            )
            y, aux = apply_chunk(chunk, a)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            # Last stage banks each microbatch's final lap.
            done = (rank == n_stages - 1) & valid & (v == interleave - 1)
            banked = jax.lax.dynamic_update_index_in_dim(out_buf, y, m, 0)
            out_buf = jnp.where(done, banked, out_buf)
            recv = jax.lax.ppermute(y, pipe_axis, perm)
            return (recv, wrap_buf, out_buf, aux_sum), None

        wrap0 = (
            jnp.zeros_like(mbs)
            if interleave > 1
            else jnp.zeros((0,), x_global.dtype)
        )
        init = (
            jnp.zeros((mb, *x_global.shape[1:]), x_global.dtype),
            wrap0,
            jnp.zeros_like(mbs),
            jnp.zeros((), jnp.float32),
        )
        (_, _, out_buf, aux_sum), _ = jax.lax.scan(
            tick, init, jnp.arange(schedule_ticks(n_micro, n_stages, interleave))
        )

        out = out_buf.reshape(b, *x_global.shape[1:])
        # Return routing: out_buf is zeros on every rank but the last, so a
        # reduce-scatter over 'pipe' hands each rank its 1/n_stages slice of
        # the sequence dim — half the bandwidth of the old full-activation
        # psum broadcast, and the final-norm/lm-head/CE downstream now runs
        # seq-sharded over the pipe axis instead of replicated on it.
        rs_dtype = jnp.float32 if boundary_f32 else out.dtype
        out = jax.lax.psum_scatter(
            out.astype(rs_dtype), pipe_axis, scatter_dimension=1, tiled=True
        ).astype(out.dtype)
        # aux was computed over the GLOBAL batch inside each stage (auto
        # axes); sum over the pipe ranks' chunks, average over microbatches.
        aux_total = jax.lax.psum(aux_sum, pipe_axis) / n_micro
        return out, aux_total

    blocks_spec = jax.tree.map(lambda _: P(pipe_axis), blocks)
    return jax_compat.shard_map(
        local,
        mesh=mesh,
        in_specs=(blocks_spec, P()),
        out_specs=(P(None, pipe_axis), P()),
        axis_names={pipe_axis},
        check_vma=False,
    )(blocks, x)
