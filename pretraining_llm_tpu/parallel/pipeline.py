"""Pipeline parallelism: layer stages over the 'pipe' mesh axis.

SURVEY §2.2 lists PP as absent from the reference (whose model lives on one
device, train_transformer.py:116) and asks the framework to leave a mesh axis
for it. This is the TPU-native design — no per-stage processes, no send/recv
threads, no schedule executor; the whole pipeline is ONE jitted SPMD program:

  - The stacked block params (leading n_layers dim, scanned by the model)
    reshard so each pipe rank holds a contiguous slice of layers
    (`PartitionSpec('pipe', ...)` on the stacked dim — stage assignment is a
    sharding decision, not a code structure).
  - A GPipe schedule runs inside `jax.shard_map`: each tick, stage 0 injects
    the next microbatch, every stage applies its local layers, and activations
    hop to the next stage with a single `jax.lax.ppermute` (one ICI neighbor
    hop). n_micro + n_stages - 1 ticks drain the pipe.
  - The backward pass needs no schedule of its own: `jax.grad` transposes the
    whole loop (ppermute transposes to the reverse hop), so the 1F1B-style
    reverse traffic falls out of autodiff.
  - Embeddings / final norm / lm-head stay outside the region under plain
    GSPMD, replicated over 'pipe' (they are a tiny fraction of compute).

Composes with the 'data'/'fsdp' batch axes (batch stays sharded inside the
region). Within a stage, weights are replicated over fsdp/tensor — PP here is
an alternative to FSDP/TP for the layer stack, as in the dryrun configs.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

BlockFn = Callable[[Any, jax.Array], Tuple[jax.Array, jax.Array]]


def schedule_ticks(n_micro: int, n_stages: int) -> int:
    """Ticks the schedule runs: n_micro + n_stages - 1, the GPipe minimum.

    Bubble fraction = (n_stages - 1) / ticks — identical to 1F1B's (1F1B's
    win over GPipe is peak activation memory, ~n_stages instead of n_micro
    microbatches in flight, not bubble; here activation memory is governed by
    the remat policy on the stage body instead). Raise
    pipeline_microbatches to shrink the bubble.
    """
    return n_micro + n_stages - 1


def bubble_fraction(n_micro: int, n_stages: int) -> float:
    return (n_stages - 1) / schedule_ticks(n_micro, n_stages)


def pipeline_apply(
    blocks: Any,
    x: jax.Array,
    mesh: Mesh,
    block_fn: BlockFn,
    *,
    n_micro: int,
    remat: str = "none",
    pipe_axis: str = "pipe",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
) -> Tuple[jax.Array, jax.Array]:
    """Run the stacked layer stack as a pipeline.

    blocks: stacked block params, leading dim n_layers (sharded over 'pipe').
    x: (B, T, D) embedded activations; B divides into n_micro microbatches.
    block_fn: (block_params, x) -> (x, aux) for ONE layer.
    Returns (y (B, T, D), aux_sum) — aux summed over layers, averaged over
    microbatches (matching the non-pipelined scan semantics).
    """
    n_stages = mesh.shape[pipe_axis]
    b = x.shape[0]
    # The PER-SHARD batch must divide into microbatches (the reshape happens
    # inside the manual region, after the batch axes split it).
    batch_shards = 1
    for ax in batch_axes:
        batch_shards *= mesh.shape.get(ax, 1)
    if b % batch_shards != 0 or (b // batch_shards) % n_micro != 0:
        raise ValueError(
            f"global batch {b} over {batch_shards} data shards gives a local "
            f"batch of {b // batch_shards if b % batch_shards == 0 else b / batch_shards}, "
            f"not divisible by pipeline_microbatches={n_micro}"
        )
    if x.shape[1] % n_stages != 0:
        raise ValueError(
            f"sequence length {x.shape[1]} must divide by n_stages="
            f"{n_stages} (the output reduce-scatter slices the sequence dim)"
        )

    from pretraining_llm_tpu.ops.remat import checkpoint_wrap

    body = checkpoint_wrap(block_fn, remat)

    def local(blocks_local: Any, x_local: jax.Array):
        # blocks_local: leading dim n_layers/n_stages; x_local: (b_local, T, D)
        from pretraining_llm_tpu.parallel.sharding import activation_mesh

        rank = jax.lax.axis_index(pipe_axis)
        bl = x_local.shape[0]
        mb = bl // n_micro
        mbs = x_local.reshape(n_micro, mb, *x_local.shape[1:])

        def apply_stage(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
            def layer(carry, blk):
                h, aux = carry
                h, aux_i = body(blk, h)
                return (h, aux + aux_i), None

            (y, aux), _ = jax.lax.scan(layer, (a, jnp.zeros((), jnp.float32)), blocks_local)
            return y, aux

        # Stage s sends to s+1; stage 0 receives zeros (replaced by injection).
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            recv, out_buf, aux_sum = carry
            inject = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            a = jnp.where(rank == 0, inject, recv)
            y, aux = apply_stage(a)
            # This rank computed microbatch (t - rank): only count real work.
            valid = ((t - rank) >= 0) & ((t - rank) < n_micro)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            # Last stage banks its finished microbatch.
            slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            banked = jax.lax.dynamic_update_index_in_dim(out_buf, y, slot, 0)
            out_buf = jnp.where((rank == n_stages - 1) & (t >= n_stages - 1), banked, out_buf)
            recv = jax.lax.ppermute(y, pipe_axis, perm)
            return (recv, out_buf, aux_sum), None

        # GSPMD sharding constraints are meaningless inside the manual region.
        with activation_mesh(None):
            init = (
                jnp.zeros((mb, *x_local.shape[1:]), x_local.dtype),
                jnp.zeros_like(mbs),
                jnp.zeros((), jnp.float32),
            )
            (_, out_buf, aux_sum), _ = jax.lax.scan(
                tick, init, jnp.arange(schedule_ticks(n_micro, n_stages))
            )

        out = out_buf.reshape(bl, *x_local.shape[1:])
        # Return routing: out_buf is zeros on every rank but the last, so a
        # reduce-scatter over 'pipe' hands each rank its 1/n_stages slice of
        # the sequence dim — half the bandwidth of the old full-activation
        # psum broadcast, and the final-norm/lm-head/CE downstream now runs
        # seq-sharded over the pipe axis instead of replicated on it.
        out = jax.lax.psum_scatter(out, pipe_axis, scatter_dimension=1, tiled=True)
        # Aux statistics are per (data shard x microbatch) group; average over
        # microbatches AND the batch axes so the scalar is well-defined
        # (replicated) everywhere.
        aux_total = jax.lax.psum(aux_sum, pipe_axis) / n_micro
        aux_total = jax.lax.pmean(aux_total, batch_axes)
        return out, aux_total

    blocks_spec = jax.tree.map(lambda _: P(pipe_axis), blocks)
    x_spec = P(batch_axes)
    out_spec = P(batch_axes, pipe_axis)
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(blocks_spec, x_spec),
        out_specs=(out_spec, P()),
        check_vma=False,
    )(blocks, x)
