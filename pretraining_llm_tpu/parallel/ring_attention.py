"""Ring attention: context parallelism over the 'seq' mesh axis.

The reference cannot exceed context 512 — attention materializes full (B,T,T)
scores per head (/root/reference/src/models/attention.py:51-57) and there is
no sequence/context parallelism of any kind (SURVEY §2.2). This module scales
context across chips the TPU way:

  - the sequence dim of q/k/v is sharded over the 'seq' mesh axis
    (`jax.shard_map`);
  - each device keeps its q shard resident and the K/V shards rotate around
    the ring with `jax.lax.ppermute` (ICI neighbor hops), one hop per step;
  - partial attention per (q-shard, kv-shard) pair merges into running
    online-softmax stats (max m, sum l, unnormalized accumulator) — the same
    math as the flash kernel, lifted one level up to the inter-chip ring;
  - causal masking is global-position index arithmetic: kv shards entirely in
    the future contribute nothing (their block's scores mask to -inf).

Memory per device: O(T/n) activations and one in-flight KV shard — 8k+
contexts at the per-chip cost of 8k/n. Compute per step maps to the MXU via
batched einsums; the ppermute overlaps with the next partial-attention block
under XLA's async collectives.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """Per-device body. q, k, v: (B, T_local, H, Dh) shards."""
    my = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    scale = 1.0 / (d**0.5)
    qf = q.astype(jnp.float32)
    q_pos = my * tl + jnp.arange(tl)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, r):
        o_acc, m, l, kc, vc = carry
        src = (my - r) % axis_size  # owner of the kv shard currently held
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            k_pos = src * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # (B, H, Tl)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])  # rows with no valid keys -> ~0
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        o_new = o_acc * alpha.transpose(0, 2, 1)[..., None] + pv
        # Rotate KV to the next device; the final rotation restores ownership.
        kc, vc = jax.lax.ppermute((kc, vc), axis_name, perm)
        return (o_new, m_new, l_new, kc, vc), None

    o0 = jnp.zeros((b, tl, h, d), jnp.float32)
    m0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    (o_acc, _, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (o_acc / safe_l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    seq_axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
) -> jax.Array:
    """Global-view entry: q, k, v (B, T, H, Dh) with T sharded over seq_axis.

    Nested inside the jitted forward via shard_map; degenerates to a single
    local block (no communication) when the seq axis has size 1.
    """
    axis_size = mesh.shape[seq_axis]
    spec = P(batch_axes, seq_axis, head_axis, None)
    local = functools.partial(
        _ring_local, causal=causal, axis_name=seq_axis, axis_size=axis_size
    )
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
