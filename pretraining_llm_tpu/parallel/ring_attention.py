"""Ring attention: context parallelism over the 'seq' mesh axis.

The reference cannot exceed context 512 — attention materializes full (B,T,T)
scores per head (/root/reference/src/models/attention.py:51-57) and there is
no sequence/context parallelism of any kind (SURVEY §2.2). This module scales
context across chips the TPU way:

  - the sequence dim of q/k/v is sharded over the 'seq' mesh axis
    (`jax.shard_map`);
  - each device keeps its q shard resident and the K/V shards rotate around
    the ring with `jax.lax.ppermute` (ICI neighbor hops), one hop per step;
  - each hop runs *flash-locally*: a blockwise online-softmax scan over KV
    sub-blocks producing unnormalized (o, m, l) partials — never a dense
    (T_local, T_local) fp32 score tensor — and the hop body is
    `jax.checkpoint`ed so autodiff recomputes score blocks instead of
    storing every hop's intermediates;
  - causal hops that contribute nothing are *skipped at runtime* via
    `lax.switch` (mode = none / causal-diagonal / full), not computed and
    masked away;
  - with `layout="zigzag"` the sequence is distributed in balanced
    chunk-pairs: the global sequence splits into 2n chunks and device i owns
    chunks (i, 2n-1-i), so under causal masking every device does the same
    work per hop — a contiguous layout leaves device 0 with one hop of work
    and device n-1 with n (utilization (n+1)/2n). The token permutation is
    applied by the caller (see parallel.zigzag + models.transformer.loss_fn);
    this module only needs the chunk arithmetic.

Memory per device: O(T/n) activations and one in-flight KV shard — 8k+
contexts at the per-chip cost of 8k/n. Compute per hop maps to the MXU via
batched einsums; the ppermute overlaps with the next hop's partial attention
under XLA's async collectives.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pretraining_llm_tpu.utils import jax_compat

NEG_INF = -1e30

# Modes for one (q-chunk, kv-chunk) partial-attention call.
_SKIP, _CAUSAL, _FULL = 0, 1, 2


def _empty_stats(b: int, t: int, h: int, d: int):
    """Identity element of the online-softmax merge: (o=0, m=NEG_INF, l=0)."""
    return (
        jnp.zeros((b, t, h, d), jnp.float32),
        jnp.full((b, h, t), NEG_INF, jnp.float32),
        jnp.zeros((b, h, t), jnp.float32),
    )


def _merge_stats(o, m, l, o2, m2, l2):
    """Online-softmax merge of two unnormalized partials.

    o: (B, t, H, D) fp32 unnormalized accumulators; m, l: (B, H, t) fp32.
    The NEG_INF sentinel makes the algebra self-guarding: exp(NEG_INF - x)
    underflows to exactly 0 for any finite x, and exp(0)=1 when both sides
    are still empty.
    """
    m_new = jnp.maximum(m, m2)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m2 - m_new)
    l_new = l * a1 + l2 * a2
    o_new = o * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[..., None]
    return o_new, m_new, l_new


def _partial_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mode: jax.Array,
    *,
    block_kv: int = 512,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Blockwise partial attention returning unnormalized online-softmax stats.

    q: (B, tq, H, D); k, v: (B, tk, G, D) with G | H — grouped-query
    attention attends each group's H/G query heads against its shared KV head
    directly (never expanding K/V, so the ring's ppermute volume is G/H of
    the MHA cost). ``mode`` is a traced scalar: _SKIP returns empty stats
    without touching the MXU (lax.switch at the call site picks the branch at
    runtime), _CAUSAL masks assuming q and k cover the SAME aligned chunk
    (the only causal case both layouts produce), _FULL attends unmasked.
    Returns (o_unnormalized (B,tq,H,D) fp32, m (B,H,tq) fp32, l (B,H,tq)
    fp32) — stats always in flattened-H layout.
    """
    b, tq, h, d = q.shape
    tk, g = k.shape[1], k.shape[2]
    rep = h // g
    scale = 1.0 / (d**0.5)
    bk = min(block_kv, tk)
    while tk % bk != 0:
        bk //= 2
    nk = tk // bk

    def empty():
        return _empty_stats(b, tq, h, d)

    def attend(causal: bool):
        q_ids = jnp.arange(tq)
        qg = q.reshape(b, tq, g, rep, d)

        def kv_step(carry, inp):
            o, m, l = carry
            j, kb, vb = inp  # kb, vb: (B, bk, G, D)
            s = (
                jnp.einsum(
                    "bqgrd,bkgd->bgrqk", qg, kb, preferred_element_type=jnp.float32
                )
                * scale
            ).reshape(b, h, tq, bk)
            if causal:
                k_pos = j * bk + jnp.arange(bk)
                s = jnp.where((q_ids[:, None] >= k_pos[None, :])[None, None], s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1)
            pv = jnp.einsum(
                "bgrqk,bkgd->bqgrd",
                p.reshape(b, g, rep, tq, bk).astype(v.dtype),
                vb,
                preferred_element_type=jnp.float32,
            ).reshape(b, tq, h, d)
            o = o * alpha.transpose(0, 2, 1)[..., None] + pv
            return (o, m_new, l), None

        kb = k.reshape(b, nk, bk, g, d).swapaxes(0, 1)
        vb = v.reshape(b, nk, bk, g, d).swapaxes(0, 1)
        (o, m, l), _ = jax.lax.scan(kv_step, empty(), (jnp.arange(nk), kb, vb))
        return o, m, l

    return jax.lax.switch(
        mode, [empty, functools.partial(attend, True), functools.partial(attend, False)]
    )


def _chunk_mode(q_chunk: jax.Array, k_chunk: jax.Array) -> jax.Array:
    """Causal relation of two equal-size chunks by global chunk index."""
    return jnp.where(q_chunk == k_chunk, _CAUSAL, jnp.where(q_chunk > k_chunk, _FULL, _SKIP))


def _ring_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    axis_name: str,
    axis_size: int,
    layout: str,
    block_kv: int,
) -> jax.Array:
    """Per-device body. q, k, v: (B, T_local, H, Dh) shards."""
    my = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    n = axis_size
    perm = [(i, (i + 1) % n) for i in range(n)]

    if layout == "zigzag" and causal:
        # Device i holds global chunks (i, 2n-1-i), each of size tl//2,
        # concatenated. Every hop costs every device exactly two
        # half-chunk partials -> balanced ring.
        c = tl // 2
        q_halves = (q[:, :c], q[:, c:])

        def hop(carry, r):
            stats0, stats1, kc, vc = carry
            src = (my - r) % n
            q_chunks = (my, 2 * n - 1 - my)
            k_chunks = (src, 2 * n - 1 - src)
            k_halves = (kc[:, :c], kc[:, c:])
            v_halves = (vc[:, :c], vc[:, c:])
            out = []
            for qi, stats in ((0, stats0), (1, stats1)):
                for ki in (0, 1):
                    mode = _chunk_mode(q_chunks[qi], k_chunks[ki])
                    part = _partial_flash(
                        q_halves[qi], k_halves[ki], v_halves[ki], mode, block_kv=block_kv
                    )
                    stats = _merge_stats(*stats, *part)
                out.append(stats)
            kc, vc = jax.lax.ppermute((kc, vc), axis_name, perm)
            return (out[0], out[1], kc, vc), None

        (s0, s1, _, _), _ = jax.lax.scan(
            jax.checkpoint(hop),
            (_empty_stats(b, c, h, d), _empty_stats(b, c, h, d), k, v),
            jnp.arange(n),
        )
        o = jnp.concatenate([s0[0], s1[0]], axis=1)
        l = jnp.concatenate([s0[2], s1[2]], axis=2)
    else:
        def hop(carry, r):
            stats, kc, vc = carry
            src = (my - r) % n
            mode = _chunk_mode(my, src) if causal else jnp.int32(_FULL)
            part = _partial_flash(q, kc, vc, mode, block_kv=block_kv)
            stats = _merge_stats(*stats, *part)
            kc, vc = jax.lax.ppermute((kc, vc), axis_name, perm)
            return (stats, kc, vc), None

        ((o, _, l), _, _), _ = jax.lax.scan(
            jax.checkpoint(hop), (_empty_stats(b, tl, h, d), k, v), jnp.arange(n)
        )

    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (o / safe_l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_supports_grouped(
    mesh: Optional[Mesh],
    n_heads: int,
    n_kv_heads: int,
    *,
    seq_axis: str = "seq",
    head_axis: Optional[str] = "tensor",
) -> bool:
    """Whether grouped (un-expanded) KV can be fed to the ring dispatch.

    True when ring won't actually run (no seq axis — the naive fallback is
    grouped-native anyway) or when every head-axis shard holds whole KV
    groups. Single source of truth for the caller-side guard in
    models.transformer and the trace-time check in ring_attention.
    """
    if mesh is None or mesh.shape.get(seq_axis, 1) <= 1:
        return True
    tp = mesh.shape.get(head_axis, 1) if head_axis else 1
    return n_kv_heads % tp == 0 or n_kv_heads == n_heads


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    seq_axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    layout: str = "contiguous",
    block_kv: int = 512,
) -> jax.Array:
    """Global-view entry: q (B, T, H, Dh), k/v (B, T, G, Dh) with G | H
    (grouped-query attention rotates only the G KV heads around the ring),
    T sharded over seq_axis.

    Nested inside the jitted forward via shard_map; degenerates to a single
    local block (no communication) when the seq axis has size 1. With
    ``layout="zigzag"`` the caller must have permuted the sequence dim with
    `parallel.zigzag.zigzag_perm` (and fed matching position ids to RoPE /
    learned embeddings) — see models.transformer.loss_fn.
    """
    axis_size = mesh.shape[seq_axis]
    if layout == "zigzag" and (q.shape[1] // axis_size) % 2 != 0:
        raise ValueError("zigzag layout needs an even per-device sequence length")
    h, g = q.shape[2], k.shape[2]
    if h % g != 0:
        raise ValueError(f"kv heads ({g}) must divide query heads ({h})")
    if g < h and not ring_supports_grouped(
        mesh, h, g, seq_axis=seq_axis, head_axis=head_axis
    ):
        # Head-sharded q with unshardable grouped KV would misalign groups
        # inside the manual region; the caller must expand K/V first.
        raise ValueError(
            f"grouped ring attention needs kv heads ({g}) divisible by the "
            f"'{head_axis}' mesh axis; expand K/V to full heads instead"
        )
    spec = P(batch_axes, seq_axis, head_axis, None)
    kv_spec = P(batch_axes, seq_axis, head_axis, None)
    local = functools.partial(
        _ring_local,
        causal=causal,
        axis_name=seq_axis,
        axis_size=axis_size,
        layout=layout,
        block_kv=block_kv,
    )
    return jax_compat.shard_map(
        local, mesh=mesh, in_specs=(spec, kv_spec, kv_spec), out_specs=spec, check_vma=False
    )(q, k, v)
