"""Partition rules: param pytree paths -> PartitionSpecs over the named mesh.

This is the TPU-native replacement for everything the reference delegates to
DDP (`/root/reference/scripts/train_transformer.py:123`): instead of wrapping
the model in a replicating container, each parameter gets a `PartitionSpec`
over the (data, fsdp, tensor, seq) mesh and XLA inserts the collectives.

The rules implement:
  - FSDP/ZeRO-3: every large matrix shards one dimension over 'fsdp'
    (params AND optimizer moments — the spec tree is reused for both).
  - Megatron TP: attention heads, MLP hidden dim and the vocab dim shard over
    'tensor'; the pairing (column-parallel w1/wqkv, row-parallel w2/wo) means
    XLA only needs one all-reduce per residual branch.
  - Norm scales/biases are replicated (tiny).

Because the train step is a single global-view `pjit` program, any spec is
*correct* — the rules only decide layout/performance. Sharding-invariance is
enforced by tests (same loss on a 1-device and an 8-device mesh).
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pretraining_llm_tpu.utils import jax_compat


def _path_names(path: Tuple[Any, ...]) -> Tuple[str, ...]:
    names = []
    for entry in path:
        if hasattr(entry, "key"):
            names.append(str(entry.key))
        elif hasattr(entry, "idx"):
            names.append(str(entry.idx))
        else:
            names.append(str(entry))
    return tuple(names)


def param_pspec(
    path_names: Tuple[str, ...],
    ndim: int,
    pipeline: bool = False,
    *,
    shape: Optional[Tuple[int, ...]] = None,
    tensor_size: int = 1,
) -> P:
    """PartitionSpec for one parameter, keyed on its pytree path.

    Parameters under 'blocks' are stacked with a leading n_layers dim (scanned
    by the model). Without pipelining that dim is never sharded (leading None);
    with ``pipeline=True`` it shards over 'pipe' (stage assignment IS the
    sharding) COMPOSED with the per-weight expert/tensor/fsdp dims — the
    pipeline region is manual over 'pipe' only, so GSPMD keeps handling TP/
    FSDP/EP collectives inside each stage (PP x TP x DP 3-D parallelism).

    ``shape``/``tensor_size`` feed shape-dependent rules: the GQA KV
    projection shards its G head dim over 'tensor' only when G divides
    evenly (see the ``wkv`` rule).
    """
    name = path_names[-1]
    parent = path_names[-2] if len(path_names) >= 2 else ""
    in_blocks = "blocks" in path_names

    if pipeline and in_blocks:
        base = tuple(
            param_pspec(
                path_names, ndim, pipeline=False, shape=shape, tensor_size=tensor_size
            )
        )
        base = base + (None,) * (ndim - len(base))  # P() drops trailing Nones
        return P("pipe", *base[1:])

    def blk(*spec: Optional[str]) -> P:
        return P(None, *spec) if in_blocks else P(*spec)

    if "experts" in path_names:
        # MoE expert FFNs: leading E dim over 'expert', matrices TP+FSDP like
        # their dense counterparts (column-parallel w1, row-parallel w2).
        if name == "w1":  # (E, D, F) or (E, D, 2, F) swiglu
            if ndim - (1 if in_blocks else 0) == 4:
                return blk("expert", "fsdp", None, "tensor")
            return blk("expert", "fsdp", "tensor")
        if name == "b1":  # (E, F) or (E, 2, F)
            if ndim - (1 if in_blocks else 0) == 3:
                return blk("expert", None, "tensor")
            return blk("expert", "tensor")
        if name == "w2":  # (E, F, D)
            return blk("expert", "tensor", "fsdp")
        if name == "b2":  # (E, D)
            return blk("expert", None)
    if name == "router":  # (D, E)
        return blk("fsdp", None)
    if name == "embedding":
        if parent == "tok_embed":
            return P("tensor", "fsdp")  # (V, D): vocab TP, dim FSDP
        return P(None, "fsdp")  # (T, D) learned positions
    if parent in ("ln1", "ln2", "final_norm") or name in ("scale",):
        return blk(*([None] * (ndim - (1 if in_blocks else 0))))
    if name.endswith("_scale") and parent in ("attn", "mlp"):
        # int8 weight scales (models/quantize.py): shaped like their
        # weight with the contracted (input) dims collapsed to 1 — shard
        # the surviving output dims exactly as the weight rule does so a
        # TP rank holds precisely its output channels' scales; singleton
        # input dims replicate.
        base = name[: -len("_scale")]
        if base == "wqkv":  # (1, 3, H, Dh)
            return blk(None, None, "tensor", None)
        if base == "wq":  # (1, H, Dh)
            return blk(None, "tensor", None)
        if base == "wkv":  # (1, 2, G, Dh): follows wkv's G-dim decision
            g = shape[-2] if shape else 0
            if tensor_size > 1 and g % tensor_size == 0:
                return blk(None, None, "tensor", None)
            return blk(None, None, None, None)
        if base == "wo":  # (1, 1, D)
            return blk(None, None, "fsdp")
        if base == "w1":  # (1, F) or (1, 2, F) swiglu
            if ndim - (1 if in_blocks else 0) == 3:
                return blk(None, None, "tensor")
            return blk(None, "tensor")
        if base == "w2":  # (1, D)
            return blk(None, "fsdp")
        # Unknown quantized weight: replicate (any spec is correct).
        return P(*([None] * ndim))
    if name == "wqkv":  # (D, 3, H, Dh): column-parallel over heads
        return blk("fsdp", None, "tensor", None)
    if name == "bqkv":  # (3, H, Dh)
        return blk(None, "tensor", None)
    if name == "wq":  # (D, H, Dh) — GQA query projection
        return blk("fsdp", "tensor", None)
    if name == "bq":  # (H, Dh)
        return blk("tensor", None)
    if name == "wkv":
        # (D, 2, G, Dh) — GQA kv projection. Shard the G head dim over
        # 'tensor' when it divides evenly (each TP rank then computes and
        # stores only its KV heads, and the wkv gradient needs no 'tensor'
        # all-reduce). When G does not divide the tensor axis (e.g. MQA G=1,
        # or G=8 on tp=3), KEEP IT REPLICATED: every rank computes the full
        # (small) KV projection, paying a per-step gradient all-reduce over
        # 'tensor' — the deliberate trade for few-head models (VERDICT r2
        # weak #4 / next #10).
        g = shape[-2] if shape else 0
        if tensor_size > 1 and g % tensor_size == 0:
            return blk("fsdp", None, "tensor", None)
        return blk("fsdp", None, None, None)
    if name == "bkv":  # (2, G, Dh): follows wkv's G-dim decision
        g = shape[-2] if shape else 0
        if tensor_size > 1 and g % tensor_size == 0:
            return blk(None, "tensor", None)
        return blk(None, None, None)
    if name == "wo":  # (H, Dh, D): row-parallel
        return blk("tensor", None, "fsdp")
    if name == "bo":  # (D,)
        return blk(None)
    if name == "w1":  # (D, F) or (D, 2, F) for swiglu: column-parallel
        if ndim - (1 if in_blocks else 0) == 3:
            return blk("fsdp", None, "tensor")
        return blk("fsdp", "tensor")
    if name == "b1":  # (F,) or (2, F)
        if ndim - (1 if in_blocks else 0) == 2:
            return blk(None, "tensor")
        return blk("tensor")
    if name == "w2":  # (F, D): row-parallel
        return blk("tensor", "fsdp")
    if name == "b2":  # (D,)
        return blk(None)
    if name == "kernel" and parent == "lm_head":  # (D, V)
        return P("fsdp", "tensor")
    if name == "bias" and parent == "lm_head":  # (V,)
        return P("tensor")
    if name == "bias":  # norm biases and any other small bias: replicate
        return blk(*([None] * (ndim - (1 if in_blocks else 0))))
    # Fallback: shard nothing rather than guess wrong.
    return P(*([None] * ndim))


def param_pspec_tree(
    params: Any, pipeline: bool = False, *, tensor_size: int = 1
) -> Any:
    """Map a params (or optimizer-moment) pytree to a PartitionSpec pytree.

    ``tensor_size`` is the mesh's 'tensor' axis extent (1 when unknown) —
    it gates shape-dependent rules like the GQA ``wkv`` head sharding.
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: param_pspec(
            _path_names(path),
            getattr(leaf, "ndim", 0),
            pipeline,
            shape=tuple(getattr(leaf, "shape", ())) or None,
            tensor_size=tensor_size,
        ),
        params,
    )


def batch_pspec(sequence_parallel: bool = False) -> P:
    """Spec for (B, T) token batches: batch over data+fsdp, seq over 'seq'."""
    return P(("data", "fsdp"), "seq" if sequence_parallel else None)


def named_sharding_tree(mesh: Mesh, pspec_tree: Any) -> Any:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pspec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# Activation sharding constraints
# ---------------------------------------------------------------------------
# The model is mesh-agnostic; the trainer installs the active mesh here before
# tracing so `constrain` can annotate activations. Outside a mesh context the
# helper is a no-op, which keeps single-device paths (tests, generation)
# mesh-free.

_CURRENT_MESH: Optional[Mesh] = None


@contextlib.contextmanager
def activation_mesh(mesh: Optional[Mesh]) -> Iterator[None]:
    global _CURRENT_MESH
    prev, _CURRENT_MESH = _CURRENT_MESH, mesh
    try:
        yield
    finally:
        _CURRENT_MESH = prev


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH


def constrain(x: jax.Array, *spec: Any) -> jax.Array:
    """Annotate an intermediate with a sharding over the active mesh (no-op
    when no mesh is installed).

    Inside a partial-manual shard_map region (e.g. the pipeline, manual over
    'pipe' only) the trace context carries an AbstractMesh whose manual axes
    differ from the installed Mesh's; the constraint must be built against
    that context mesh or XLA rejects the mismatch. Specs here only ever name
    auto axes (data/fsdp/tensor/seq/expert), so they stay valid either way.
    """
    mesh = _CURRENT_MESH
    if mesh is None:
        return x
    context = jax_compat.get_abstract_mesh()
    target = context if context is not None else mesh
    return jax.lax.with_sharding_constraint(x, NamedSharding(target, P(*spec)))
