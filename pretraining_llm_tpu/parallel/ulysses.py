"""Ulysses-style sequence parallelism: all-to-all seq<->head exchange.

The complement to ring attention (SURVEY §2.2): instead of rotating KV shards
around the ring, one `all_to_all` re-shards activations from
sequence-partitioned to head-partitioned, each device runs *full-sequence*
attention for its subset of heads, and a second `all_to_all` swaps back:

    (B, T/n, H,  D)  --all_to_all-->  (B, T, H/n, D)
          attention over the full sequence, H/n heads
    (B, T, H/n, D)  --all_to_all-->  (B, T/n, H,  D)

Two collectives per attention vs ring's n-1 ppermutes; requires n_heads
divisible by the seq-axis size. Inner attention is the dense/flash path, so
on TPU the Pallas kernel runs unchanged under Ulysses.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, PartitionSpec as P

from pretraining_llm_tpu.utils import jax_compat

from pretraining_llm_tpu.ops.attention import naive_attention


def _ulysses_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    axis_name: str,
    use_flash: bool,
    block_q: int,
    block_kv: int,
) -> jax.Array:
    """Per-device body. q, k, v: (B, T_local, H, Dh) -> same shape."""

    def seq_to_heads(x):
        # (B, T/n, H, D) -> (B, T, H/n, D): split heads, concat sequence.
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    # GQA: q and k/v exchange independently (H/n vs G/n heads per device);
    # the contiguous head split is group-aligned — device j's H/n query
    # heads cover exactly groups [j*G/n, (j+1)*G/n) — so the grouped inner
    # kernels see whole groups. KV moves G/H the all-to-all bytes of MHA.
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from pretraining_llm_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal, block_q=block_q, block_kv=block_kv)
    else:
        out = naive_attention(qh, kh, vh, causal=causal)
    return heads_to_seq(out)


def ulysses_supports_grouped(
    mesh: Optional[Mesh],
    n_heads: int,
    n_kv_heads: int,
    *,
    seq_axis: str = "seq",
    head_axis: Optional[str] = "tensor",
) -> bool:
    """Whether grouped (un-expanded) KV can ride the all-to-all exchange.

    True when ulysses won't run (no seq axis — the naive fallback is
    grouped-native) or when the KV heads split evenly over both the head
    (tensor) shards and the seq-axis all-to-all.
    """
    if mesh is None or mesh.shape.get(seq_axis, 1) <= 1:
        return True
    if n_kv_heads == n_heads:
        return True
    tp = mesh.shape.get(head_axis, 1) if head_axis else 1
    n = mesh.shape[seq_axis]
    return n_kv_heads % tp == 0 and (n_kv_heads // tp) % n == 0


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    seq_axis: str = "seq",
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    use_flash: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
) -> jax.Array:
    """Global-view entry: q (B, T, H, Dh), k/v (B, T, G, Dh) with G | H
    (grouped-query attention exchanges only the G KV heads), T sharded over
    seq_axis."""
    n = mesh.shape[seq_axis]
    h, g = q.shape[2], k.shape[2]
    if h % g != 0:
        raise ValueError(f"kv heads ({g}) must divide query heads ({h})")
    tp = mesh.shape[head_axis] if head_axis else 1
    h_local = h // tp
    if h_local % n != 0:
        raise ValueError(
            f"ulysses needs per-device heads ({h_local}) divisible by seq axis size ({n})"
        )
    if g < h and not ulysses_supports_grouped(
        mesh, h, g, seq_axis=seq_axis, head_axis=head_axis
    ):
        raise ValueError(
            f"grouped ulysses needs kv heads ({g}) divisible by "
            f"{head_axis} x {seq_axis} shards; expand K/V to full heads instead"
        )
    spec = P(batch_axes, seq_axis, head_axis, None)
    local = functools.partial(
        _ulysses_local,
        causal=causal,
        axis_name=seq_axis,
        use_flash=use_flash,
        block_q=block_q,
        block_kv=block_kv,
    )
    return jax_compat.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
