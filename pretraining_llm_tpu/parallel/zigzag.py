"""Zigzag sequence layout for balanced causal context parallelism.

Contiguous sequence sharding under causal masking is pathologically
imbalanced: device 0's tokens attend only to themselves (one ring hop of
work) while device n-1 attends to everything (n hops). The zigzag layout
splits the global sequence into 2n equal chunks and gives device i the pair
(i, 2n-1-i) — one early chunk, one late chunk — so every device does the
same causal work on every hop (see parallel.ring_attention._ring_local).

The permutation is applied to the token stream once, host/trace-side, before
the model: `x_zz = x[:, perm]`. Targets permute with the same index map (y is
the shift-by-1 of x POSITION-wise, so permuting both keeps x_zz[i] -> y_zz[i]
pairs intact), position ids become `perm` itself (RoPE / learned embeddings
then see true global positions), and the mean CE loss is permutation
invariant — nothing needs un-permuting during training.

All functions are pure numpy on static shapes: the permutation is a compile
time constant baked into the jitted step.
"""

from __future__ import annotations

import numpy as np


def zigzag_perm(seq_len: int, n_shards: int) -> np.ndarray:
    """perm[p] = original position of the token at zigzag-layout index p.

    Layout index space: device i owns [i*L, (i+1)*L) with L = seq_len//n,
    holding original chunks i then 2n-1-i, each of size L//2.
    """
    if seq_len % (2 * n_shards) != 0:
        raise ValueError(
            f"seq_len={seq_len} must divide by 2*n_shards={2 * n_shards} for zigzag"
        )
    c = seq_len // (2 * n_shards)
    chunks = np.arange(seq_len).reshape(2 * n_shards, c)
    order = []
    for i in range(n_shards):
        order += [i, 2 * n_shards - 1 - i]
    return chunks[order].reshape(-1)


def inverse_perm(perm: np.ndarray) -> np.ndarray:
    """inv[orig] = zigzag index holding original position `orig`."""
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv
