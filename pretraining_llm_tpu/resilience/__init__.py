"""Resilience subsystem: closes the loop from fault detection to recovery.

The checkpoint/resume machinery (training/checkpoint.py) gives the repo a
*manual* recovery story; this package makes it automatic:

  - anomaly.py   — rolling-window detector over the log-boundary metrics the
                   trainer already fetched (NaN/Inf, loss spike, grad spike);
                   costs nothing on the hot path.
  - rollback.py  — on anomaly: restore the last good checkpoint, advance the
                   data-RNG frontier past the poison window, re-arm with a
                   cooldown and a bounded rollback budget.
  - watchdog.py  — host-side hung-step detector (wedged chip / stuck
                   collective): dumps all thread stacks, attempts an
                   emergency checkpoint, exits EXIT_WEDGED.
  - faults.py    — deterministic config-driven fault injection so every
                   recovery path is exercised in CPU tests.

scripts/supervisor.py is the out-of-process half: a bounded
exponential-backoff relauncher mapping the return codes below to restart
policy. Configured via config.ResilienceConfig; see README "Fault tolerance".

Return-code contract (consumed by scripts/supervisor.py):
  0              clean completion — do not relaunch.
  EXIT_PREEMPTED graceful SIGTERM stop, checkpoint written — relaunch
                 immediately, no backoff (preemptions are routine).
  EXIT_ANOMALY   rollback budget exhausted (or anomaly with no loadable
                 checkpoint) — fatal, needs a human; never relaunched.
  EXIT_WEDGED    watchdog fired on a hung step — relaunch with backoff
                 (counts toward the restart budget).
  anything else  crash — relaunch with backoff, counts toward the budget.
"""

EXIT_CLEAN = 0
EXIT_PREEMPTED = 43
EXIT_ANOMALY = 44
EXIT_WEDGED = 45

from pretraining_llm_tpu.resilience.anomaly import Anomaly, AnomalyDetector  # noqa: E402
from pretraining_llm_tpu.resilience.faults import FaultInjector, parse_faults  # noqa: E402
from pretraining_llm_tpu.resilience.rollback import RollbackManager  # noqa: E402
from pretraining_llm_tpu.resilience.watchdog import StepWatchdog  # noqa: E402

__all__ = [
    "EXIT_CLEAN",
    "EXIT_PREEMPTED",
    "EXIT_ANOMALY",
    "EXIT_WEDGED",
    "Anomaly",
    "AnomalyDetector",
    "FaultInjector",
    "parse_faults",
    "RollbackManager",
    "StepWatchdog",
]
