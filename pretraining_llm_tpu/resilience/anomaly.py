"""Rolling-window anomaly detection over log-boundary training metrics.

The trainer already pays the device->host sync to fetch loss/grad_norm at
every log boundary; observing those floats costs nothing on the hot path —
no extra dispatches, no per-step host work. Three rules:

  nan        loss or grad_norm is NaN/Inf. Always armed (needs no history):
             a non-finite loss never recovers on its own under Adam.
  loss_spike loss > loss_spike_factor * rolling-median(loss). Median, not
             mean: a single poisoned window must not drag its own baseline.
  grad_spike grad_norm > grad_spike_factor * rolling-median(grad_norm).
             The pre-clip global norm is the earliest scalar symptom of a
             bad batch or a divergence — it fires before the loss moves.

The spike rules arm only after ``anomaly_min_history`` finite samples so an
empty baseline cannot flag ordinary early-training noise, and anomalous
samples are never added to the window (a detected spike must not poison the
baseline that detected it).
"""

from __future__ import annotations

import math
import statistics
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional

from pretraining_llm_tpu.config import ResilienceConfig


@dataclass(frozen=True)
class Anomaly:
    kind: str  # "nan" | "loss_spike" | "grad_spike"
    step: int
    value: float
    threshold: float

    def as_event(self) -> Dict[str, Any]:
        return {
            "event": "anomaly_detected",
            "kind": self.kind,
            "step": self.step,
            "value": self.value,
            "threshold": self.threshold,
        }


class AnomalyDetector:
    def __init__(self, cfg: ResilienceConfig) -> None:
        self.cfg = cfg
        self._loss: "deque[float]" = deque(maxlen=cfg.anomaly_window)
        self._grad: "deque[float]" = deque(maxlen=cfg.anomaly_window)

    def reset(self) -> None:
        """Drop all history (call after a rollback: the restored timeline's
        baseline must not include the poisoned window's samples)."""
        self._loss.clear()
        self._grad.clear()

    def observe(self, step: int, metrics: Dict[str, float]) -> Optional[Anomaly]:
        """Feed one log boundary's metrics; returns the anomaly, if any."""
        loss = metrics.get("loss")
        grad = metrics.get("grad_norm")

        for kind_value in (loss, grad):
            if kind_value is not None and not math.isfinite(kind_value):
                return Anomaly("nan", step, float(kind_value), float("nan"))

        anomaly = None
        if loss is not None and len(self._loss) >= self.cfg.anomaly_min_history:
            baseline = statistics.median(self._loss)
            threshold = self.cfg.loss_spike_factor * baseline
            if baseline > 0 and loss > threshold:
                anomaly = Anomaly("loss_spike", step, loss, threshold)
        if (
            anomaly is None
            and grad is not None
            and len(self._grad) >= self.cfg.anomaly_min_history
        ):
            baseline = statistics.median(self._grad)
            threshold = self.cfg.grad_spike_factor * baseline
            if baseline > 0 and grad > threshold:
                anomaly = Anomaly("grad_spike", step, grad, threshold)

        if anomaly is None:
            # Only clean samples extend the baseline.
            if loss is not None:
                self._loss.append(loss)
            if grad is not None:
                self._grad.append(grad)
        return anomaly
