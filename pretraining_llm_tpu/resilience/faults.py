"""Deterministic, config-driven fault injection.

Every recovery path in this package is only trustworthy if it is exercised —
on CPU, in tier-1 tests, not for the first time during a real multi-day run.
The injector fires scripted faults at exact steps so tests (and operators
running drills) can drive the full loop: inject -> detect -> recover.

Plan grammar (``ResilienceConfig.faults``): comma-separated ``kind@step``
entries, e.g. ``"nan@20,sigterm@50"``. Steps are the trainer's step counter
(the fault fires right before that step executes, i.e. after ``step``
completed steps). Kinds:

  nan            poison the params with NaN — the next step's loss is NaN,
                 which the anomaly detector must catch at the next log
                 boundary and roll back.
  sigterm        deliver SIGTERM to this process (preemption drill): the
                 trainer's handler checkpoints and stops at the next log
                 boundary.
  hang           block the host loop indefinitely (wedged-chip drill): the
                 step watchdog must fire, emergency-checkpoint, and exit
                 EXIT_WEDGED.
  ckpt_truncate  truncate one ``.npy`` leaf of the latest checkpoint on disk
                 (torn-write drill): the next restore must skip it and fall
                 back to the previous good step.

Once-only semantics: each plan entry fires at most once per process, and a
resumed run never re-fires an entry at or below its start step — so a
supervisor relaunch after an injected hang resumes from the emergency
checkpoint and runs clean instead of wedging forever.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

FAULT_KINDS = ("nan", "sigterm", "hang", "ckpt_truncate")

# Serving-path fault kinds (frontend/router.py drills). Same philosophy as
# the training kinds — every fleet recovery path must be exercisable on CPU
# in tier-1 — but the trigger is a REQUEST count, not a step count: serving
# has no step clock, and "the Nth submission to a replica" is deterministic
# under a seeded load schedule.
SERVING_FAULT_KINDS = (
    "replica_crash",  # next scheduler turn on the replica raises -> loop dies
    "replica_hang",   # next scheduler turn blocks (wedged-engine drill)
    "slow_window",    # next few turns run with an injected delay (SLO drill)
    "reject_storm",   # next few submissions to the replica are refused busy
    # Silent-corruption kinds (integrity drills): the replica keeps
    # answering — only its OUTPUTS are wrong — so crash/hang detection
    # never fires and the output-integrity sentinel has to catch it.
    "corrupt_kv_page",  # flip a published prefix-cache pool page in place
    "corrupt_weights",  # negate the largest param leaf (bit-rot drill)
    "wrong_token",      # force one out-of-vocab token id into the commit path
    # Process-level kinds (frontend/remote_replica.py drills): executed by
    # the PARENT against a worker process right after the triggering
    # submit is accepted. In-process replicas arm them but nothing
    # consumes the queue — they are no-ops without a process boundary.
    "worker_kill",    # SIGKILL the worker process (hard crash, no cleanup)
    "worker_stall",   # worker stops reading frames but stays alive
    "conn_drop",      # sever the parent<->worker socket; both ends survive
    "partition",      # blackhole the socket: reads hang, writes buffer —
                      # no RST/EOF, so only leases + fencing can detect it
                      # (heal via FleetAction kind="heal" or replica.heal())
    "wire_delay",     # add per-recv delay + jitter (slow WAN link drill)
    # KV-migration corruption (disaggregation drill): flip bytes in the
    # next in-flight kv_page transfer pushed THROUGH the scoped replica's
    # connection (armed at the Nth accepted submission, consumed by the
    # sender side of the next push) — the receiver must detect the digest
    # mismatch, drop the page, and let the request re-prefill. Works for
    # in-process and process fleets alike: the flip happens on the
    # serialized transfer, before (or instead of) the wire.
    "corrupt_kv_migration",
)

# The subset above that needs a process boundary to mean anything.
# corrupt_kv_migration is sender-side (the parent corrupts the serialized
# transfer before pushing), so in process fleets it must ride in the
# PARENT's plan half, like the kill/stall/sever kinds.
PROCESS_SERVING_FAULT_KINDS = (
    "worker_kill", "worker_stall", "conn_drop", "partition", "wire_delay",
    "corrupt_kv_migration",
)

# How long an injected hang blocks the host loop. Effectively forever next to
# any sane watchdog timeout; bounded so a test run without a watchdog still
# terminates eventually instead of needing a kill -9.
_HANG_SECONDS = 3600.0


def parse_faults(spec: str) -> List[Tuple[str, int]]:
    """Parse a fault plan; raises ValueError naming the offending entry."""
    out: List[Tuple[str, int]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, sep, at = entry.partition("@")
        if not sep or not at:
            raise ValueError(
                f"malformed fault entry {entry!r} in {spec!r}: expected kind@step"
            )
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {spec!r}; one of {FAULT_KINDS}"
            )
        try:
            step = int(at)
        except ValueError:
            raise ValueError(
                f"fault step must be an integer in {entry!r} (plan {spec!r})"
            ) from None
        if step < 1:
            raise ValueError(
                f"fault step must be >= 1 in {entry!r} (step 0 is never "
                "reachable: faults fire only past the run's start step)"
            )
        out.append((kind, step))
    if not out:
        raise ValueError(f"empty fault plan {spec!r}")
    return out


class FaultInjector:
    """Fires the parsed plan against a live Trainer, once per entry.

    ``start_step`` is the step the run resumed from: entries at or below it
    are considered spent (they fired in the lineage that produced the
    checkpoint), which is what lets a supervisor relaunch make progress.
    """

    def __init__(
        self, spec: str, *, start_step: int = 0, logger: Any = None, bus: Any = None
    ) -> None:
        self.plan = parse_faults(spec)
        self.start_step = start_step
        self.logger = logger
        self.bus = bus  # optional observability EventBus
        self._fired: set = set()

    def maybe_fire(self, step: int, trainer: Any) -> None:
        for i, (kind, at) in enumerate(self.plan):
            if at != step or at <= self.start_step or i in self._fired:
                continue
            self._fired.add(i)
            if self.logger is not None:
                self.logger.log({"event": "fault_injected", "kind": kind, "step": step})
            if self.bus is not None:
                # Before the action: sigterm/hang never return control here.
                # ("fault", not "kind": kind is emit's event-name parameter.)
                self.bus.emit("fault_injected", step=step, fault=kind)
            getattr(self, f"_fire_{kind}")(trainer)

    # -- actions -------------------------------------------------------

    def _fire_nan(self, trainer: Any) -> None:
        import jax
        import jax.numpy as jnp

        # Multiply every param by NaN in place of the state dict — shardings
        # are preserved (elementwise op), and the very next loss is NaN.
        state = dict(trainer.state)
        state["params"] = jax.tree.map(
            lambda p: p * jnp.float32(float("nan")).astype(p.dtype),
            state["params"],
        )
        trainer.state = state

    def _fire_sigterm(self, trainer: Any) -> None:  # noqa: ARG002 — uniform shape
        os.kill(os.getpid(), signal.SIGTERM)

    def _fire_hang(self, trainer: Any) -> None:  # noqa: ARG002 — uniform shape
        time.sleep(_HANG_SECONDS)

    def _fire_ckpt_truncate(self, trainer: Any) -> None:
        from pretraining_llm_tpu.training import checkpoint as ckpt

        latest = ckpt.latest_checkpoint(trainer.config.train.checkpoint_dir)
        if latest is None:
            return
        truncate_leaf(latest)

    # expose for tests that want to corrupt a checkpoint without a plan
    @staticmethod
    def _noop(trainer: Any) -> None:  # pragma: no cover
        pass


@dataclasses.dataclass(frozen=True)
class ServingFault:
    """One parsed serving-fault entry: fire ``kind`` when a replica sees
    its ``at_submit``-th accepted submission. ``replica=None`` means any
    replica (whichever reaches the count first)."""

    kind: str
    at_submit: int
    replica: Optional[int] = None


def parse_serving_faults(spec: str) -> List[ServingFault]:
    """Parse a serving fault plan: comma-separated ``kind@reqN`` entries,
    optionally replica-scoped as ``kind@reqN:rM`` (e.g.
    ``"replica_crash@req3,slow_window@req1:r0"``). Raises ValueError
    naming the offending entry."""
    out: List[ServingFault] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, sep, at = entry.partition("@")
        if not sep or not at or not at.startswith("req"):
            raise ValueError(
                f"malformed serving fault entry {entry!r} in {spec!r}: "
                f"expected kind@reqN or kind@reqN:rM"
            )
        if kind not in SERVING_FAULT_KINDS:
            raise ValueError(
                f"unknown serving fault kind {kind!r} in {spec!r}; one of "
                f"{SERVING_FAULT_KINDS}"
            )
        at = at[len("req"):]
        at, rsep, rep = at.partition(":")
        replica: Optional[int] = None
        if rsep:
            if not rep.startswith("r"):
                raise ValueError(
                    f"malformed replica scope in {entry!r} (plan {spec!r}): "
                    f"expected :rM"
                )
            try:
                replica = int(rep[1:])
            except ValueError:
                raise ValueError(
                    f"replica index must be an integer in {entry!r} "
                    f"(plan {spec!r})"
                ) from None
            if replica < 0:
                raise ValueError(
                    f"replica index must be >= 0 in {entry!r} (plan {spec!r})"
                )
        try:
            n = int(at)
        except ValueError:
            raise ValueError(
                f"fault request count must be an integer in {entry!r} "
                f"(plan {spec!r})"
            ) from None
        if n < 1:
            raise ValueError(
                f"fault request count must be >= 1 in {entry!r} "
                f"(plan {spec!r})"
            )
        out.append(ServingFault(kind, n, replica))
    if not out:
        raise ValueError(f"empty serving fault plan {spec!r}")
    return out


def split_serving_plan(spec: str) -> Tuple[str, str]:
    """Split one plan string into (engine_plan, process_plan) — both in
    the same ``kind@reqN[:rM]`` grammar, either possibly "". Process-mode
    serving needs this because the two halves run in different
    processes: engine kinds ride in each worker's spec and fire inside
    its scheduler, while process kinds stay with the parent-side
    injector that can actually kill/stall/sever a worker. Keeping one
    user-facing plan string (``--serving_faults``) with both vocabularies
    means drills read the same regardless of replica mode."""
    parse_serving_faults(spec)  # validate once; errors name the entry
    engine: List[str] = []
    process: List[str] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind = entry.partition("@")[0]
        (process if kind in PROCESS_SERVING_FAULT_KINDS else engine).append(
            entry
        )
    return ",".join(engine), ",".join(process)


class InjectedFault(RuntimeError):
    """Raised inside a replica's scheduler turn by ``replica_crash`` — the
    engine loop's failure path treats it like any real engine error."""


class ServingFaultInjector:
    """Fires a parsed serving plan against a fleet of replicas, once per
    entry. Shared across the fleet: each Replica reports its accepted
    submissions via ``on_submit`` (router/gateway threads) which ARMS the
    matching entries; the armed action then fires at the replica's next
    scheduler turn via the ``wrap_tick`` shim (loop thread) or, for
    ``reject_storm``, at its next submissions via ``should_reject``.

    Arming at submit + firing at the turn boundary keeps the drill honest:
    a crash lands while the triggering request (at least) is in flight, so
    the redrive path — not just fresh routing — is what recovers it.
    """

    def __init__(
        self,
        spec: str,
        *,
        bus: Any = None,
        slow_ticks: int = 4,
        slow_s: float = 0.05,
        storm_rejects: int = 4,
    ) -> None:
        self.plan = parse_serving_faults(spec)
        self.bus = bus
        self.slow_ticks = int(slow_ticks)
        self.slow_s = float(slow_s)
        self.storm_rejects = int(storm_rejects)
        self._lock = threading.Lock()
        self._fired: set = set()
        self._armed: Dict[int, List[str]] = {}   # replica -> crash/hang queue
        self._slow: Dict[int, int] = {}          # replica -> slowed ticks left
        self._storm: Dict[int, int] = {}         # replica -> rejects left
        self._corrupt: Dict[int, List[str]] = {}  # replica -> corruption queue
        self._process: Dict[int, List[str]] = {}  # replica -> process faults
        self._kv_corrupt: Dict[int, int] = {}    # replica -> armed kv flips
        self._engines: Dict[int, Any] = {}       # replica -> live engine handle

    def attach_engine(self, replica: int, engine: Any) -> None:
        """Give the injector the replica's LIVE engine (called from
        Replica._launch_locked on every launch/relaunch): the corruption
        kinds mutate engine state in place, which crash/hang never needed.
        A relaunch re-attaches, so a quarantined replica's fresh engine is
        the one any still-armed entries would hit."""
        with self._lock:
            self._engines[replica] = engine

    def on_submit(self, replica: int, nth_submit: int) -> None:
        """Called by a Replica after accepting its ``nth_submit``-th
        request; arms any plan entries that trigger there."""
        with self._lock:
            for i, f in enumerate(self.plan):
                if (
                    i in self._fired
                    or f.at_submit != nth_submit
                    or (f.replica is not None and f.replica != replica)
                ):
                    continue
                self._fired.add(i)
                if self.bus is not None:
                    self.bus.emit(
                        "fault_injected", fault=f.kind, replica=replica,
                        req_n=nth_submit,
                    )
                if f.kind in ("replica_crash", "replica_hang"):
                    self._armed.setdefault(replica, []).append(f.kind)
                elif f.kind == "corrupt_kv_migration":
                    # Sender-side: consumed by the next kv-page push
                    # through this replica (take_kv_corruption), not by
                    # the generic process-fault drain.
                    self._kv_corrupt[replica] = (
                        self._kv_corrupt.get(replica, 0) + 1
                    )
                elif f.kind in PROCESS_SERVING_FAULT_KINDS:
                    self._process.setdefault(replica, []).append(f.kind)
                elif f.kind in (
                    "corrupt_kv_page", "corrupt_weights", "wrong_token"
                ):
                    self._corrupt.setdefault(replica, []).append(f.kind)
                elif f.kind == "slow_window":
                    self._slow[replica] = (
                        self._slow.get(replica, 0) + self.slow_ticks
                    )
                else:  # reject_storm
                    self._storm[replica] = (
                        self._storm.get(replica, 0) + self.storm_rejects
                    )

    def should_reject(self, replica: int) -> bool:
        """Consume one reject_storm token for this replica (submit path)."""
        with self._lock:
            left = self._storm.get(replica, 0)
            if left <= 0:
                return False
            self._storm[replica] = left - 1
            return True

    def take_process_faults(self, replica: int) -> List[str]:
        """Drain the armed process-level faults for ``replica``. Called
        by RemoteReplica right after the triggering submit's reply, on
        the submitting thread — the parent is the only party that can
        kill/stall/sever a worker process. In-process fleets never call
        this, which is exactly why process kinds are no-ops there."""
        with self._lock:
            return self._process.pop(replica, [])

    def take_kv_corruption(self, replica: int) -> int:
        """Drain the armed ``corrupt_kv_migration`` count for ``replica``.
        Called by the kv-page push path (Replica.push_kv_pages /
        RemoteReplica.push_kv_pages) right before serializing onto the
        wire; a nonzero return means: flip bytes in this transfer."""
        with self._lock:
            return self._kv_corrupt.pop(replica, 0)

    def wrap_tick(self, replica: int, tick: Any) -> Any:
        """Shim for ``engine.pipeline_tick``: checks armed actions before
        delegating. Installed as an instance attribute on the engine (the
        same shadowing trick the throttle tests use), so the engine class
        stays untouched."""

        def _tick(*a: Any, **kw: Any) -> Any:
            with self._lock:
                armed = self._armed.get(replica, [])
                action = armed.pop(0) if armed else None
                slow = self._slow.get(replica, 0)
                if action is None and slow > 0:
                    self._slow[replica] = slow - 1
                corrupt = self._corrupt.get(replica, [])
                corruption = corrupt.pop(0) if corrupt else None
                engine = self._engines.get(replica)
            if corruption is not None:
                # Fired on the loop thread (the engine's owner), BEFORE the
                # turn, so the very next dispatched window runs against the
                # corrupted state. A corruption with no target yet (e.g. a
                # KV flip before anything is cached) stays armed.
                if not self._fire_corruption(corruption, replica, engine):
                    with self._lock:
                        self._corrupt.setdefault(replica, []).insert(
                            0, corruption
                        )
            if action == "replica_crash":
                raise InjectedFault(f"injected replica_crash on replica {replica}")
            if action == "replica_hang":
                time.sleep(_HANG_SECONDS)
            elif action is None and slow > 0:
                time.sleep(self.slow_s)
            return tick(*a, **kw)

        return _tick

    # -- corruption actions (integrity drills) -------------------------

    def _fire_corruption(
        self, kind: str, replica: int, engine: Any
    ) -> bool:
        """Mutate the attached engine's state in place; returns False when
        the fault has no target yet and should stay armed."""
        if engine is None:
            return False
        fired = getattr(self, f"_fire_{kind}")(engine)
        if fired and self.bus is not None:
            self.bus.emit("fault_fired", fault=kind, replica=replica)
        return fired

    @staticmethod
    def _fire_corrupt_kv_page(engine: Any) -> bool:
        """Overwrite one PUBLISHED prefix-cache pool block with garbage —
        the silent version of a DMA bit-flip on a shared page. Targets the
        lowest cached block id so the drill is deterministic; with no
        cache (or nothing published yet) it waits for one.

        Poisons EVERY pool leaf at that block: on an exact pool that is
        K/V; on a quantized pool (kv_cache_dtype=int8 / serving.quantize=
        int8-kv) it flips both the int8 code pages AND their float scale
        leaves, so the drill exercises the same detectors — kv_checksum
        digests (which cover codes and scales alike, see kv_block_digest)
        verify-on-acquire and golden-probe divergence — on the quantized
        byte layout."""
        import jax
        import jax.numpy as jnp

        cache = getattr(engine, "prefix_cache", None)
        if cache is None:
            return False
        cached = cache.cached_block_ids()
        if not cached:
            return False
        block = cached[0]

        def _poison(leaf):
            idx = (slice(None), block) if leaf.ndim >= 5 else (block,)
            page = leaf[idx]
            if jnp.issubdtype(page.dtype, jnp.floating):
                # Exact K/V bytes, or quantization SCALES: 100.0 blows the
                # dequantized magnitudes far outside any trained range.
                bad = jnp.full_like(page, 100.0)
            else:
                # int8 quantized codes: a constant nonzero page (sign-flip
                # would leave an all-zero page — and its digest — intact).
                bad = jnp.full_like(page, 101)
            return leaf.at[idx].set(bad)

        engine.pools = jax.tree_util.tree_map(_poison, engine.pools)
        return True

    @staticmethod
    def _fire_corrupt_weights(engine: Any) -> bool:
        """Negate the largest floating param leaf (the embedding table on
        any realistic config): every forward pass afterwards is wrong, but
        nothing crashes — exactly the failure mode golden probes and the
        weight fingerprint exist to catch."""
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(engine.params)
        target = None
        for i, leaf in enumerate(leaves):
            if not (
                hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.floating)
            ):
                continue
            if target is None or leaf.size > leaves[target].size:
                target = i
        if target is None:
            return False
        leaves[target] = leaves[target] * -1
        engine.params = jax.tree_util.tree_unflatten(treedef, leaves)
        return True

    @staticmethod
    def _fire_wrong_token(engine: Any) -> bool:
        """Force the next committed token id out of vocab range by
        shadowing ``engine._consume_tokens`` (one shot, then restored):
        proves the reap-time sanity guard end-to-end — the guard must
        raise before the garbage id reaches any client stream."""
        import numpy as np

        orig = engine._consume_tokens

        def _bad(req, row, toks, advance_seq=True, **kw):
            # **kw forwards commit-path extras (e.g. the fused-sampling
            # logprob sliver) untouched — only the token ids are forged.
            if len(toks) == 0:
                return orig(req, row, toks, advance_seq, **kw)
            engine._consume_tokens = orig
            bad = np.array(
                [engine.cfg.vocab_size + 7] + [int(t) for t in toks[1:]],
                dtype=np.int64,
            )
            return orig(req, row, bad, advance_seq, **kw)

        engine._consume_tokens = _bad
        return True


def truncate_leaf(ckpt_path: str, leaf: Optional[str] = None) -> Optional[str]:
    """Truncate one ``.npy`` leaf file in a checkpoint dir to half its size
    (a torn write). Returns the damaged filename, or None if no leaf found."""
    names = sorted(n for n in os.listdir(ckpt_path) if n.endswith(".npy"))
    if leaf is not None:
        names = [n for n in names if n.startswith(leaf)]
    if not names:
        return None
    target = os.path.join(ckpt_path, names[0])
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(1, size // 2))
    return names[0]
