"""Deterministic, config-driven fault injection.

Every recovery path in this package is only trustworthy if it is exercised —
on CPU, in tier-1 tests, not for the first time during a real multi-day run.
The injector fires scripted faults at exact steps so tests (and operators
running drills) can drive the full loop: inject -> detect -> recover.

Plan grammar (``ResilienceConfig.faults``): comma-separated ``kind@step``
entries, e.g. ``"nan@20,sigterm@50"``. Steps are the trainer's step counter
(the fault fires right before that step executes, i.e. after ``step``
completed steps). Kinds:

  nan            poison the params with NaN — the next step's loss is NaN,
                 which the anomaly detector must catch at the next log
                 boundary and roll back.
  sigterm        deliver SIGTERM to this process (preemption drill): the
                 trainer's handler checkpoints and stops at the next log
                 boundary.
  hang           block the host loop indefinitely (wedged-chip drill): the
                 step watchdog must fire, emergency-checkpoint, and exit
                 EXIT_WEDGED.
  ckpt_truncate  truncate one ``.npy`` leaf of the latest checkpoint on disk
                 (torn-write drill): the next restore must skip it and fall
                 back to the previous good step.

Once-only semantics: each plan entry fires at most once per process, and a
resumed run never re-fires an entry at or below its start step — so a
supervisor relaunch after an injected hang resumes from the emergency
checkpoint and runs clean instead of wedging forever.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, List, Optional, Tuple

FAULT_KINDS = ("nan", "sigterm", "hang", "ckpt_truncate")

# How long an injected hang blocks the host loop. Effectively forever next to
# any sane watchdog timeout; bounded so a test run without a watchdog still
# terminates eventually instead of needing a kill -9.
_HANG_SECONDS = 3600.0


def parse_faults(spec: str) -> List[Tuple[str, int]]:
    """Parse a fault plan; raises ValueError naming the offending entry."""
    out: List[Tuple[str, int]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, sep, at = entry.partition("@")
        if not sep or not at:
            raise ValueError(
                f"malformed fault entry {entry!r} in {spec!r}: expected kind@step"
            )
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {spec!r}; one of {FAULT_KINDS}"
            )
        try:
            step = int(at)
        except ValueError:
            raise ValueError(
                f"fault step must be an integer in {entry!r} (plan {spec!r})"
            ) from None
        if step < 1:
            raise ValueError(
                f"fault step must be >= 1 in {entry!r} (step 0 is never "
                "reachable: faults fire only past the run's start step)"
            )
        out.append((kind, step))
    if not out:
        raise ValueError(f"empty fault plan {spec!r}")
    return out


class FaultInjector:
    """Fires the parsed plan against a live Trainer, once per entry.

    ``start_step`` is the step the run resumed from: entries at or below it
    are considered spent (they fired in the lineage that produced the
    checkpoint), which is what lets a supervisor relaunch make progress.
    """

    def __init__(
        self, spec: str, *, start_step: int = 0, logger: Any = None, bus: Any = None
    ) -> None:
        self.plan = parse_faults(spec)
        self.start_step = start_step
        self.logger = logger
        self.bus = bus  # optional observability EventBus
        self._fired: set = set()

    def maybe_fire(self, step: int, trainer: Any) -> None:
        for i, (kind, at) in enumerate(self.plan):
            if at != step or at <= self.start_step or i in self._fired:
                continue
            self._fired.add(i)
            if self.logger is not None:
                self.logger.log({"event": "fault_injected", "kind": kind, "step": step})
            if self.bus is not None:
                # Before the action: sigterm/hang never return control here.
                # ("fault", not "kind": kind is emit's event-name parameter.)
                self.bus.emit("fault_injected", step=step, fault=kind)
            getattr(self, f"_fire_{kind}")(trainer)

    # -- actions -------------------------------------------------------

    def _fire_nan(self, trainer: Any) -> None:
        import jax
        import jax.numpy as jnp

        # Multiply every param by NaN in place of the state dict — shardings
        # are preserved (elementwise op), and the very next loss is NaN.
        state = dict(trainer.state)
        state["params"] = jax.tree.map(
            lambda p: p * jnp.float32(float("nan")).astype(p.dtype),
            state["params"],
        )
        trainer.state = state

    def _fire_sigterm(self, trainer: Any) -> None:  # noqa: ARG002 — uniform shape
        os.kill(os.getpid(), signal.SIGTERM)

    def _fire_hang(self, trainer: Any) -> None:  # noqa: ARG002 — uniform shape
        time.sleep(_HANG_SECONDS)

    def _fire_ckpt_truncate(self, trainer: Any) -> None:
        from pretraining_llm_tpu.training import checkpoint as ckpt

        latest = ckpt.latest_checkpoint(trainer.config.train.checkpoint_dir)
        if latest is None:
            return
        truncate_leaf(latest)

    # expose for tests that want to corrupt a checkpoint without a plan
    @staticmethod
    def _noop(trainer: Any) -> None:  # pragma: no cover
        pass


def truncate_leaf(ckpt_path: str, leaf: Optional[str] = None) -> Optional[str]:
    """Truncate one ``.npy`` leaf file in a checkpoint dir to half its size
    (a torn write). Returns the damaged filename, or None if no leaf found."""
    names = sorted(n for n in os.listdir(ckpt_path) if n.endswith(".npy"))
    if leaf is not None:
        names = [n for n in names if n.startswith(leaf)]
    if not names:
        return None
    target = os.path.join(ckpt_path, names[0])
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(1, size // 2))
    return names[0]
