"""Output-integrity primitives: golden probes, fingerprints, digests.

PR 9 made the XLA gather fallback the single source of numeric truth for
the ragged Pallas kernel at TEST time. This module extends that idea to
LIVE serving: a replica that still answers health checks can nonetheless
be silently wrong — a bit-flipped weight shard, a corrupted shared
prefix-cache page, a miscompiled kernel — and nothing in the crash/hang
fleet machinery (PR 8) notices, because the loop keeps turning. The
detectors here all compare CURRENT state against something pinned while
the replica was known-good:

  golden probes       seeded prompts whose greedy continuations are pinned
                      once at startup from the reference ``generate`` path
                      (the same oracle every bit-identity test uses); the
                      router re-runs them per replica through the normal
                      admission lane and any token mismatch is proof of
                      divergence, whatever the root cause;
  weight fingerprint  one cheap device-side reduction over the param tree,
                      pinned at loop start and recomputed on an interval —
                      catches in-place weight corruption without hashing
                      gigabytes host-side;
  KV page digests     blake2b over a pool block's bytes, recorded when the
                      block is published into the cross-request prefix
                      cache and re-verified on acquire — a corrupted
                      shared page re-prefills privately instead of
                      poisoning every future hit;
  array digests       content checksums for checkpoint leaves, verified on
                      restore like the existing torn/truncated fallback.

Everything is gated off by default and costs nothing when off; the probe
comparison itself happens host-side on already-materialized token lists.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


class IntegrityError(RuntimeError):
    """A detector fired: observed state contradicts pinned reference state."""


# ---------------------------------------------------------------------------
# Golden probes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GoldenProbe:
    """One pinned probe: a prompt and its reference greedy continuation."""

    prompt: Tuple[int, ...]
    expected: Tuple[int, ...]


def probe_prompts(
    n_probes: int, probe_len: int, vocab_size: int, seed: int = 20260805
) -> List[List[int]]:
    """Deterministic probe prompts. Every probe shares the first
    ``probe_len - 1`` tokens and differs in its LAST token only: with the
    prefix cache on, probe #0 publishes the shared prefix blocks and every
    later probe re-acquires them — so the probes continuously exercise the
    cached-KV read path and a corrupted shared page shows up as probe
    divergence, not just as a checksum event."""
    if n_probes < 1:
        raise ValueError(f"n_probes must be >= 1, got {n_probes}")
    if probe_len < 2:
        raise ValueError(f"probe_len must be >= 2, got {probe_len}")
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, vocab_size, size=probe_len - 1).tolist()
    return [
        prefix + [int(rng.randint(0, vocab_size))] for _ in range(n_probes)
    ]


def build_probe_set(
    params: Any,
    cfg: Any,
    *,
    n_probes: int = 2,
    probe_len: int = 9,
    max_new: int = 4,
    seed: int = 20260805,
) -> List[GoldenProbe]:
    """Pin the probe set: greedy continuations from the reference
    ``generate`` path (batch-1 fixed-count decode — deliberately NOT the
    serving engine, so the pin is independent of the machinery it later
    judges). Call once at startup, before traffic."""
    import jax
    import jax.numpy as jnp

    from pretraining_llm_tpu.generation.generate import generate

    probes = []
    for prompt in probe_prompts(n_probes, probe_len, cfg.vocab_size, seed):
        toks = generate(
            params, cfg, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.key(7), temperature=0.0,
        )
        probes.append(
            GoldenProbe(tuple(prompt), tuple(np.asarray(toks)[0].tolist()))
        )
    return probes


# ---------------------------------------------------------------------------
# Weight fingerprint
# ---------------------------------------------------------------------------


def weight_fingerprint(params: Any) -> float:
    """One device-side reduction over every floating AND integer leaf ->
    one scalar pull. Position-weighted sums (not abs) so both value
    corruption and leaf swaps move it; float32 accumulation is
    deterministic for a fixed tree on a fixed platform, which is all the
    pinned-vs-current and fleet-wide comparisons need. Integer leaves are
    the int8 codes of quantized serving params (models/quantize.py) —
    excluding them would leave most of a quantized replica's weight bytes
    outside the detector. Cost: one fused reduce + ONE host sync — cheap
    enough for an interval loop, never on the per-token path."""
    import jax
    import jax.numpy as jnp

    leaves = [
        leaf for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "dtype")
        and (
            jnp.issubdtype(leaf.dtype, jnp.floating)
            or jnp.issubdtype(leaf.dtype, jnp.integer)
        )
    ]
    total = _fingerprint_reduce(leaves)
    return float(np.asarray(total))


_REDUCE_JIT = None  # lazily-built module-level jit: one trace per tree shape


def _fingerprint_reduce(leaves: Sequence[Any]):
    global _REDUCE_JIT
    import jax
    import jax.numpy as jnp

    if _REDUCE_JIT is None:

        def _reduce(ls):
            acc = jnp.float32(0.0)
            for i, leaf in enumerate(ls):
                acc = acc + jnp.float32(i + 1) * jnp.sum(
                    leaf.astype(jnp.float32)
                )
            return acc

        _REDUCE_JIT = jax.jit(_reduce)
    return _REDUCE_JIT(list(leaves))


# ---------------------------------------------------------------------------
# KV page + array digests
# ---------------------------------------------------------------------------


def _block_axis(leaf: Any) -> int:
    # Stacked pools are (L, n_blocks, block_size, ...); the per-layer
    # container's leaves are (n_blocks, block_size, ...). See
    # make_paged_kv_pool — n_blocks is the only axis a block id indexes.
    return 1 if getattr(leaf, "ndim", 0) >= 5 else 0


def kv_block_digest(pools: Any, block: int) -> str:
    """Content digest of ONE pool block across every pool leaf (K, V, and
    quantization scales alike). This is a device pull per leaf, so callers
    gate it behind the ``kv_checksum`` knob — it runs at publish/acquire
    boundaries, never inside the decode window."""
    import jax

    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree_util.tree_leaves(pools):
        if _block_axis(leaf) == 1:
            page = leaf[:, block]
        else:
            page = leaf[block]
        arr = np.ascontiguousarray(jax.device_get(page))
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def array_digest(arr: np.ndarray) -> str:
    """Content checksum for a checkpoint leaf: dtype + shape + bytes, so a
    silently truncated or bit-flipped ``.npy`` cannot verify."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def verify_array(arr: np.ndarray, expected: Optional[str], name: str) -> None:
    """Raise IntegrityError unless ``arr`` digests to ``expected``.
    ``expected=None`` (a pre-checksum checkpoint) verifies vacuously —
    old checkpoints stay restorable."""
    if expected is None:
        return
    got = array_digest(arr)
    if got != expected:
        raise IntegrityError(
            f"checksum mismatch for {name}: expected {expected}, got {got} "
            f"(corrupted checkpoint leaf)"
        )
