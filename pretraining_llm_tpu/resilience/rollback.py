"""Automatic checkpoint rollback: anomaly -> last good state -> fresh data.

The recovery policy that turns detection (anomaly.py) into continued
training without a human in the loop:

  restore   the newest loadable checkpoint (checkpoint.restore_latest_synced
            — it GC's partial tmp dirs, digs past truncated/torn step dirs,
            and on multi-host runs makes every process adopt the same step).
            If an anomaly recurs before any NEW checkpoint lands — i.e. the
            candidate equals the step we just restored — that checkpoint is
            itself suspect (poison crossed a save boundary), so the retry
            digs strictly earlier.
  skip      the data-RNG frontier is advanced past the poison window: the
            (anomaly_step - restored_step) batches the restored timeline
            would replay, plus ``skip_batches`` extra margin. A loss spike
            caused by a bad data region must not be replayed verbatim.
  re-arm    the detector's history is cleared (the poisoned samples must not
            seed the new baseline) and further anomalies are suppressed for
            ``cooldown_steps`` while it rebuilds.
  budget    at most ``rollback_budget`` rollbacks per train() call; the next
            anomaly past the budget ends the run with EXIT_ANOMALY — an
            anomaly that survives N rollbacks is systemic, and looping on
            it would burn the cluster forever.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from pretraining_llm_tpu.config import ResilienceConfig
from pretraining_llm_tpu.resilience.anomaly import Anomaly


class RollbackManager:
    """Decides and executes rollbacks against a live Trainer.

    ``handle`` returns one of:
      "rolled_back"    state restored, data skipped; the caller continues
                       the loop from the returned-to step.
      "suppressed"     anomaly inside the post-rollback cooldown; ignored.
      "exhausted"      rollback budget spent; the caller must stop.
      "no_checkpoint"  nothing loadable to restore; the caller must stop.
    """

    def __init__(self, cfg: ResilienceConfig, logger: Any = None, bus: Any = None) -> None:
        self.cfg = cfg
        self.logger = logger
        # Optional observability EventBus: an executed rollback is a run
        # event (its dur_s lands in the goodput "restore" bucket).
        self.bus = bus
        self.used = 0
        self._cooldown_until = -1
        self._last_restored: Optional[int] = None

    def _log(self, record: dict) -> None:
        if self.logger is not None:
            self.logger.log(record)

    def handle(self, trainer: Any, anomaly: Anomaly) -> str:
        step = anomaly.step
        t0 = time.perf_counter()
        if step < self._cooldown_until:
            self._log({
                "event": "anomaly_suppressed",
                "kind": anomaly.kind,
                "step": step,
                "cooldown_until": self._cooldown_until,
            })
            return "suppressed"
        if self.used >= self.cfg.rollback_budget:
            self._log({
                "event": "rollback_budget_exhausted",
                "step": step,
                "used": self.used,
                "budget": self.cfg.rollback_budget,
            })
            return "exhausted"

        # An in-flight async save may be writing the poisoned state; let it
        # land (and surface its errors) before we pick a restore target. The
        # same-step deepening below covers the poisoned-checkpoint case.
        try:
            trainer.join_pending_save()
        except RuntimeError:
            self._log({"event": "async_checkpoint_failed", "step": step})
        trainer._drop_feed()

        from pretraining_llm_tpu.training import checkpoint as ckpt

        directory = trainer.config.train.checkpoint_dir
        template = trainer._state_template()
        # Same-candidate rule: if the newest checkpoint is the one we already
        # restored and the anomaly came back, restoring it again is futile —
        # the poison predates it. Dig strictly earlier.
        newest = max(ckpt._list_steps(directory), default=None)
        before = newest + 1 if newest is not None else None
        if newest is not None and newest == self._last_restored:
            before = newest
        # _synced: on multi-host runs every process must restore the SAME
        # step — a host-local load failure digging deeper on one host
        # alone would leave divergent params/step/data-RNG and deadlock
        # at the next collective.
        restored = ckpt.restore_latest_synced(
            directory,
            template,
            before_step=before,
            loader=trainer._checkpoint_loader,
            on_skip=lambda path, e: self._log({
                "event": "checkpoint_skipped",
                "path": path,
                "error": repr(e)[:200],
            }),
        )
        if restored is None:
            self._log({"event": "rollback_no_checkpoint", "step": step})
            return "no_checkpoint"

        state, extra, restored_step = restored
        trainer._adopt_restored(state, extra)
        skip = max(0, step - restored_step) + self.cfg.skip_batches
        trainer._skip_batches(skip)

        self.used += 1
        self._last_restored = restored_step
        self._cooldown_until = restored_step + self.cfg.cooldown_steps
        self._log({
            "event": "rollback",
            "kind": anomaly.kind,
            "from_step": step,
            "to_step": restored_step,
            "skipped_batches": skip,
            "budget_left": self.cfg.rollback_budget - self.used,
        })
        if self.bus is not None:
            # One event covers the whole recovery (restore included) — the
            # trainer's resume path owns "ckpt_restore"; emitting both here
            # would double-count the restore seconds in goodput.
            self.bus.emit(
                "rollback",
                step=step,
                from_step=step,
                to_step=restored_step,
                skipped_batches=skip,
                anomaly=anomaly.kind,
                dur_s=time.perf_counter() - t0,
            )
        return "rolled_back"

    @property
    def last_restored(self) -> Optional[int]:
        return self._last_restored
