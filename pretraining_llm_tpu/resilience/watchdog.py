"""Host-side hung-step watchdog.

The failure class the round-5 campaign actually hit: a chip wedge (the
``save_attn_res`` Pallas hang) blocks the host thread inside a device sync
forever — no exception, no SIGTERM, nothing for the trainer's failure path
to catch. The only recovery is out-of-process: a watchdog thread that
notices steps stopped completing, preserves what it can, and exits with a
distinct return code so the supervisor knows to relaunch.

On timeout the watchdog, in order:
  1. dumps every thread's stack to stderr (faulthandler — the wedge's
     location is the single most valuable debugging artifact);
  2. runs the ``on_timeout`` callback (the trainer passes its emergency
     checkpoint save) under a try/except — best-effort by construction,
     since the main thread may hold arbitrary locks;
  3. ``os._exit(EXIT_WEDGED)``. ``_exit``, not ``sys.exit``: a raised
     SystemExit in a daemon thread is swallowed, and atexit handlers may
     themselves block on the wedged device.

Arm it AFTER the first completed step so compile time never counts against
the timeout, then call ``heartbeat()`` every completed step. Bracket
known-long off-path work (eval, checkpoint saves, rollback restores) with
``pause()``/``resume()`` — the timeout budgets a step, not a save.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Any, Callable, Optional

from pretraining_llm_tpu.resilience import EXIT_WEDGED


class StepWatchdog:
    def __init__(
        self,
        timeout_s: float,
        *,
        on_timeout: Optional[Callable[[], None]] = None,
        logger: Any = None,
        bus: Any = None,
        exit_code: int = EXIT_WEDGED,
        exit_fn: Callable[[int], None] = os._exit,
    ) -> None:
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self.on_timeout = on_timeout
        self.logger = logger
        # Optional observability EventBus. The wedge event must be emitted
        # BEFORE os._exit (which bypasses finally/atexit) or it never lands.
        self.bus = bus
        self.exit_code = exit_code
        self._exit = exit_fn  # injectable so tests can observe instead of die
        self._last_beat: Optional[float] = None  # None = not armed yet
        self._was_armed_at_pause = False
        self._stopped = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "StepWatchdog":
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def heartbeat(self) -> None:
        """A step completed. First call arms the watchdog."""
        self._last_beat = time.monotonic()

    def pause(self) -> None:
        """Disarm while known-long off-path host work runs on the main
        thread — eval, a checkpoint save, a rollback restore. The timeout
        budgets a training STEP; charging it for a multi-minute save or
        eval falsely fires EXIT_WEDGED on a healthy run (emergency-
        checkpointing, killing the process, and burning the supervisor's
        restart budget). ``resume`` re-arms with a fresh beat iff the
        watchdog was armed when paused, so compile time stays excluded."""
        self._was_armed_at_pause = self._last_beat is not None
        self._last_beat = None

    def resume(self) -> None:
        if self._was_armed_at_pause:
            self._was_armed_at_pause = False
            self.heartbeat()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    @property
    def fired(self) -> bool:
        return self._fired

    # -- monitor thread ------------------------------------------------

    def _run(self) -> None:
        poll = min(self.timeout_s / 4.0, 1.0)
        while not self._stopped.wait(poll):
            if self._last_beat is None:
                continue  # not armed: still compiling / first step in flight
            stalled = time.monotonic() - self._last_beat
            if stalled > self.timeout_s:
                self._fire(stalled)
                return

    def _fire(self, stalled: float) -> None:
        self._fired = True
        if self.bus is not None:
            try:
                self.bus.emit(
                    "wedge",
                    stalled_s=round(stalled, 2),
                    timeout_s=self.timeout_s,
                )
            except Exception:
                pass
        if self.logger is not None:
            try:
                self.logger.log({
                    "event": "watchdog_timeout",
                    "stalled_s": round(stalled, 2),
                    "timeout_s": self.timeout_s,
                })
            except Exception:
                pass
        try:
            sys.stderr.write(
                f"\n=== step watchdog: no completed step in {stalled:.1f}s "
                f"(timeout {self.timeout_s:.1f}s); all thread stacks: ===\n"
            )
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            sys.stderr.flush()
        except Exception:
            pass
        if self.on_timeout is not None:
            try:
                self.on_timeout()
            except Exception as e:
                if self.logger is not None:
                    try:
                        self.logger.log({
                            "event": "emergency_save_failed",
                            "error": repr(e)[:200],
                        })
                    except Exception:
                        pass
        self._exit(self.exit_code)
