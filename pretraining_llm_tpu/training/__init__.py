from pretraining_llm_tpu.training.trainer import Trainer  # noqa: F401
from pretraining_llm_tpu.training.train_step import build_train_step, init_train_state  # noqa: F401
