"""Framework-owned checkpointing: sharded arrays + JSON metadata, exact resume.

The reference saves once, at the end of training, via pickle
(`/root/reference/scripts/train_transformer.py:104-109`) and cannot resume
(SURVEY §5). This module provides the TPU-native recovery story — periodic
checkpoints + restart-from-latest — with:

  - no pickle: pytree leaves are `.npy` files named by their escaped path,
    plus `metadata.json` (step, leaf manifest, config snapshot, data-RNG state);
  - multi-host sharded save: when an array is not fully addressable, each
    process writes only its own device shards (`leaf.addressable_shards`,
    replica 0 only), tagged with their global index slices; load reassembles
    from the manifest. Single-host arrays are written whole;
  - atomic publish: all processes write into `<dir>/tmp-<step>`; after a
    cross-host barrier, process 0 fsyncs metadata and `os.rename`s to
    `step-<N>` — a killed run can never leave a half-checkpoint visible
    (the TPU preemption model assumes exactly this);
  - exact resume: params + optimizer moments + step + data-sampler RNG state
    round-trip bit-exactly, so a resumed run reproduces the original loss
    curve (tested);
  - retention: keep the latest K checkpoints.

Assumes the checkpoint directory is shared (or per-host paths are rejoined
out-of-band) — the standard TPU pod setup.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from pretraining_llm_tpu.observability.spans import span as _span
from pretraining_llm_tpu.resilience import integrity


def _leaf_name(path: Tuple[Any, ...]) -> str:
    parts = []
    for entry in path:
        key = entry.key if hasattr(entry, "key") else getattr(entry, "idx", entry)
        parts.append(str(key))
    return "__".join(parts)


def _flatten_with_names(tree: Any) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(_leaf_name(path), leaf) for path, leaf in flat]


def _slices_to_json(index: Tuple[slice, ...], shape: Tuple[int, ...]) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _save_leaf(tmp: str, name: str, leaf: Any) -> Dict[str, Any]:
    """Write one pytree leaf; return its manifest entry."""
    entry: Dict[str, Any] = {"name": name}
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        # Multi-host: each process persists only the shards it holds.
        entry["shape"] = list(leaf.shape)
        entry["dtype"] = str(leaf.dtype)
        entry["sharded"] = True
        for k, shard in enumerate(leaf.addressable_shards):
            if shard.replica_id != 0:
                continue  # replicated copies: one writer is enough
            fname = f"{name}.p{jax.process_index()}_{k}.npy"
            arr = np.asarray(shard.data)
            np.save(os.path.join(tmp, fname), arr)
            with open(os.path.join(tmp, fname + ".idx"), "w") as f:
                json.dump(_slices_to_json(shard.index, leaf.shape), f)
        return entry
    arr = np.asarray(jax.device_get(leaf))
    if jax.process_index() == 0:
        np.save(os.path.join(tmp, f"{name}.npy"), arr)
    entry["shape"] = list(arr.shape)
    entry["dtype"] = str(arr.dtype)
    entry["sharded"] = False
    # Content checksum over the bytes actually written: restore verifies it
    # so silent on-disk corruption (a flipped byte, a torn block that still
    # parses) fails THIS step and falls back to an older one, instead of
    # resuming training from poisoned weights. Sharded leaves skip it —
    # their shard set differs per mesh and the assembled array is not a
    # stable byte stream.
    entry["checksum"] = integrity.array_digest(arr)
    return entry


def save_checkpoint(
    directory: str,
    step: int,
    state: Any,
    *,
    extra: Optional[Dict[str, Any]] = None,
    local_extra: Optional[Dict[str, Any]] = None,
    keep: int = 3,
) -> str:
    """Write `<directory>/step-<step>/` atomically. Returns the final path.

    Call from ALL processes in a multi-host run (the barrier is internal);
    single-host it is just a local atomic write. `extra` is global metadata
    (written once, by process 0); `local_extra` is per-process state (e.g.
    this host's data-sampler RNG) — every process writes its own
    `local.p<i>.json`, and `load_checkpoint` hands each process back its own.
    """
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f"tmp-{step}")
    final = os.path.join(directory, f"step-{step}")
    if jax.process_index() == 0:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
    _barrier()

    with _span("checkpoint/write_leaves"):
        manifest = [
            _save_leaf(tmp, name, leaf) for name, leaf in _flatten_with_names(state)
        ]
    if local_extra:
        with open(os.path.join(tmp, f"local.p{jax.process_index()}.json"), "w") as f:
            json.dump(local_extra, f)
    _barrier()

    if jax.process_index() == 0:
        meta = {
            "step": int(step),
            "format_version": 2,
            "n_processes": jax.process_count(),
            "manifest": manifest,
            "extra": extra or {},
        }
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        _prune(directory, keep)
    _barrier()
    return final


def _barrier() -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("pllm_checkpoint")


def _prune(directory: str, keep: int) -> None:
    steps = sorted(_list_steps(directory))
    for s in steps[:-keep] if keep > 0 else []:
        shutil.rmtree(os.path.join(directory, f"step-{s}"), ignore_errors=True)


def _list_steps(directory: str) -> List[int]:
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if name.startswith("step-"):
            try:
                out.append(int(name.split("-", 1)[1]))
            except ValueError:
                continue
    return out


def latest_checkpoint(directory: str) -> Optional[str]:
    steps = _list_steps(directory)
    if not steps:
        return None
    return os.path.join(directory, f"step-{max(steps)}")


def gc_partial(directory: str) -> List[str]:
    """Remove leftover ``tmp-<step>`` dirs (partial writes by a killed run).

    The atomic-publish protocol makes these invisible to ``latest_checkpoint``
    already; GC keeps them from accumulating and from confusing operators
    inspecting the directory. Call from process 0 only (the writer of the
    shared dir). Returns the removed names."""
    if not os.path.isdir(directory):
        return []
    removed = []
    for name in sorted(os.listdir(directory)):
        if name.startswith("tmp-"):
            shutil.rmtree(os.path.join(directory, name), ignore_errors=True)
            removed.append(name)
    return removed


def restore_latest(
    directory: str,
    state_template: Any,
    *,
    before_step: Optional[int] = None,
    loader: Optional[Any] = None,
    on_skip: Optional[Any] = None,
) -> Optional[Tuple[Any, Dict[str, Any], int]]:
    """Restore the newest LOADABLE checkpoint, falling back past corrupt ones.

    The recovery-path counterpart of ``load_checkpoint``: a preempted or
    wedged run can leave a ``tmp-<step>`` partial (GC'd here), and disk/
    backend faults can truncate a leaf or lose ``metadata.json`` inside a
    published step dir. Steps are tried newest-first (optionally only those
    ``< before_step`` — the rollback manager uses this to dig past a
    poisoned checkpoint); each failure is reported via ``on_skip(path, exc)``
    and the next-older step is tried. Returns ``(state, extra, step)`` or
    None when the directory holds no loadable checkpoint at all.

    ``loader(path, template)`` defaults to ``load_checkpoint``; the trainer
    passes a wrapper adding its ema-compat fallback.
    """
    if jax.process_index() == 0:
        gc_partial(directory)
    load = loader or load_checkpoint
    steps = sorted(_list_steps(directory), reverse=True)
    if before_step is not None:
        steps = [s for s in steps if s < before_step]
    for step in steps:
        path = os.path.join(directory, f"step-{step}")
        try:
            state, extra = load(path, state_template)
        except Exception as e:  # corrupt/truncated/missing pieces: fall back
            if on_skip is not None:
                on_skip(path, e)
            continue
        return state, extra, step
    return None


def restore_latest_synced(
    directory: str,
    state_template: Any,
    *,
    before_step: Optional[int] = None,
    loader: Optional[Any] = None,
    on_skip: Optional[Any] = None,
) -> Optional[Tuple[Any, Dict[str, Any], int]]:
    """``restore_latest`` with cross-host agreement on the restore target.

    Independent per-process ``restore_latest`` calls can diverge: a
    host-LOCAL load failure (flaky disk, torn ``local.p<i>.json``) sends
    only that host past the failing step to an older one, and the
    processes then deadlock at the next collective with different params,
    steps, and data-RNG frontiers. Here candidates are tried in lockstep:
    every process loads the same step, the per-host success flags are
    all-gathered, and a step is adopted only unanimously — any host
    failing sends ALL hosts to the next-older candidate together.
    Single-process this is exactly ``restore_latest``.

    Candidate listing relies on the module's shared-directory assumption
    (every process sees the same ``step-<N>`` dirs).
    """
    if jax.process_count() == 1:
        return restore_latest(
            directory, state_template,
            before_step=before_step, loader=loader, on_skip=on_skip,
        )
    from jax.experimental import multihost_utils

    if jax.process_index() == 0:
        gc_partial(directory)
    _barrier()  # no process may list the dir while the GC is mid-flight
    load = loader or load_checkpoint
    steps = sorted(_list_steps(directory), reverse=True)
    if before_step is not None:
        steps = [s for s in steps if s < before_step]
    for step in steps:
        path = os.path.join(directory, f"step-{step}")
        result = None
        try:
            result = load(path, state_template)
        except Exception as e:  # corrupt/truncated/missing pieces: vote no
            if on_skip is not None:
                on_skip(path, e)
        oks = multihost_utils.process_allgather(
            np.asarray([result is not None], dtype=np.bool_)
        )
        if bool(np.asarray(oks).all()):
            state, extra = result
            return state, extra, step
        # Some host failed this step: nobody adopts it (a split restore
        # deadlocks at the next collective); every host digs older.
    return None


def _load_leaf(path: str, entry: Dict[str, Any]) -> np.ndarray:
    name = entry["name"]
    if not entry.get("sharded"):
        arr = np.load(os.path.join(path, f"{name}.npy"))
        # Absent checksum = pre-checksum checkpoint: verify vacuously so
        # old runs stay restorable. A mismatch raises IntegrityError, which
        # restore_latest's fallback treats exactly like a torn write.
        integrity.verify_array(arr, entry.get("checksum"), name)
        return arr
    arr = np.empty(entry["shape"], dtype=np.dtype(entry["dtype"]))
    found = False
    for fname in os.listdir(path):
        if fname.startswith(f"{name}.p") and fname.endswith(".npy"):
            with open(os.path.join(path, fname + ".idx")) as f:
                slices = tuple(slice(a, b) for a, b in json.load(f))
            arr[slices] = np.load(os.path.join(path, fname))
            found = True
    if not found:
        raise FileNotFoundError(f"no shard files for leaf {name} in {path}")
    return arr


def load_checkpoint(path: str, state_template: Any) -> Tuple[Any, Dict[str, Any]]:
    """Restore a pytree matching `state_template`'s structure from `path`.

    The template only supplies structure/shapes — `jax.eval_shape` output
    (ShapeDtypeStructs) works and avoids materializing a throwaway init.
    Returns (numpy_tree, extra_metadata); the caller device_puts with its own
    shardings, so restore is mesh-shape independent: a checkpoint written on
    one mesh resumes on any other. Per-process `local.p<i>.json` entries
    (see `save_checkpoint`) are merged into the returned extra dict, each
    process receiving its own — so multi-host data-RNG state resumes exactly.
    """
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    local_path = os.path.join(path, f"local.p{jax.process_index()}.json")
    if os.path.exists(local_path):
        with open(local_path) as f:
            meta.setdefault("extra", {}).update(json.load(f))
    entries = {m["name"]: m for m in meta["manifest"]}
    flat_template = jax.tree_util.tree_flatten_with_path(state_template)
    names = [_leaf_name(p) for p, _ in flat_template[0]]
    missing = [n for n in names if n not in entries]
    if missing:
        raise ValueError(
            f"checkpoint {path} missing leaves: {missing[:5]}"
            f" (+{max(0, len(missing) - 5)} more)"
        )
    leaves = []
    with _span("checkpoint/load_leaves"):
        for n, (_, tmpl) in zip(names, flat_template[0]):
            got = _load_leaf(path, entries[n])
            want_shape = tuple(getattr(tmpl, "shape", np.shape(tmpl)))
            if tuple(got.shape) != want_shape:
                raise ValueError(
                    f"checkpoint leaf {n}: shape {got.shape} != expected {want_shape}"
                )
            leaves.append(got)
    return jax.tree.unflatten(flat_template[1], leaves), meta.get("extra", {})
