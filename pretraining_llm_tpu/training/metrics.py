"""Structured metrics: JSONL + stdout, throughput and MFU accounting.

Replaces the reference's master-only `print()`s of step/loss/ms
(`/root/reference/scripts/train_transformer.py:97-101`) with a structured
stream (SURVEY §5): every record carries loss, grad-norm, LR, tokens/sec/chip,
and MFU computed from the model's analytic FLOP count against the chip's peak.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, Optional, TextIO

import jax

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.observability.events import json_line
from pretraining_llm_tpu.utils.hardware import device_peak_flops


class MetricsLogger:
    """JSONL + stdout sink. Context manager; ``close`` is idempotent and the
    JSONL file transparently reopens (append) on the next ``log`` — so the
    trainer can close the fd on every train() exit path while the same
    logger keeps working across repeated train() calls on one Trainer."""

    def __init__(self, jsonl_path: str = "", stream: Optional[TextIO] = None) -> None:
        self.stream = stream or sys.stdout
        self._path = jsonl_path
        self._file = open(jsonl_path, "a") if jsonl_path else None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    def log(self, record: Dict[str, Any]) -> None:
        record = {k: (float(v) if hasattr(v, "item") else v) for k, v in record.items()}
        if self._file is None and self._path:
            self._file = open(self._path, "a")
        if self._file is not None:
            # Strict JSON: json.dumps' default emits bare NaN/Infinity
            # tokens — invalid JSON that corrupts the JSONL exactly when
            # the anomaly detector is logging a NaN loss. json_line maps
            # non-finite floats to null + a "<key>_nonfinite" string.
            self._file.write(json_line(record) + "\n")
            self._file.flush()
        parts = []
        for key, val in record.items():
            if isinstance(val, float):
                parts.append(f"{key} {val:.4g}")
            else:
                parts.append(f"{key} {val}")
        print(" | ".join(parts), file=self.stream, flush=True)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class Throughput:
    """Throughput/MFU meter over log windows.

    Step dispatch is async (and on some remote platforms `block_until_ready`
    doesn't synchronize at all), so per-step host timing is meaningless.
    Instead: `tick(tokens)` cheaply accumulates work each step, and `window()`
    — called right after a genuine device→host sync (fetching the loss at a
    log boundary) — converts the wall time since the previous sync into
    tokens/sec and MFU. `reset_clock()` excludes eval/checkpoint time from the
    next window.
    """

    def __init__(self, model_cfg: ModelConfig, n_chips: Optional[int] = None) -> None:
        self.flops_per_token = model_cfg.flops_per_token()
        self.n_chips = n_chips or jax.device_count()
        self.peak = device_peak_flops() * self.n_chips
        self._last_time: Optional[float] = None
        self._tokens = 0
        self._steps = 0

    def tick(self, tokens: int) -> None:
        self._tokens += tokens
        self._steps += 1

    def reset_clock(self) -> None:
        """Restart the window (call after off-path work: eval, checkpoint)."""
        self._last_time = time.perf_counter()
        self._tokens = 0
        self._steps = 0

    def window(self) -> Dict[str, float]:
        now = time.perf_counter()
        if self._last_time is None or self._steps == 0:
            self._last_time = now
            self._tokens = 0
            self._steps = 0
            return {}
        dt = now - self._last_time
        tokens, steps = self._tokens, self._steps
        self._last_time = now
        self._tokens = 0
        self._steps = 0
        if dt <= 0:
            # Coarse clocks (or two boundaries landing on the same tick)
            # can yield dt <= 0; a rate over it is a ZeroDivisionError,
            # not a metric. Skip this window.
            return {}
        tok_per_sec = tokens / dt
        mfu = tok_per_sec * self.flops_per_token / self.peak
        return {
            "step_ms": dt / steps * 1e3,
            "tokens_per_sec": tok_per_sec,
            "tokens_per_sec_chip": tok_per_sec / self.n_chips,
            "mfu": mfu,
            # Raw window geometry for the observability event stream: the
            # goodput fold needs (end step, steps, wall duration) per window.
            "window_s": dt,
            "window_steps": float(steps),
        }
