"""Structured metrics: JSONL + stdout, throughput and MFU accounting.

Replaces the reference's master-only `print()`s of step/loss/ms
(`/root/reference/scripts/train_transformer.py:97-101`) with a structured
stream (SURVEY §5): every record carries loss, grad-norm, LR, tokens/sec/chip,
and MFU computed from the model's analytic FLOP count against the chip's peak.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional, TextIO

import jax

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.utils.hardware import device_peak_flops


class MetricsLogger:
    def __init__(self, jsonl_path: str = "", stream: Optional[TextIO] = None) -> None:
        self.stream = stream or sys.stdout
        self._file = open(jsonl_path, "a") if jsonl_path else None

    def log(self, record: Dict[str, Any]) -> None:
        record = {k: (float(v) if hasattr(v, "item") else v) for k, v in record.items()}
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        parts = []
        for key, val in record.items():
            if isinstance(val, float):
                parts.append(f"{key} {val:.4g}")
            else:
                parts.append(f"{key} {val}")
        print(" | ".join(parts), file=self.stream, flush=True)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()


class Throughput:
    """Sliding throughput/MFU meter. Call `tick(tokens)` once per step."""

    def __init__(self, model_cfg: ModelConfig, n_chips: Optional[int] = None) -> None:
        self.flops_per_token = model_cfg.flops_per_token()
        self.n_chips = n_chips or jax.device_count()
        self.peak = device_peak_flops() * self.n_chips
        self._last_time: Optional[float] = None

    def tick(self, tokens: int) -> Dict[str, float]:
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            return {}
        dt = now - self._last_time
        self._last_time = now
        tok_per_sec = tokens / dt
        mfu = tok_per_sec * self.flops_per_token / self.peak
        return {
            "step_ms": dt * 1e3,
            "tokens_per_sec": tok_per_sec,
            "tokens_per_sec_chip": tok_per_sec / self.n_chips,
            "mfu": mfu,
        }
