"""In-repo AdamW, gradient clipping, and LR schedules — pure pytree functions.

Replaces `torch.optim.AdamW` + the reference's hand-rolled warmup schedule
(`/root/reference/scripts/train_transformer.py:43-49,126`). Implemented in-repo
(not optax) so the optimizer state is a plain dict pytree that shares the
params' PartitionSpecs — FSDP shards moments for free — and checkpoints with
no library coupling.

Decoupled weight decay (AdamW), applied only to weight matrices/embeddings
(never biases or norm scales), selected by param path.
"""

from __future__ import annotations

import math

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import TrainConfig

OptState = Dict[str, Any]

# Every weight-matrix leaf across all model variants. The reference applies
# AdamW decay to ALL Linear weights (train_transformer.py:126); here decay is
# by-name so biases and norm scales stay undecayed. `wq`/`wkv` are the GQA
# projection leaves (transformer.py:92-94) — omitting them silently trained
# GQA attention without decay (VERDICT r2 weak #3). `router` (moe.py:68) is
# decayed deliberately: it is a plain d×e dense projection, and the reference
# decays every Linear weight.
_DECAY_LEAVES = frozenset(
    {"wqkv", "wq", "wkv", "wo", "w1", "w2", "kernel", "embedding", "router"}
)

# Leaves that deliberately receive NO decay: norm parameters and biases.
# Several bias leaves are >=2-D (head-structured shapes, e.g. bqkv (3,H,Dh)),
# so classification is by name, never by rank. tests/test_optimizer.py asserts
# every leaf of every preset lands in exactly one of these two sets.
_NO_DECAY_LEAVES = frozenset(
    {"scale", "bias", "bqkv", "bq", "bkv", "bo", "b1", "b2"}
)


def _leaf_name(path) -> str:
    """Last path component as a string (DictKey or index)."""
    return str(path[-1].key) if hasattr(path[-1], "key") else str(path[-1])


def decay_mask(params: Any) -> Any:
    """True for leaves that receive weight decay, keyed on the leaf name."""

    def rule(path, leaf):
        return _leaf_name(path) in _DECAY_LEAVES

    return jax.tree_util.tree_map_with_path(rule, params)


def adamw_init(params: Any) -> OptState:
    zeros = lambda p: jnp.zeros_like(p)  # noqa: E731
    return {
        "mu": jax.tree.map(zeros, params),
        "nu": jax.tree.map(zeros, params),
        "count": jnp.zeros((), jnp.int32),
    }


def global_norm(tree: Any) -> jax.Array:
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(tree))
    )


def clip_by_global_norm(grads: Any, max_norm: float) -> Tuple[Any, jax.Array]:
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-9))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads), norm


def adamw_update(
    grads: Any,
    state: OptState,
    params: Any,
    lr: jax.Array,
    cfg: TrainConfig,
) -> Tuple[Any, OptState]:
    """One AdamW step. Returns (new_params, new_state). All math in fp32."""
    count = state["count"] + 1
    b1, b2, eps, wd = cfg.adam_b1, cfg.adam_b2, cfg.adam_eps, cfg.weight_decay
    c = count.astype(jnp.float32)
    bc1 = 1.0 - b1**c
    bc2 = 1.0 - b2**c
    mask = decay_mask(params)

    def leaf_update(g, mu, nu, p, decay):
        g32 = g.astype(jnp.float32)
        mu_new = b1 * mu.astype(jnp.float32) + (1 - b1) * g32
        nu_new = b2 * nu.astype(jnp.float32) + (1 - b2) * jnp.square(g32)
        step = (mu_new / bc1) / (jnp.sqrt(nu_new / bc2) + eps)
        if decay and wd > 0:
            step = step + wd * p.astype(jnp.float32)
        p_new = p.astype(jnp.float32) - lr * step
        return p_new.astype(p.dtype), mu_new.astype(mu.dtype), nu_new.astype(nu.dtype)

    flat_g, treedef = jax.tree.flatten(grads)
    flat_mu = jax.tree.leaves(state["mu"])
    flat_nu = jax.tree.leaves(state["nu"])
    flat_p = jax.tree.leaves(params)
    flat_mask = jax.tree.leaves(mask)
    new_p, new_mu, new_nu = [], [], []
    for g, mu, nu, p, d in zip(flat_g, flat_mu, flat_nu, flat_p, flat_mask):
        pn, mn, nn = leaf_update(g, mu, nu, p, d)
        new_p.append(pn)
        new_mu.append(mn)
        new_nu.append(nn)
    return (
        jax.tree.unflatten(treedef, new_p),
        {
            "mu": jax.tree.unflatten(treedef, new_mu),
            "nu": jax.tree.unflatten(treedef, new_nu),
            "count": count,
        },
    )


# ---------------------------------------------------------------------------
# Adafactor (memory-factored second moments)
# ---------------------------------------------------------------------------
#
# Cuts optimizer state from 8 bytes/param (Adam mu+nu fp32) to ~0.3:
# the second moment of an (r, c) matrix is stored as row/column statistics
# R (r,) and C (c,) with V ~= R C^T / sum(R) (Shazeer & Stern 2018). No
# first moment (beta1 = 0). This is what lets the Llama-style 1B train on
# ONE 16 GB chip: fp32 params 4.96 GB + Adam moments 9.9 GB does not fit;
# + factored state ~0.2 GB does. The reference has no optimizer choice at
# all (torch AdamW only, train_transformer.py:126).
#
# Factoring rule (chosen so every `blocks` state array keeps the leading
# stacked-layer axis — the interleaved-pipeline baking permutes axis 0 of
# every blocks leaf):
#   - ndim >= 3           -> factored over the LAST TWO axes, leading axes
#                            kept as batch (R: shape[:-1], C: shape[:-2]+(c,))
#   - ndim == 2 top-level -> factored (embeddings, lm_head)
#   - ndim == 2 in blocks -> full v (stacked norm scales (L, d) — tiny, and
#                            factoring would drop the leading L from C)
#   - ndim <= 1           -> full v
_ADAFACTOR_EPS1 = 1e-30  # inside sqrt: g^2 + eps1
_ADAFACTOR_EPS2 = 1e-3   # not used in the plain-lr variant; kept for parity
_ADAFACTOR_CLIP = 1.0    # update-RMS clipping threshold d


def _adafactor_factored(path, leaf) -> bool:
    if leaf.ndim >= 3:
        return True
    top = str(path[0].key) if hasattr(path[0], "key") else str(path[0])
    return leaf.ndim == 2 and top != "blocks"


def adafactor_init(params: Any) -> OptState:
    def init_leaf(path, p):
        if _adafactor_factored(path, p):
            return {
                "r": jnp.zeros(p.shape[:-1], jnp.float32),
                "c": jnp.zeros(p.shape[:-2] + (p.shape[-1],), jnp.float32),
            }
        return {"full": jnp.zeros(p.shape, jnp.float32)}

    return {
        "v": jax.tree_util.tree_map_with_path(init_leaf, params),
        "count": jnp.zeros((), jnp.int32),
    }


def adafactor_update(
    grads: Any,
    state: OptState,
    params: Any,
    lr: jax.Array,
    cfg: TrainConfig,
) -> Tuple[Any, OptState]:
    """One Adafactor step (beta1=0, update-RMS clipping, decoupled wd).

    beta2 follows the paper's schedule 1 - t^-0.8 (no bias correction
    needed); the step size is the trainer's lr schedule (not the paper's
    relative-step variant) so runs stay comparable with AdamW configs.
    """
    count = state["count"] + 1
    c = count.astype(jnp.float32)
    b2t = 1.0 - c ** -0.8
    wd = cfg.weight_decay
    mask = decay_mask(params)

    def leaf_update(g, v, p, decay):
        g32 = g.astype(jnp.float32)
        g2 = jnp.square(g32) + _ADAFACTOR_EPS1
        if "full" in v:
            v_new = {"full": b2t * v["full"] + (1.0 - b2t) * g2}
            u = g32 * jax.lax.rsqrt(v_new["full"])
        else:
            r_new = b2t * v["r"] + (1.0 - b2t) * jnp.sum(g2, axis=-1)
            c_new = b2t * v["c"] + (1.0 - b2t) * jnp.sum(g2, axis=-2)
            v_new = {"r": r_new, "c": c_new}
            denom = jnp.sum(r_new, axis=-1, keepdims=True)
            # Normalize BEFORE the outer product: r and c are O(eps1)-small
            # for zero-gradient slices, and (1e-30 * 1e-30) underflows fp32
            # to 0 -> rsqrt(0)=inf -> 0*inf=NaN. r/sum(r) is O(1), so the
            # product stays representable; the floor catches any residual
            # underflow without touching legitimate small statistics.
            v_hat = (r_new / denom)[..., :, None] * c_new[..., None, :]
            u = g32 * jax.lax.rsqrt(jnp.maximum(v_hat, 1e-37))
        rms_u = jnp.sqrt(jnp.mean(jnp.square(u)))
        u = u / jnp.maximum(1.0, rms_u / _ADAFACTOR_CLIP)
        if decay and wd > 0:
            u = u + wd * p.astype(jnp.float32)
        p_new = p.astype(jnp.float32) - lr * u
        return p_new.astype(p.dtype), v_new

    flat_g = jax.tree.leaves(grads)
    treedef = jax.tree.structure(params)
    # v's tree is deeper than params' (dict per param leaf); rebuild by
    # walking params' flattened order against v's matching subtrees.
    flat_v = jax.tree.leaves(
        state["v"], is_leaf=lambda x: isinstance(x, dict) and ("full" in x or "r" in x)
    )
    flat_p = jax.tree.leaves(params)
    flat_mask = jax.tree.leaves(mask)
    new_p, new_v = [], []
    for g, v, p, d in zip(flat_g, flat_v, flat_p, flat_mask):
        pn, vn = leaf_update(g, v, p, d)
        new_p.append(pn)
        new_v.append(vn)
    return (
        jax.tree.unflatten(treedef, new_p),
        {"v": jax.tree.unflatten(treedef, new_v), "count": count},
    )


# ---------------------------------------------------------------------------
# Muon (momentum + Newton-Schulz orthogonalization; Jordan et al. 2024,
# "Muon is Scalable" scaling rule)
# ---------------------------------------------------------------------------
#
# Beyond-reference optimizer choice (the reference has torch AdamW only,
# train_transformer.py:126). Hidden weight MATRICES take momentum-SGD whose
# update is orthogonalized by 5 Newton-Schulz iterations — pure batched
# matmuls, exactly what the MXU is for (the NS cost at gpt2-124m is ~0.1%
# of step FLOPs). Everything else (embeddings, lm head, biases, norm
# scales, 1-D leaves) takes the in-repo AdamW path, per the canonical Muon
# recipe. The update is rescaled by 0.2*sqrt(max(rows, cols)) to match
# AdamW's update RMS ("Muon is Scalable"), so lr / weight-decay knobs are
# SHARED with AdamW configs — one schedule, comparable runs.
#
# Matrix view of head-structured leaves: a blocks leaf (L, ...) is a batch
# of L per-layer matrices. wqkv (L, D, 3, H, Dh) maps D -> 3*H*Dh, so rows
# = axis 1, cols = the rest; wo (L, H, Dh, D) maps H*Dh -> D, so cols =
# last axis, rows = the middle. Orthogonalization runs on the 2-D view and
# the update is reshaped back.

_MUON_LEAVES = frozenset({"wqkv", "wq", "wkv", "wo", "w1", "w2", "router"})

# Quintic Newton-Schulz coefficients (Jordan 2024): converge singular
# values of the normalized momentum into ~[0.7, 1.2] in 5 iterations —
# loose orthogonality is all Muon needs.
_NS_COEFFS = (3.4445, -4.7750, 2.0315)
_NS_STEPS = 5
_MUON_RMS_MATCH = 0.2  # update-RMS match factor vs AdamW


def _muon_leaf(path, leaf) -> bool:
    return _leaf_name(path) in _MUON_LEAVES and leaf.ndim >= 2


def _matrix_view(path, leaf_shape) -> Tuple[int, int, int]:
    """(batch, rows, cols) of the leaf's 2-D matrix view.

    Leading BATCH axes are the stacked-layer axis (blocks leaves) plus the
    expert axis for MoE leaves (path contains "experts": w1 (L, E, D, F) /
    (L, E, D, 2, F), w2 (L, E, F, D) — each EXPERT's matrix is
    orthogonalized independently, never across experts). The matrix is the
    linear map the leaf applies: wo contracts everything before its last
    axis (H*Dh -> D); all other names map their first post-batch axis to
    the rest (wqkv D -> 3*H*Dh, w1 D -> F or packed 2F, w2 F -> D,
    router D -> E)."""
    shape = tuple(leaf_shape)
    name = _leaf_name(path)
    n_batch = 1 + any(
        (str(p.key) if hasattr(p, "key") else str(p)) == "experts" for p in path
    )
    n_batch = min(n_batch, len(shape) - 2)  # bare (r, c) test leaves: batch 1
    b = math.prod(shape[:n_batch])
    if name == "wo":
        return b, math.prod(shape[n_batch:-1]), shape[-1]
    return b, shape[n_batch], math.prod(shape[n_batch + 1:])


def newton_schulz_orthogonalize(m: jax.Array, steps: int = _NS_STEPS) -> jax.Array:
    """Batched (B, r, c) quintic Newton-Schulz iteration toward the nearest
    semi-orthogonal matrix (zeroth power of the SVD). Iterates in the
    smaller dimension; fp32 throughout (cost is negligible vs the step)."""
    a, b, c = _NS_COEFFS
    transpose = m.shape[-2] > m.shape[-1]
    x = jnp.swapaxes(m, -1, -2) if transpose else m
    x = x / (
        jnp.linalg.norm(x, axis=(-2, -1), keepdims=True) + 1e-7
    )
    for _ in range(steps):
        xxt = jnp.einsum("brc,bsc->brs", x, x)
        y = b * xxt + c * jnp.einsum("brs,bst->brt", xxt, xxt)
        x = a * x + jnp.einsum("brs,bsc->brc", y, x)
    return jnp.swapaxes(x, -1, -2) if transpose else x


def muon_init(params: Any) -> OptState:
    """Per-leaf dict state (the adafactor pattern): momentum only for Muon
    matrices, Adam mu+nu for everything else."""

    def init_leaf(path, p):
        if _muon_leaf(path, p):
            return {"m": jnp.zeros(p.shape, jnp.float32)}
        return {
            "mu": jnp.zeros(p.shape, jnp.float32),
            "nu": jnp.zeros(p.shape, jnp.float32),
        }

    return {
        "s": jax.tree_util.tree_map_with_path(init_leaf, params),
        "count": jnp.zeros((), jnp.int32),
    }


def muon_update(
    grads: Any,
    state: OptState,
    params: Any,
    lr: jax.Array,
    cfg: TrainConfig,
) -> Tuple[Any, OptState]:
    """One Muon step (nesterov momentum -> NS orthogonalization -> RMS-match
    scaling) for hidden matrices; AdamW math for the rest. All fp32."""
    count = state["count"] + 1
    mu_m = cfg.muon_momentum
    b1, b2, eps, wd = cfg.adam_b1, cfg.adam_b2, cfg.adam_eps, cfg.weight_decay
    c32 = count.astype(jnp.float32)
    bc1 = 1.0 - b1**c32
    bc2 = 1.0 - b2**c32
    mask = decay_mask(params)

    def leaf_update(path, g, s, p, decay):
        g32 = g.astype(jnp.float32)
        if "m" in s:
            m_new = mu_m * s["m"] + g32
            u_in = g32 + mu_m * m_new  # nesterov
            bsz, rows, cols = _matrix_view(path, p.shape)
            u2d = newton_schulz_orthogonalize(u_in.reshape(bsz, rows, cols))
            scale = _MUON_RMS_MATCH * float(max(rows, cols)) ** 0.5
            u = (u2d * scale).reshape(p.shape)
            s_new = {"m": m_new}
        else:
            mu_new = b1 * s["mu"] + (1 - b1) * g32
            nu_new = b2 * s["nu"] + (1 - b2) * jnp.square(g32)
            u = (mu_new / bc1) / (jnp.sqrt(nu_new / bc2) + eps)
            s_new = {"mu": mu_new, "nu": nu_new}
        if decay and wd > 0:
            u = u + wd * p.astype(jnp.float32)
        p_new = p.astype(jnp.float32) - lr * u
        return p_new.astype(p.dtype), s_new

    flat_g = jax.tree.leaves(grads)
    treedef = jax.tree.structure(params)
    flat_s = jax.tree.leaves(
        state["s"], is_leaf=lambda x: isinstance(x, dict) and ("m" in x or "mu" in x)
    )
    flat_p_paths, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_mask = jax.tree.leaves(mask)
    new_p, new_s = [], []
    for (path, p), g, s, d in zip(flat_p_paths, flat_g, flat_s, flat_mask):
        pn, sn = leaf_update(path, g, s, p, d)
        new_p.append(pn)
        new_s.append(sn)
    return (
        jax.tree.unflatten(treedef, new_p),
        {"s": jax.tree.unflatten(treedef, new_s), "count": count},
    )


def optimizer_init(params: Any, cfg: TrainConfig) -> OptState:
    """Dispatch by cfg.optimizer ('adamw' | 'adafactor' | 'muon')."""
    if cfg.optimizer == "adafactor":
        return adafactor_init(params)
    if cfg.optimizer == "muon":
        return muon_init(params)
    return adamw_init(params)


def optimizer_update(
    grads: Any, state: OptState, params: Any, lr: jax.Array, cfg: TrainConfig
) -> Tuple[Any, OptState]:
    if cfg.optimizer == "adafactor":
        return adafactor_update(grads, state, params, lr, cfg)
    if cfg.optimizer == "muon":
        return muon_update(grads, state, params, lr, cfg)
    return adamw_update(grads, state, params, lr, cfg)


def learning_rate(step: jax.Array, cfg: TrainConfig) -> jax.Array:
    """LR schedule. The reference uses 10%-warmup-then-constant
    (train_transformer.py:43-49); warmup+cosine is the pretraining default;
    warmup_stable_decay (WSD) holds lr constant after warmup then decays
    linearly over the final decay_frac of the run — mid-run checkpoints
    carry no cosine horizon, so runs extend/branch cleanly."""
    s = step.astype(jnp.float32)
    warmup = jnp.maximum(cfg.warmup_frac * cfg.train_steps, 1.0)
    warm_lr = cfg.lr * (s + 1.0) / warmup
    if cfg.lr_schedule == "warmup_constant":
        return jnp.minimum(warm_lr, cfg.lr)
    min_lr = cfg.lr * cfg.min_lr_frac
    if cfg.lr_schedule == "warmup_stable_decay":
        # Clamp to the warmup boundary: decay_frac ~ 1.0 must not put the
        # decay start INSIDE warmup (an instant LR cliff at the handoff).
        decay_start = jnp.maximum(
            cfg.train_steps * (1.0 - cfg.decay_frac), warmup
        )
        frac = jnp.clip(
            (s - decay_start)
            / jnp.maximum(cfg.train_steps - decay_start, 1.0),
            0.0, 1.0,
        )
        stable_or_decay = cfg.lr + (min_lr - cfg.lr) * frac
        return jnp.where(s < warmup, warm_lr, stable_or_decay)
    # warmup_cosine
    progress = jnp.clip((s - warmup) / jnp.maximum(cfg.train_steps - warmup, 1.0), 0.0, 1.0)
    cos_lr = min_lr + 0.5 * (cfg.lr - min_lr) * (1.0 + jnp.cos(jnp.pi * progress))
    return jnp.where(s < warmup, warm_lr, cos_lr)
