"""In-repo AdamW, gradient clipping, and LR schedules — pure pytree functions.

Replaces `torch.optim.AdamW` + the reference's hand-rolled warmup schedule
(`/root/reference/scripts/train_transformer.py:43-49,126`). Implemented in-repo
(not optax) so the optimizer state is a plain dict pytree that shares the
params' PartitionSpecs — FSDP shards moments for free — and checkpoints with
no library coupling.

Decoupled weight decay (AdamW), applied only to weight matrices/embeddings
(never biases or norm scales), selected by param path.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import TrainConfig

OptState = Dict[str, Any]

# Every weight-matrix leaf across all model variants. The reference applies
# AdamW decay to ALL Linear weights (train_transformer.py:126); here decay is
# by-name so biases and norm scales stay undecayed. `wq`/`wkv` are the GQA
# projection leaves (transformer.py:92-94) — omitting them silently trained
# GQA attention without decay (VERDICT r2 weak #3). `router` (moe.py:68) is
# decayed deliberately: it is a plain d×e dense projection, and the reference
# decays every Linear weight.
_DECAY_LEAVES = frozenset(
    {"wqkv", "wq", "wkv", "wo", "w1", "w2", "kernel", "embedding", "router"}
)

# Leaves that deliberately receive NO decay: norm parameters and biases.
# Several bias leaves are >=2-D (head-structured shapes, e.g. bqkv (3,H,Dh)),
# so classification is by name, never by rank. tests/test_optimizer.py asserts
# every leaf of every preset lands in exactly one of these two sets.
_NO_DECAY_LEAVES = frozenset(
    {"scale", "bias", "bqkv", "bq", "bkv", "bo", "b1", "b2"}
)


def decay_mask(params: Any) -> Any:
    """True for leaves that receive weight decay, keyed on the leaf name."""

    def rule(path, leaf):
        name = str(path[-1].key) if hasattr(path[-1], "key") else str(path[-1])
        return name in _DECAY_LEAVES

    return jax.tree_util.tree_map_with_path(rule, params)


def adamw_init(params: Any) -> OptState:
    zeros = lambda p: jnp.zeros_like(p)  # noqa: E731
    return {
        "mu": jax.tree.map(zeros, params),
        "nu": jax.tree.map(zeros, params),
        "count": jnp.zeros((), jnp.int32),
    }


def global_norm(tree: Any) -> jax.Array:
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(tree))
    )


def clip_by_global_norm(grads: Any, max_norm: float) -> Tuple[Any, jax.Array]:
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-9))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads), norm


def adamw_update(
    grads: Any,
    state: OptState,
    params: Any,
    lr: jax.Array,
    cfg: TrainConfig,
) -> Tuple[Any, OptState]:
    """One AdamW step. Returns (new_params, new_state). All math in fp32."""
    count = state["count"] + 1
    b1, b2, eps, wd = cfg.adam_b1, cfg.adam_b2, cfg.adam_eps, cfg.weight_decay
    c = count.astype(jnp.float32)
    bc1 = 1.0 - b1**c
    bc2 = 1.0 - b2**c
    mask = decay_mask(params)

    def leaf_update(g, mu, nu, p, decay):
        g32 = g.astype(jnp.float32)
        mu_new = b1 * mu.astype(jnp.float32) + (1 - b1) * g32
        nu_new = b2 * nu.astype(jnp.float32) + (1 - b2) * jnp.square(g32)
        step = (mu_new / bc1) / (jnp.sqrt(nu_new / bc2) + eps)
        if decay and wd > 0:
            step = step + wd * p.astype(jnp.float32)
        p_new = p.astype(jnp.float32) - lr * step
        return p_new.astype(p.dtype), mu_new.astype(mu.dtype), nu_new.astype(nu.dtype)

    flat_g, treedef = jax.tree.flatten(grads)
    flat_mu = jax.tree.leaves(state["mu"])
    flat_nu = jax.tree.leaves(state["nu"])
    flat_p = jax.tree.leaves(params)
    flat_mask = jax.tree.leaves(mask)
    new_p, new_mu, new_nu = [], [], []
    for g, mu, nu, p, d in zip(flat_g, flat_mu, flat_nu, flat_p, flat_mask):
        pn, mn, nn = leaf_update(g, mu, nu, p, d)
        new_p.append(pn)
        new_mu.append(mn)
        new_nu.append(nn)
    return (
        jax.tree.unflatten(treedef, new_p),
        {
            "mu": jax.tree.unflatten(treedef, new_mu),
            "nu": jax.tree.unflatten(treedef, new_nu),
            "count": count,
        },
    )


def learning_rate(step: jax.Array, cfg: TrainConfig) -> jax.Array:
    """LR schedule. The reference uses 10%-warmup-then-constant
    (train_transformer.py:43-49); warmup+cosine is the pretraining default."""
    s = step.astype(jnp.float32)
    warmup = jnp.maximum(cfg.warmup_frac * cfg.train_steps, 1.0)
    warm_lr = cfg.lr * (s + 1.0) / warmup
    if cfg.lr_schedule == "warmup_constant":
        return jnp.minimum(warm_lr, cfg.lr)
    # warmup_cosine
    min_lr = cfg.lr * cfg.min_lr_frac
    progress = jnp.clip((s - warmup) / jnp.maximum(cfg.train_steps - warmup, 1.0), 0.0, 1.0)
    cos_lr = min_lr + 0.5 * (cfg.lr - min_lr) * (1.0 + jnp.cos(jnp.pi * progress))
    return jnp.where(s < warmup, warm_lr, cos_lr)
