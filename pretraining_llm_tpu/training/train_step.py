"""The single compiled SPMD train step.

The reference's step is many separate device launches — autocast forward,
scaled backward, DDP bucketed all-reduce, scaler step, zero_grad
(`/root/reference/scripts/train_transformer.py:64-94`). Here the *entire*
optimizer step is one `jit`-compiled XLA program over the global mesh:

    grads = mean over microbatches (lax.scan)   # grad accumulation, done right
    clip -> AdamW -> new params                  # fused into the same program
    collectives inserted by XLA from shardings   # no NCCL calls to write

Gradient accumulation via `lax.scan` fixes the reference's broken
every-other-step sync gating (SURVEY §A B7) by construction: the optimizer
sees exactly the mean gradient of the full global batch.

State is a plain dict pytree {'params', 'opt', 'step'} so checkpointing and
sharding rules treat it uniformly.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pretraining_llm_tpu.config import Config
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.parallel.sharding import (
    activation_mesh,
    batch_pspec,
    named_sharding_tree,
    param_pspec_tree,
)
from pretraining_llm_tpu.training import optimizer as opt

TrainState = Dict[str, Any]


def init_train_state(cfg: Config, key: jax.Array) -> TrainState:
    params = transformer.init_params(cfg.model, key)
    state = {
        "params": params,
        "opt": opt.optimizer_init(params, cfg.train),
        "step": jnp.zeros((), jnp.int32),
    }
    if cfg.train.ema_decay > 0:
        # Exponential moving average of the params for evaluation/serving
        # (beyond-reference): fp32 shadow updated after every optimizer
        # step; checkpointed and sharded exactly like the params.
        # copy=True: fp32 params' astype would alias the SAME buffer,
        # and the jitted step donates the state — donating params and
        # ema as one buffer is an XLA error (and would be wrong anyway).
        state["ema"] = jax.tree.map(
            lambda p: jnp.array(p, dtype=jnp.float32, copy=True), params
        )
    return state


def state_pspec_tree(
    state: TrainState, pipeline: bool = False, *, tensor_size: int = 1
) -> Any:
    """PartitionSpecs for the full train state (moments mirror params)."""
    kw = {"tensor_size": tensor_size}
    pspecs = param_pspec_tree(state["params"], pipeline, **kw)
    if "v" in state["opt"]:
        # Adafactor: the factored statistics are ~0.3 bytes/param — too
        # small to be worth sharding (and their shapes don't match the
        # param sharding rules). Replicate every statistic array.
        opt_pspecs = {
            "v": jax.tree.map(lambda _: P(), state["opt"]["v"]),
            "count": P(),
        }
    elif "s" in state["opt"]:
        # Muon: every per-leaf state array (muon momentum "m", or adam
        # "mu"/"nu" for the non-matrix leaves) mirrors its param's shape —
        # shard each exactly like the param (FSDP shards momentum for
        # free, same as adamw's moments). tree.map flattens the per-leaf
        # state dict UP TO the param pspec tree, so each dict maps to
        # {key: param_pspec}.
        opt_pspecs = {
            "s": jax.tree.map(
                lambda ps, sd: {k: ps for k in sd}, pspecs, state["opt"]["s"]
            ),
            "count": P(),
        }
    else:
        opt_pspecs = {
            "mu": param_pspec_tree(state["opt"]["mu"], pipeline, **kw),
            "nu": param_pspec_tree(state["opt"]["nu"], pipeline, **kw),
            "count": P(),
        }
    out = {
        "params": pspecs,
        "opt": opt_pspecs,
        "step": P(),
    }
    if "ema" in state:
        out["ema"] = param_pspec_tree(state["ema"], pipeline, **kw)
    return out


def _tensor_size(mesh: Optional[Mesh]) -> int:
    return mesh.shape.get("tensor", 1) if mesh is not None else 1


def _is_pipelined(cfg: Config, mesh: Optional[Mesh]) -> bool:
    return (
        cfg.model.pipeline_stages > 1
        and mesh is not None
        and mesh.shape.get("pipe", 1) > 1
    )


def shard_train_state(state: TrainState, mesh: Mesh, cfg: Optional[Config] = None) -> TrainState:
    """Place the train state on the mesh (and bake the pipeline layout).

    With an interleaved pipeline (pipeline_interleave>1 on a pipe>1 mesh),
    block params AND optimizer moments are stored rank-major
    (parallel.pipeline.interleave_layout) so the P('pipe') shards hold each
    rank's V depth chunks directly — the schedule then runs with no per-step
    cross-rank reshard (VERDICT r2 next #5). Checkpoints remain canonical
    depth-major; the trainer converts at save/load.
    """
    pipeline = cfg is not None and _is_pipelined(cfg, mesh)
    if cfg is not None and uses_baked_layout(cfg, mesh):
        state = bake_state_layout(state, cfg, forward=True)
    shardings = named_sharding_tree(
        mesh, state_pspec_tree(state, pipeline, tensor_size=_tensor_size(mesh))
    )
    return jax.device_put(state, shardings)


def bake_state_layout(state: TrainState, cfg: Config, forward: bool = True) -> TrainState:
    """Convert blocks (+ mirrored moments) between canonical depth-major and
    the interleaved rank-major layout. ``forward=True``: depth -> rank-major
    (entering pipelined training); ``False``: back to canonical (checkpoint
    save, export)."""
    from pretraining_llm_tpu.parallel import pipeline as pp

    s = cfg.model.pipeline_stages
    v = cfg.model.pipeline_interleave
    f = pp.interleave_layout if forward else pp.deinterleave_layout
    out = dict(state)
    out["params"] = dict(state["params"])
    out["params"]["blocks"] = f(state["params"]["blocks"], s, v)
    if "opt" in state:
        out["opt"] = dict(state["opt"])
        # Every moment container mirroring the params' structure (adamw:
        # mu/nu; adafactor: v — whose blocks arrays all keep the leading
        # stacked-layer axis by the factoring rule) gets the same layout
        # permutation as the params.
        for m, sub in state["opt"].items():
            if isinstance(sub, dict) and "blocks" in sub:
                out["opt"][m] = dict(sub)
                out["opt"][m]["blocks"] = f(sub["blocks"], s, v)
    if "ema" in state:
        out["ema"] = dict(state["ema"])
        out["ema"]["blocks"] = f(state["ema"]["blocks"], s, v)
    return out


def _loss_and_metrics(params, xb, yb, model_cfg, blocks_baked=False):
    loss = transformer.loss_fn(params, xb, yb, model_cfg, blocks_baked=blocks_baked)
    return loss


def uses_baked_layout(cfg: Config, mesh: Optional[Mesh]) -> bool:
    """True when the train state stores blocks in the rank-major interleaved
    layout (baked once by shard_train_state instead of re-permuted per step)."""
    return _is_pipelined(cfg, mesh) and cfg.model.pipeline_interleave > 1


def _make_step_fn(cfg: Config, mesh: Optional[Mesh] = None):
    """The raw (unjitted) SPMD step: grads -> clip -> AdamW -> metrics."""
    model_cfg = cfg.model
    tcfg = cfg.train
    n_micro = tcfg.microbatches
    baked = uses_baked_layout(cfg, mesh)

    def step_fn(state: TrainState, batch: Tuple[jax.Array, jax.Array]):
        x, y = batch
        if tcfg.grad_dtype == "bfloat16":
            # HBM lever (the 1B b8 knee): cast each gradient leaf to bf16
            # IMMEDIATELY after the backward produces it — XLA fuses the
            # convert into the producing fusion, so the end-of-backward
            # state holds a 2-byte/param tree (and the microbatch
            # accumulator below matches). Chosen over differentiating a
            # bf16 param view after AOT memory analysis (2026-08-02): the
            # up-front bf16 param copy stays PINNED across the whole
            # backward (+2.8 GiB at 1B), cancelling the saving, while
            # this form keeps the fp32 cotangent chain (grads are the
            # fp32-path values rounded once) and adds no pinned copy.
            # Clip and the optimizer updates upcast per-leaf internally.
            def grad_fn(params, mx, my, mcfg, bk):
                loss, g = jax.value_and_grad(_loss_and_metrics)(
                    params, mx, my, mcfg, bk
                )
                g = jax.tree.map(
                    lambda leaf: leaf.astype(jnp.bfloat16)
                    if leaf.dtype == jnp.float32 else leaf,
                    g,
                )
                return loss, g
        else:
            grad_fn = jax.value_and_grad(_loss_and_metrics)

        if n_micro == 1:
            loss, grads = grad_fn(state["params"], x, y, model_cfg, baked)
        else:
            b = x.shape[0]
            xm = x.reshape(n_micro, b // n_micro, -1)
            ym = y.reshape(n_micro, b // n_micro, -1)

            def micro_step(carry, mb):
                loss_acc, grads_acc = carry
                mx, my = mb
                loss, grads = grad_fn(state["params"], mx, my, model_cfg, baked)
                return (
                    loss_acc + loss,
                    jax.tree.map(jnp.add, grads_acc, grads),
                ), None

            # The accumulator matches the grad storage dtype (bf16 halves
            # it too under grad_dtype="bfloat16" — mean-of-microbatches in
            # bf16 is the documented precision trade of that knob).
            gdt = (
                jnp.bfloat16 if tcfg.grad_dtype == "bfloat16" else None
            )
            zero_grads = jax.tree.map(
                lambda p: jnp.zeros_like(
                    p,
                    dtype=gdt if (gdt and p.dtype == jnp.float32) else p.dtype,
                ),
                state["params"],
            )
            (loss_sum, grad_sum), _ = jax.lax.scan(
                micro_step, (jnp.zeros((), jnp.float32), zero_grads), (xm, ym)
            )
            loss = loss_sum / n_micro
            grads = jax.tree.map(lambda g: g / n_micro, grad_sum)

        if tcfg.grad_clip > 0:
            grads, grad_norm = opt.clip_by_global_norm(grads, tcfg.grad_clip)
        else:
            grad_norm = opt.global_norm(grads)

        lr = opt.learning_rate(state["step"], tcfg)
        new_params, new_opt = opt.optimizer_update(
            grads, state["opt"], state["params"], lr, tcfg
        )
        new_state = {"params": new_params, "opt": new_opt, "step": state["step"] + 1}
        if "ema" in state:
            d = tcfg.ema_decay
            new_state["ema"] = jax.tree.map(
                lambda e, p: d * e + (1.0 - d) * p.astype(jnp.float32),
                state["ema"], new_params,
            )
        metrics = {"loss": loss, "grad_norm": grad_norm, "lr": lr}
        return new_state, metrics

    return step_fn


def build_train_step(
    cfg: Config, mesh: Optional[Mesh] = None
) -> Callable[[TrainState, Tuple[jax.Array, jax.Array]], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Compile the train step. batch: (x, y) each (B, T) int32, B = global batch."""
    model_cfg = cfg.model
    step_fn = _make_step_fn(cfg, mesh)

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=0)

    def traced(state, batch):
        with activation_mesh(mesh):
            return step_fn(state, batch)

    # Shardings are derived from the live state at first call (the pytree
    # structure depends on model flags), then the compiled fn is memoized.
    batch_sharding = NamedSharding(mesh, batch_pspec(model_cfg.sequence_parallel))
    compiled_cache: Dict[Any, Any] = {}

    pipelined = _is_pipelined(cfg, mesh)

    def wrapper(state, batch):
        key = jax.tree.structure(state)
        fn = compiled_cache.get(key)
        if fn is None:
            state_shardings = named_sharding_tree(
                mesh, state_pspec_tree(state, pipelined, tensor_size=_tensor_size(mesh))
            )
            fn = jax.jit(
                traced,
                in_shardings=(state_shardings, (batch_sharding, batch_sharding)),
                out_shardings=(state_shardings, None),
                donate_argnums=0,
            )
            compiled_cache[key] = fn
        return fn(state, batch)

    return wrapper


def lower_train_step(cfg: Config, mesh: Optional[Mesh] = None):
    """AOT-lower the EXACT jitted train-step program (same in/out shardings,
    same donation) from shape specs alone — no params materialize, no data
    loads. Returns the jax.stages.Lowered; `.compile().memory_analysis()`
    gives XLA's per-device memory breakdown (scripts/train.py --compile-only
    uses this to size big configs before burning pod time on an OOM)."""
    state_shapes = jax.eval_shape(lambda: init_train_state(cfg, jax.random.key(0)))
    b, t = cfg.train.batch_size, cfg.model.context_length
    if mesh is None:
        step = build_train_step(cfg, None)
        batch_sds = jax.ShapeDtypeStruct((b, t), jnp.int32)
        return step.lower(state_shapes, (batch_sds, batch_sds))
    batch_sharding = NamedSharding(mesh, batch_pspec(cfg.model.sequence_parallel))
    state_shardings = named_sharding_tree(
        mesh,
        state_pspec_tree(
            state_shapes, _is_pipelined(cfg, mesh), tensor_size=_tensor_size(mesh)
        ),
    )
    step_fn = _make_step_fn(cfg, mesh)

    def traced(state, batch):
        with activation_mesh(mesh):
            return step_fn(state, batch)

    fn = jax.jit(
        traced,
        in_shardings=(state_shardings, (batch_sharding, batch_sharding)),
        out_shardings=(state_shardings, None),
        donate_argnums=0,
    )
    batch_sds = jax.ShapeDtypeStruct((b, t), jnp.int32, sharding=batch_sharding)
    return fn.lower(state_shapes, (batch_sds, batch_sds))


def build_eval_step(
    cfg: Config, mesh: Optional[Mesh] = None
) -> Callable[[TrainState, Tuple[jax.Array, jax.Array]], jax.Array]:
    model_cfg = cfg.model
    baked = uses_baked_layout(cfg, mesh)

    def eval_fn(state: TrainState, batch):
        x, y = batch
        with activation_mesh(mesh):
            # Pure CE (no MoE router aux): val_loss comparable across models.
            return transformer.loss_fn(
                state["params"], x, y, model_cfg, include_aux=False,
                blocks_baked=baked,
            )

    return jax.jit(eval_fn)


def build_eval_loop(
    cfg: Config, mesh: Optional[Mesh] = None
) -> Callable[[TrainState, Tuple[jax.Array, jax.Array]], jax.Array]:
    """Mean eval loss over a stacked batch set in ONE dispatch.

    batches: (x, y) each (N, B, T). A `lax.scan` over the N eval batches runs
    device-side — versus N individual eval_fn dispatches (each a host round
    trip on remote platforms), this is one launch and one scalar fetch.
    """
    model_cfg = cfg.model
    baked = uses_baked_layout(cfg, mesh)

    def eval_many(state: TrainState, batches: Tuple[jax.Array, jax.Array]) -> jax.Array:
        def body(acc, xy):
            x, y = xy
            with activation_mesh(mesh):
                loss = transformer.loss_fn(
                    state["params"], x, y, model_cfg, include_aux=False,
                    blocks_baked=baked,
                )
            return acc + loss, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), batches)
        return total / batches[0].shape[0]

    return jax.jit(eval_many)
