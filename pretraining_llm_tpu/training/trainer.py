"""Training orchestration: the host-side loop around the compiled SPMD step.

Capability superset of the reference Trainer
(`/root/reference/scripts/train_transformer.py:35-109`): LR scheduling, eval
cadence, and final save — plus what it lacks (SURVEY §5): periodic atomic
checkpoints, exact resume (params/opt/step/data-RNG), and structured metrics
with tokens/sec/chip + MFU. Batch sampling + H2D transfer run `data.prefetch`
batches ahead on a worker thread (loader.DevicePrefetcher) while resume stays
bit-exact — the checkpointed data-RNG state is the CONSUMED-batch frontier,
not the producer's; step dispatch is additionally async under JAX, the host
running ahead of the device between metric syncs.

The loop itself does no math — everything numerical lives in the compiled
step. Metric device→host syncs happen only at log boundaries so the device
queue stays full between logs.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pretraining_llm_tpu.config import Config
from pretraining_llm_tpu.data import loader as data_loader
from pretraining_llm_tpu.observability import ObservabilityHub
from pretraining_llm_tpu.parallel.mesh import build_mesh
from pretraining_llm_tpu.parallel.sharding import batch_pspec
from pretraining_llm_tpu.training import checkpoint as ckpt
from pretraining_llm_tpu.training import train_step as ts
from pretraining_llm_tpu.training.metrics import MetricsLogger, Throughput


@contextlib.contextmanager
def _watchdog_paused(watchdog):
    """Disarm the step watchdog around off-path host work (eval, checkpoint
    save, rollback restore): its timeout budgets a training step, and a save
    or eval longer than the timeout would falsely fire EXIT_WEDGED on a
    healthy run. No-op when the watchdog is off."""
    if watchdog is None:
        yield
        return
    watchdog.pause()
    try:
        yield
    finally:
        watchdog.resume()


class Trainer:
    def __init__(
        self,
        config: Config,
        *,
        mesh: Optional[Mesh] = None,
        train_iterator: Optional[Iterator[Tuple[np.ndarray, np.ndarray]]] = None,
        val_iterator: Optional[Iterator[Tuple[np.ndarray, np.ndarray]]] = None,
        synthetic_data: bool = False,
        resume: bool = True,
        logger: Optional[MetricsLogger] = None,
    ) -> None:
        self.config = config
        if config.train.debug_nans:
            from pretraining_llm_tpu.utils.debug import enable_nan_checks

            enable_nan_checks()
        from pretraining_llm_tpu.parallel.mesh import needs_mesh

        self.mesh = mesh if mesh is not None else (
            build_mesh(config.mesh) if needs_mesh(config.mesh) else None
        )
        # Own the logger's lifecycle only if we created it: train() closes an
        # owned logger's JSONL fd on every exit path (it reopens on demand).
        self._owns_logger = logger is None
        self.logger = logger or MetricsLogger(config.train.metrics_path)
        # Run-wide telemetry: event bus + spans + goodput + device/compile
        # counters. Host-side only; file sinks are config-gated and host0's.
        self.obs = ObservabilityHub(config.obs, is_host0=jax.process_index() == 0)
        self.step_fn = ts.build_train_step(config, self.mesh)
        self.eval_loop = ts.build_eval_loop(config, self.mesh)
        self.throughput = Throughput(config.model)
        self._synthetic_data = synthetic_data

        # --- data -------------------------------------------------------
        # Each process samples only its rows of the global batch
        # (batch_size / process_count); `_put` assembles the global sharded
        # array from the per-process pieces. Single-process this is the
        # identity arrangement.
        mcfg, dcfg, tcfg = config.model, config.data, config.train
        n_proc = jax.process_count()
        if tcfg.batch_size % n_proc != 0:
            raise ValueError(
                f"batch_size={tcfg.batch_size} must divide by process_count={n_proc}"
            )
        local_batch = tcfg.batch_size // n_proc
        if train_iterator is None:
            if synthetic_data:
                # Decorrelate hosts the same way the file loader does.
                host_seed = dcfg.sample_seed + 7919 * jax.process_index()
                train_iterator = data_loader.synthetic_iterator(
                    mcfg.vocab_size, mcfg.context_length, local_batch, host_seed
                )
            else:
                train_iterator = self._make_iterator(dcfg.train_path, dcfg.sample_seed)
        self.train_iterator = train_iterator
        # None = build a fresh deterministic eval set per evaluate() call;
        # a caller-injected iterator is consumed as a stream instead.
        self.val_iterator = val_iterator

        if self.mesh is not None:
            sharding = NamedSharding(self.mesh, batch_pspec(mcfg.sequence_parallel))
            eval_sharding = NamedSharding(
                self.mesh, P(None, *batch_pspec(mcfg.sequence_parallel))
            )
            if n_proc > 1:
                # Host-local rows -> global sharded array. Assumes only the
                # batch dim spans processes (seq stays within a host), the
                # standard pod layout: batch over DCN, model axes over ICI.
                def put(b):
                    global_shape = (tcfg.batch_size, mcfg.context_length)
                    return tuple(
                        jax.make_array_from_process_local_data(
                            sharding, np.ascontiguousarray(a), global_shape
                        )
                        for a in b
                    )

                def put_eval(b):
                    n = b[0].shape[0]
                    global_shape = (n, tcfg.batch_size, mcfg.context_length)
                    return tuple(
                        jax.make_array_from_process_local_data(
                            eval_sharding, np.ascontiguousarray(a), global_shape
                        )
                        for a in b
                    )

                self._put, self._put_eval = put, put_eval
            else:
                self._put = lambda b: jax.device_put(
                    (jnp.asarray(b[0]), jnp.asarray(b[1])), (sharding, sharding)
                )
                self._put_eval = lambda b: jax.device_put(
                    (jnp.asarray(b[0]), jnp.asarray(b[1])), (eval_sharding, eval_sharding)
                )
        else:
            self._put = lambda b: (jnp.asarray(b[0]), jnp.asarray(b[1]))
            self._put_eval = self._put

        # --- state: fresh init or resume-from-latest ----------------------
        # Resume goes through checkpoint.restore_latest: leftover tmp-<step>
        # partials are GC'd and a corrupt newest checkpoint (truncated leaf,
        # missing metadata) falls back to the previous good step instead of
        # dying. If step dirs exist but NONE load, refuse to silently
        # reinitialize — that would look like a fresh run to the supervisor
        # and quietly lose the whole training lineage.
        self.start_step = 0
        restored = None
        restore_t0 = time.perf_counter()
        if resume and ckpt.latest_checkpoint(tcfg.checkpoint_dir) is not None:
            # _synced: multi-host, all processes must adopt the SAME step —
            # a host-local load failure digging deeper on one host alone
            # would deadlock the first collective.
            with self.obs.spans.span("ckpt_restore"):
                restored = ckpt.restore_latest_synced(
                    tcfg.checkpoint_dir,
                    self._state_template(),
                    loader=self._checkpoint_loader,
                    on_skip=lambda path, e: self.logger.log({
                        "event": "checkpoint_skipped",
                        "path": path,
                        "error": repr(e)[:200],
                    }),
                )
            if restored is None:
                raise RuntimeError(
                    f"checkpoint dir {tcfg.checkpoint_dir!r} contains step "
                    "dirs but none are loadable; refusing to reinitialize "
                    "over a corrupt lineage (pass resume=False to override)"
                )
        if restored is not None:
            state, extra, restored_step = restored
            self.start_step = self._adopt_restored(state, extra)
            # Resume restore-time is restore-category wall-clock in the
            # goodput budget (the replayed steps are charged separately by
            # the step high-water mark).
            self.obs.bus.emit(
                "ckpt_restore",
                step=self.start_step,
                dur_s=time.perf_counter() - restore_t0,
            )
            self.logger.log({
                "event": "resumed",
                "from": os.path.join(tcfg.checkpoint_dir, f"step-{restored_step}"),
                "step": self.start_step,
            })
        else:
            state = ts.init_train_state(config, jax.random.key(tcfg.seed))
            if self.mesh is not None:
                state = ts.shard_train_state(state, self.mesh, config)
            else:
                state = jax.device_put(state)
            self.state = state
        # Input-pipeline overlap (VERDICT r2 next #8): sampling + H2D run on
        # a background thread, `data.prefetch` batches deep. Exact resume is
        # preserved because the prefetcher checkpoints the CONSUMED-batch RNG
        # frontier, not the producer's (see loader.DevicePrefetcher). Built
        # lazily on first train() so resume's set_state lands first.
        self._feed: Optional[data_loader.DevicePrefetcher] = None
        self._eval_batch_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        # Set by the SIGTERM handler (TPU preemption / maintenance events
        # deliver SIGTERM); the loop checkpoints and stops at the next step
        # boundary instead of dying mid-step.
        self._stop_requested = False
        # Why the last train() call ended: "completed" | "preempted" |
        # "anomaly_budget" | "anomaly_no_checkpoint". scripts/train.py maps
        # this to the resilience return-code contract for the supervisor.
        self.exit_reason = "completed"
        # Last step whose state is fully materialized — what the watchdog's
        # emergency checkpoint persists.
        self._completed_step = self.start_step

    def _make_iterator(self, path: str, seed: int):
        """File iterator: native C++ gatherer when built, numpy otherwise.

        Samples this process's rows only (batch_size / process_count) from
        this process's contiguous token-stream shard.
        """
        dcfg, tcfg, mcfg = self.config.data, self.config.train, self.config.model
        local_batch = tcfg.batch_size // jax.process_count()
        # Mixture specs ("a.bin:3,b.bin:1") route through the numpy
        # MixtureIterator; the native batcher reads exactly one memmap.
        if dcfg.use_native_batcher and not data_loader.is_mixture(path):
            try:
                from pretraining_llm_tpu.data.native_batcher import NativeBatchIterator

                return NativeBatchIterator(
                    path,
                    local_batch,
                    mcfg.context_length,
                    seed=seed,
                    shard_index=jax.process_index(),
                    shard_count=jax.process_count(),
                )
            except (RuntimeError, ValueError):
                pass  # no toolchain / unreadable: numpy path below
        return data_loader.get_batch_iterator(
            path,
            local_batch,
            mcfg.context_length,
            seed=seed,
            shard_index=jax.process_index(),
            shard_count=jax.process_count(),
        )

    # --- restore / rollback plumbing ----------------------------------
    def _state_template(self):
        """Structure/shape template without materializing a throwaway init."""
        return jax.eval_shape(
            lambda: ts.init_train_state(self.config, jax.random.key(self.config.train.seed))
        )

    def _checkpoint_loader(self, path: str, template: Any):
        """load_checkpoint plus the ema-compat fallback (used both at resume
        and by rollback's restore_latest)."""
        try:
            return ckpt.load_checkpoint(path, template)
        except ValueError as e:
            if "ema" in template and "missing leaves: ['ema" in str(e):
                # ema_decay was turned ON mid-run: the old checkpoints
                # carry no shadow. Load without it and seed the shadow
                # from the restored params (exactly what a fresh
                # init_train_state does) instead of dying.
                no_ema = {k: v for k, v in template.items() if k != "ema"}
                state, extra = ckpt.load_checkpoint(path, no_ema)
                state["ema"] = jax.tree.map(
                    lambda p: np.array(p, dtype=np.float32, copy=True),
                    state["params"],
                )
                self.logger.log({"event": "ema_seeded_from_params", "from": path})
                return state, extra
            raise

    def _adopt_restored(self, state: Any, extra: Dict[str, Any]) -> int:
        """Install a loaded checkpoint as the live train state (sharded for
        the active mesh) + data-RNG frontier. Returns the restored step."""
        # Migration guard: checkpoints written by this trainer are always
        # depth-major (save de-interleaves a baked state); a checkpoint
        # carrying the interleaved layout (e.g. a raw dump of a baked
        # state by external tooling) is converted back to canonical here
        # before shard_train_state re-bakes for the active mesh.
        if extra.get("block_layout", "depth_major") == "interleaved":
            state = ts.bake_state_layout(state, self.config, forward=False)
        if self.mesh is not None:
            state = ts.shard_train_state(state, self.mesh, self.config)
        else:
            state = jax.device_put(state)
        self.state = state
        rng_state = extra.get("data_rng")
        if rng_state is not None and hasattr(self.train_iterator, "set_state"):
            self.train_iterator.set_state(rng_state)
        return int(extra.get("step", 0))

    def _drop_feed(self) -> None:
        """Close the prefetch feed WITHOUT rewinding the source iterator —
        rollback callers overwrite its RNG state right after (so the queued
        poison-window batches are simply discarded). The close() join makes
        the subsequent set_state safe against a mid-draw worker."""
        if self._feed is not None:
            self._feed.close()
            self._feed = None

    def _skip_batches(self, n: int) -> None:
        """Advance the data-RNG frontier by drawing and discarding n batches
        (host-side sampling only — nothing is transferred to devices)."""
        for _ in range(n):
            next(self.train_iterator)

    # ------------------------------------------------------------------
    def _fresh_val_iterator(self):
        """A NEW deterministic iterator per evaluate() call: the same eval
        batches every time (and across resumes), so val_loss is comparable
        run-to-run — unlike sampling from an advancing stream."""
        mcfg, dcfg, tcfg = self.config.model, self.config.data, self.config.train
        eval_seed = dcfg.sample_seed + 104729  # fixed, never advanced
        if self._synthetic_data:
            local_batch = tcfg.batch_size // jax.process_count()
            return data_loader.synthetic_iterator(
                mcfg.vocab_size, mcfg.context_length,
                local_batch, eval_seed + 7919 * jax.process_index(),
            )
        return self._make_iterator(dcfg.val_path, eval_seed)

    def evaluate(self, iters: Optional[int] = None) -> float:
        """Mean val loss over `iters` fixed batches (reference: _evaluate,
        l.51-62 — but deterministic, and ONE device dispatch, not `iters`).

        The fixed-iterator eval set is identical every call by construction,
        so the sampled host stack is built once and cached per `iters`
        (VERDICT r2 weak #8: no `eval_iters x batch` re-sampling on the step
        budget every eval_interval). Caller-injected val streams advance, so
        they are never cached.
        """
        iters = iters or self.config.train.eval_iters
        if self.val_iterator is not None:
            it = self.val_iterator  # caller-injected stream: use as-is
            xs, ys = zip(*(next(it) for _ in range(iters)))
            batch = (np.stack(xs), np.stack(ys))
        else:
            batch = self._eval_batch_cache.get(iters)
            if batch is None:
                it = self._fresh_val_iterator()
                xs, ys = zip(*(next(it) for _ in range(iters)))
                batch = (np.stack(xs), np.stack(ys))
                self._eval_batch_cache[iters] = batch
        return float(self.eval_loop(self.state, self._put_eval(batch)))

    def save(self, step: int, *, sync: bool = False) -> Optional[str]:
        """Write a checkpoint. Call from ALL processes in a multi-host run —
        every process persists its own array shards and data-RNG state;
        process 0 alone writes the global metadata (the gating lives inside
        `checkpoint.save_checkpoint`, not here).

        With ``train.checkpoint_async`` (single-process only), the device ->
        host snapshot happens here synchronously — the saved state and
        data-RNG frontier are exactly this step's — but the file IO runs on
        a background thread and this returns None immediately. ``sync=True``
        forces a blocking save (failure/final paths).

        Every save is a span + ``ckpt_save`` event (``background=True`` when
        only the snapshot was measured and the write continues off-thread)."""
        t0 = time.perf_counter()
        with self.obs.spans.span("ckpt_save"):
            result = self._save_impl(step, sync=sync)
        self.obs.bus.emit(
            "ckpt_save",
            step=step,
            dur_s=time.perf_counter() - t0,
            background=result is None,
        )
        return result

    def _save_impl(self, step: int, *, sync: bool = False) -> Optional[str]:
        extra: Dict[str, Any] = {
            "step": step,
            "config": dataclasses.asdict(self.config),
            "preset": self.config.name,
            # Layout-version field (VERDICT r2 next #5): checkpoints are
            # ALWAYS canonical depth-major — a baked interleaved-PP state is
            # de-interleaved below before writing, so checkpoints round-trip
            # across pipeline layouts and the torch import/export scripts
            # never see the rank-major order.
            "block_layout": "depth_major",
        }
        local_extra: Dict[str, Any] = {}
        # With the prefetcher active, the source iterator's own RNG has run
        # ahead by the queue depth — checkpoint the consumed-batch frontier.
        rng_src = self._feed if self._feed is not None else self.train_iterator
        if hasattr(rng_src, "state") and rng_src.state() is not None:
            local_extra["data_rng"] = rng_src.state()
        kwargs = dict(
            extra=extra, local_extra=local_extra,
            keep=self.config.train.keep_checkpoints,
        )
        use_async = (
            self.config.train.checkpoint_async
            and not sync
            and jax.process_count() == 1
        )
        state_to_save = self.state
        if ts.uses_baked_layout(self.config, self.mesh):
            state_to_save = ts.bake_state_layout(self.state, self.config, forward=False)
        if not use_async:
            self.join_pending_save()  # never interleave writes to the dir
            return ckpt.save_checkpoint(
                self.config.train.checkpoint_dir, step, state_to_save, **kwargs
            )
        host_state = jax.device_get(state_to_save)  # pins this step's values
        self.join_pending_save()
        import threading

        def write():
            try:
                ckpt.save_checkpoint(
                    self.config.train.checkpoint_dir, step, host_state, **kwargs
                )
            except Exception as e:  # surfaced by the next join_pending_save
                self._pending_save_error = e

        self._pending_save_error: Optional[Exception] = None
        self._pending_save = threading.Thread(target=write, daemon=True)
        self._pending_save.start()
        return None

    def join_pending_save(self) -> None:
        """Wait for an in-flight async checkpoint write; re-raise its error.

        A swallowed write failure would let a run end 'successfully' with
        its checkpoints missing — the writer thread's exception must reach
        the training loop."""
        pending = getattr(self, "_pending_save", None)
        if pending is not None:
            pending.join()
            self._pending_save = None
            err = getattr(self, "_pending_save_error", None)
            if err is not None:
                self._pending_save_error = None
                raise RuntimeError("async checkpoint write failed") from err

    # Upper bound on the watchdog's emergency checkpoint write. On a real
    # chip wedge the device_get inside save can block behind the wedged
    # step; the watchdog must still exit EXIT_WEDGED rather than hang with
    # the run it is supposed to be guarding.
    EMERGENCY_SAVE_TIMEOUT_S = 60.0

    def _emergency_save(self) -> None:
        """Watchdog-thread best effort: persist the last COMPLETED step before
        the process exits EXIT_WEDGED. self.state is that step's output and
        still valid; the main thread is wedged, so everything here must be
        bounded — a stalled write is abandoned (atomic publish means an
        abandoned tmp-<step> is invisible and GC'd on the next restore).
        Multi-host saves barrier across processes and a wedge is usually
        collective, so only single-process runs attempt the save."""
        if jax.process_count() > 1:
            return
        # The wedged main thread never reaches train()'s finally, so stop an
        # in-flight profiler trace here — an open capture would otherwise be
        # lost with the process (os._exit runs no cleanup).
        prof = getattr(self, "_profiler", None)
        if prof is not None:
            prof.close()
        pending = getattr(self, "_pending_save", None)
        if pending is not None and pending.is_alive():
            pending.join(timeout=10.0)
            if pending.is_alive():
                return  # async writer wedged too; two writers would tear the dir
        self._pending_save = None
        self._pending_save_error = None
        step = self._completed_step
        self.logger.log({"event": "emergency_checkpoint", "step": step})
        import threading

        done = threading.Event()

        def write() -> None:
            try:
                self.save(step, sync=True)
            except Exception as e:
                self.logger.log({
                    "event": "emergency_save_failed", "error": repr(e)[:200],
                })
            finally:
                done.set()

        threading.Thread(target=write, daemon=True).start()
        if not done.wait(timeout=self.EMERGENCY_SAVE_TIMEOUT_S):
            self.logger.log({"event": "emergency_checkpoint_stalled", "step": step})

    # ------------------------------------------------------------------
    _NOT_INSTALLED = object()  # sentinel: handler could not be installed

    def _install_preemption_handler(self):
        """SIGTERM -> request a graceful stop. Returns the previous handler
        (restored by train's finally; may legitimately be None for a C-level
        handler) or _NOT_INSTALLED when installation failed (non-main
        thread / embedded interpreter)."""

        def handler(signum, frame):  # noqa: ARG001 — signal API shape
            self._stop_requested = True

        try:
            return signal.signal(signal.SIGTERM, handler)
        except ValueError:
            return Trainer._NOT_INSTALLED

    def _stop_synced(self) -> bool:
        """Whether ANY process requested a stop. Multi-host preemption can
        deliver SIGTERM to one host first; syncing the flag keeps every
        process entering the (collective) checkpoint save together. Called
        at log boundaries only — one tiny DCN allgather per log interval."""
        if jax.process_count() == 1:
            return self._stop_requested
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self._stop_requested], dtype=np.bool_)
        )
        return bool(np.asarray(flags).any())

    def train(self, steps: Optional[int] = None) -> Dict[str, float]:
        tcfg = self.config.train
        rcfg = self.config.resilience
        total = steps if steps is not None else tcfg.train_steps
        tokens_per_step = tcfg.batch_size * self.config.model.context_length
        is_host0 = jax.process_index() == 0
        self._stop_requested = False  # a prior run's SIGTERM must not persist
        self.exit_reason = "completed"
        prev_sigterm = self._install_preemption_handler()

        from pretraining_llm_tpu.utils.profiling import StepProfiler

        profiler = StepProfiler(tcfg.profile_dir, tcfg.profile_start, tcfg.profile_steps)
        # Exposed so the watchdog's emergency path (which os._exits past this
        # function's finally) can stop an in-flight trace too.
        self._profiler = profiler
        self.obs.start_run(self.start_step, total)

        # --- resilience wiring (resilience/): all host-side, every piece a
        # no-op unless its config knob is set. Anomaly decisions need no
        # cross-host sync: the observed metrics are replicated global-batch
        # scalars, so every process detects (and rolls back) identically.
        detector = rollback_mgr = faults = watchdog = None
        event_log = self.logger if is_host0 else None
        if rcfg.anomaly_detection:
            from pretraining_llm_tpu.resilience.anomaly import AnomalyDetector
            from pretraining_llm_tpu.resilience.rollback import RollbackManager

            detector = AnomalyDetector(rcfg)
            rollback_mgr = RollbackManager(rcfg, logger=event_log, bus=self.obs.bus)
        if rcfg.faults:
            from pretraining_llm_tpu.resilience.faults import FaultInjector

            faults = FaultInjector(
                rcfg.faults, start_step=self.start_step, logger=event_log,
                bus=self.obs.bus,
            )
        if rcfg.watchdog_timeout_s > 0:
            from pretraining_llm_tpu.resilience.watchdog import StepWatchdog

            watchdog = StepWatchdog(
                rcfg.watchdog_timeout_s,
                on_timeout=self._emergency_save,
                logger=event_log,
                bus=self.obs.bus,
            ).start()

        last: Dict[str, float] = {}
        step = self.start_step
        preempted = False
        try:
            while step < total:
                # Sampling + device_put run `data.prefetch` batches ahead on
                # a worker thread; the checkpointed data-RNG state remains
                # exactly the consumed-batch frontier (DevicePrefetcher
                # .state), so resume is still bit-exact. Built inside the
                # loop so a rollback's _drop_feed gets a fresh feed on the
                # rewound iterator. prefetch=0 keeps the synchronous loop.
                if self._feed is None and self.config.data.prefetch > 0:
                    self._feed = data_loader.DevicePrefetcher(
                        self.train_iterator, self._put, self.config.data.prefetch
                    )
                profiler.step(step)
                if faults is not None:
                    # Injected chaos compiles its own poisoning programs (one
                    # per param leaf); those aren't step-loop recompiles.
                    with self.obs.suppressed_compiles():
                        faults.maybe_fire(step, self)
                if self._feed is not None:
                    batch = next(self._feed)
                else:
                    batch = self._put(next(self.train_iterator))
                self.state, metrics = self.step_fn(self.state, batch)
                self.throughput.tick(tokens_per_step)
                step += 1
                self._completed_step = step
                if step == self.start_step + 1:
                    # First completed step: the initial jit is behind us, so
                    # any later backend compile is a recompile worth an event.
                    self.obs.mark_warm(step)
                if watchdog is not None:
                    watchdog.heartbeat()  # first beat arms it: compile excluded

                at_log = step % tcfg.log_interval == 0 or step == total
                if at_log and self._stop_synced():
                    preempted = True
                    self.exit_reason = "preempted"
                    self.obs.bus.emit("preempt", step=step)
                    if is_host0:
                        self.logger.log({"event": "preempted", "step": step})
                    with _watchdog_paused(watchdog):
                        self.save(step, sync=True)
                    break
                off_path = False
                if at_log:
                    last = {k: float(v) for k, v in metrics.items()}  # device sync
                    last.update(self.throughput.window())
                    # Emit the step_window event + interval samplers; merges
                    # the cumulative goodput fraction into the log record.
                    last.update(self.obs.on_log_boundary(step, last, last))
                    if is_host0:
                        self.logger.log({"step": step, **last})
                    if detector is not None:
                        anomaly = detector.observe(step, last)
                        if anomaly is not None:
                            if is_host0:
                                self.logger.log(anomaly.as_event())
                            # The restore's device_put programs compile fresh;
                            # suppressed_compiles keeps them out of the
                            # recompile classification (they aren't a step-loop
                            # shape leak).
                            with _watchdog_paused(watchdog), self.obs.suppressed_compiles():
                                outcome = rollback_mgr.handle(self, anomaly)
                            if outcome == "rolled_back":
                                detector.reset()
                                step = rollback_mgr.last_restored
                                self._completed_step = step
                                self.throughput.reset_clock()
                                continue
                            if outcome in ("exhausted", "no_checkpoint"):
                                self.exit_reason = (
                                    "anomaly_budget"
                                    if outcome == "exhausted"
                                    else "anomaly_no_checkpoint"
                                )
                                break
                            # "suppressed": inside the cooldown; keep going.
                if tcfg.eval_interval > 0 and step % tcfg.eval_interval == 0:
                    with _watchdog_paused(watchdog):
                        with self.obs.timed_event("eval", step=step) as ev:
                            val_loss = self.evaluate()
                            ev["val_loss"] = val_loss
                    # Standard derived views of the same number: perplexity
                    # and bits-per-token (nats -> bits) for cross-run and
                    # cross-tokenizer comparison. 700 ~ float64 exp overflow;
                    # past it ppl reports inf rather than a silently-wrong
                    # clamped value.
                    eval_metrics = {
                        "val_loss": val_loss,
                        "val_ppl": float(np.exp(val_loss)) if val_loss < 700 else float("inf"),
                        "val_bits_per_token": val_loss / float(np.log(2.0)),
                    }
                    last.update(eval_metrics)
                    off_path = True
                    if is_host0:
                        self.logger.log({"step": step, **eval_metrics})
                if tcfg.checkpoint_interval > 0 and step % tcfg.checkpoint_interval == 0:
                    off_path = True
                    # ALL processes: each writes its own shards; the barrier
                    # and metadata gating are inside save_checkpoint.
                    with _watchdog_paused(watchdog):
                        self.save(step)
                if off_path:
                    self.throughput.reset_clock()  # keep eval/ckpt time out of step_ms
        except Exception as e:
            # Failure recovery (SURVEY §5): persist the last good state before
            # propagating. self.state is the step-(k-1) output and still valid
            # even though the failing step's donated inputs are gone. All
            # processes attempt the save: step failures are collective in SPMD
            # (same program, same data-dependent fault); a genuinely host-local
            # fault leaves the others stuck in a collective anyway, and the
            # distributed runtime's barrier timeout is the backstop for both.
            self.obs.bus.emit("failure", step=step, error=repr(e)[:200])
            if is_host0:
                self.logger.log({"event": "failure", "step": step, "error": repr(e)[:200]})
            try:
                with _watchdog_paused(watchdog):
                    self.save(step, sync=True)
            except Exception as save_err:  # keep the original error primary
                if is_host0:
                    self.logger.log({"event": "emergency_save_failed", "error": repr(save_err)[:200]})
            raise
        finally:
            profiler.close()
            if watchdog is not None:
                # Disarm BEFORE the exit-path joins below: a slow final
                # checkpoint is not a wedged step.
                watchdog.stop()
            if prev_sigterm is not Trainer._NOT_INSTALLED:
                signal.signal(signal.SIGTERM, prev_sigterm)
            # Join the in-flight async write on EVERY exit path — incl.
            # KeyboardInterrupt/SystemExit, which bypass `except Exception`;
            # exiting would kill the daemon writer mid-write and lose the
            # newest checkpoint. Don't let a join failure mask an exception
            # that is already propagating.
            import sys as _sys

            # Capture BEFORE the inner try: inside `except RuntimeError:` the
            # exc_info is always the RuntimeError being handled, so testing it
            # there can never distinguish "clean exit" from "already
            # propagating" — which silently swallowed async-write failures on
            # the clean-exit path (ADVICE r2, medium).
            propagating = _sys.exc_info()[0] is not None
            # Release the prefetch feed: stop the worker thread and free the
            # queued device batches (HBM). Determinism across train() calls
            # is preserved by REWINDING the source iterator to the consumed
            # frontier — the discarded queue is re-drawn identically by the
            # next call's fresh feed. Sources without set_state (plain
            # generators) can't rewind, so their live feed is kept instead.
            if self._feed is not None and hasattr(self.train_iterator, "set_state"):
                frontier = self._feed.state()
                if self._feed.close():  # worker provably dead: rewind is safe
                    if frontier is not None:
                        self.train_iterator.set_state(frontier)
                else:
                    # Wedged worker (blocked >10s in a draw/transfer): the
                    # rewind would race its in-flight draw, so skip it —
                    # an IN-PROCESS continuation may skip up to depth+1
                    # batches (said loudly below); checkpoint resume is
                    # unaffected (the saved frontier is already exact).
                    if is_host0:
                        self.logger.log({
                            "event": "prefetch_worker_wedged",
                            "step": step,
                            "note": "feed dropped without RNG rewind; "
                            "in-process continuation loses stream continuity",
                        })
                self._feed = None
            try:
                self.join_pending_save()
            except RuntimeError:
                if is_host0:
                    self.logger.log({"event": "async_checkpoint_failed", "step": step})
                if not propagating:
                    raise
            finally:
                # run_end must be the stream's last event; the clean paths
                # emit it AFTER the final save below, so only a propagating
                # exception (incl. KeyboardInterrupt/SystemExit) closes the
                # run here — exit_reason is still "completed" then, which
                # would mislabel the stream.
                if propagating:
                    self.obs.end_run("exception", step=step)
                # Flush + release the JSONL fd on EVERY exit path (clean,
                # preempted, rollback-budget, exception). Only a logger this
                # Trainer created is closed — and MetricsLogger reopens on
                # the next log(), so repeated train() calls keep working.
                # getattr: tests swap in bare capture objects post-init.
                if self._owns_logger:
                    close = getattr(self.logger, "close", None)
                    if close is not None:
                        close()

        if preempted:
            self.obs.end_run(self.exit_reason, step=step)
            return last  # already checkpointed at the stop step
        # Final save only for a genuinely completed run, labeled with the
        # step actually reached. After an anomaly break the live state is
        # the poisoned (possibly NaN) one; persisting it — as step-<total>
        # no less, mislabeled and newest in the dir — would hand any later
        # resume corrupted params with a desynced data-RNG frontier.
        if (
            tcfg.save_final
            and self.exit_reason == "completed"
            and (tcfg.checkpoint_interval <= 0 or step % tcfg.checkpoint_interval != 0)
        ):
            self.save(step, sync=True)
        self.obs.end_run(self.exit_reason, step=step)
        return last
