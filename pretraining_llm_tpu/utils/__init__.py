from pretraining_llm_tpu.utils.hardware import device_peak_flops  # noqa: F401
from pretraining_llm_tpu.utils.pytree import tree_num_params, tree_cast  # noqa: F401
