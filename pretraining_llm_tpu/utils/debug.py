"""Numerics debugging: the JAX-side analog of sanitizers (SURVEY §5).

The reference has no anomaly detection of any kind. Here:
  - `enable_nan_checks()`: jax_debug_nans/jax_debug_infs — every compiled
    function re-runs op-by-op on a NaN and pinpoints the producing primitive;
  - `checked_loss`: a checkify-wrapped loss that turns non-finite loss and
    out-of-range token ids into structured, jit-safe errors (usable inside
    the compiled step, where Python asserts cannot live).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import checkify

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.models import transformer


def enable_nan_checks(nans: bool = True, infs: bool = False) -> None:
    jax.config.update("jax_debug_nans", nans)
    jax.config.update("jax_debug_infs", infs)


def checked_loss(
    params: Any, tokens: jax.Array, targets: jax.Array, cfg: ModelConfig
) -> Tuple[checkify.Error, jax.Array]:
    """Loss with traced assertions: call via `checkify.checkify`d jit.

    Example:
        err, loss = jax.jit(functools.partial(checked_loss, cfg=cfg))(p, x, y)
        err.throw()  # raises with the failed predicate if any
    """

    def body(params, tokens, targets):
        checkify.check(jnp.all(tokens >= 0), "negative token id")
        checkify.check(
            jnp.all(tokens < cfg.vocab_size),
            "token id out of range for vocab {v}",
            v=jnp.int32(cfg.vocab_size),
        )
        loss = transformer.loss_fn(params, tokens, targets, cfg)
        checkify.check(jnp.isfinite(loss), "non-finite loss")
        return loss

    checked = checkify.checkify(body)
    return checked(params, tokens, targets)
