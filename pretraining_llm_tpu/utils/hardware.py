"""Per-chip peak FLOPs table for MFU accounting.

The reference prints only wall-clock deltas (`train_transformer.py:98-101`);
MFU = achieved_flops / peak_flops is the BASELINE.json headline metric, so the
framework needs to know what "peak" is for the chip it runs on.

Published bf16 peak matmul throughput per chip (Google Cloud TPU docs).
"""

from __future__ import annotations

import jax

_PEAK_BF16_FLOPS = {
    # substring of jax.Device.device_kind (lowercased) -> FLOP/s
    "v6e": 918e12,
    "trillium": 918e12,
    "v5p": 459e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}

_DEFAULT_CPU_FLOPS = 1e11  # nominal, so MFU math never divides by zero


def device_peak_flops(device: jax.Device | None = None) -> float:
    """Peak bf16 FLOP/s for one chip; a nominal constant on CPU."""
    if device is None:
        device = jax.devices()[0]
    kind = device.device_kind.lower()
    for key, flops in _PEAK_BF16_FLOPS.items():
        if key in kind:
            return flops
    return _DEFAULT_CPU_FLOPS
