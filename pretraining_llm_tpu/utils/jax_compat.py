"""Shims over JAX API drift so the codebase runs on 0.4.x and 0.5+ installs.

The code targets the modern surface (`jax.shard_map`, `jax.sharding
.get_abstract_mesh`, `AxisType`); on older installs the same machinery lives
under `jax.experimental.shard_map` with a different keyword spelling
(`check_rep` / `auto` instead of `check_vma` / `axis_names`) and the abstract
trace-context mesh is internal-only. Routing every call through this module
keeps version probes out of model and parallelism code.
"""

from __future__ import annotations

from typing import Any, Optional, Set

import jax

_HAS_MODERN_SHARD_MAP = hasattr(jax, "shard_map")

if not _HAS_MODERN_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
    """`jax.shard_map` with the modern keyword surface on either JAX.

    `axis_names` (modern: the axes the region is MANUAL over) maps to the
    legacy `auto` keyword as its complement over the mesh's axes.
    """
    if _HAS_MODERN_SHARD_MAP:
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kwargs,
        )
    kwargs = {}
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _legacy_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, **kwargs,
    )


def get_abstract_mesh() -> Optional[Any]:
    """The trace-context AbstractMesh, or None when unset/unavailable.

    Modern JAX returns an empty AbstractMesh sentinel outside any context;
    0.4.x keeps the context internal and stores a bare `()` when unset —
    both normalize to None here so callers only branch on truthiness.
    """
    try:
        context = jax.sharding.get_abstract_mesh()
    except AttributeError:
        from jax._src import mesh as _mesh_internal

        context = _mesh_internal.get_abstract_mesh()
        if not isinstance(context, _mesh_internal.AbstractMesh):
            return None
    if context is None or getattr(context, "empty", False):
        return None
    return context


def manual_axis_names(abstract_mesh: Any) -> Set[str]:
    """Mesh axes the current trace context is Manual over; empty when the
    install predates typed mesh axes (0.4.x: shard_map regions are manual
    over every mapped axis, but the context doesn't record it)."""
    axis_types = getattr(abstract_mesh, "axis_types", None)
    axis_type_enum = getattr(jax.sharding, "AxisType", None)
    if abstract_mesh is None or axis_types is None or axis_type_enum is None:
        return set()
    return {
        name
        for name, kind in zip(abstract_mesh.axis_names, axis_types)
        if kind == axis_type_enum.Manual
    }
