"""Platform selection for entry points.

`PLLM_PLATFORM=cpu|tpu` forces the JAX backend before first device use —
needed because some environments pin `JAX_PLATFORMS` at the process level
(e.g. a preregistered TPU plugin) where the env var alone cannot be
overridden from the command line. `PLLM_CPU_DEVICES=N` additionally requests
N virtual CPU devices (multi-chip simulation off-hardware).
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    platform = os.environ.get("PLLM_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
        n = os.environ.get("PLLM_CPU_DEVICES")
        if platform == "cpu" and n:
            jax.config.update("jax_num_cpu_devices", int(n))
