"""Tracing/profiling: jax.profiler capture around training steps.

The reference's only observability is wall-clock deltas printed at eval
boundaries (`/root/reference/scripts/train_transformer.py:75,98-101`). Here
(SURVEY §5): on-demand XLA trace capture (TensorBoard/Perfetto-readable
xplane dumps) scoped to a step window, plus `annotate` for named_scope
regions that show up in the trace timeline.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a profiler trace into `logdir` (view with TensorBoard)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named scope that appears on the profiler timeline (and in HLO names)."""
    return jax.named_scope(name)


class StepProfiler:
    """Capture a [start, stop) window of training steps.

    Used by the train CLI: `--profile logdir --profile_start 10 --profile_steps 5`.
    """

    def __init__(self, logdir: str, start_step: int, n_steps: int) -> None:
        self.logdir = logdir
        self.start_step = start_step
        self.stop_step = start_step + n_steps
        self._active = False

    def step(self, step: int) -> None:
        if not self.logdir:
            return
        if step == self.start_step and not self._active:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop_step and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        """Stop an in-flight capture. Idempotent and exception-safe: called
        from every train() exit path (including the watchdog's emergency
        path and mid-window exceptions), where a stop_trace failure must
        not mask the original error or block the emergency save."""
        if not self._active:
            return
        self._active = False
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
