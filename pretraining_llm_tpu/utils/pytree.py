"""Small pytree helpers used across the framework."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def tree_num_params(tree: Any) -> int:
    return sum(int(leaf.size) for leaf in jax.tree.leaves(tree))


def tree_cast(tree: Any, dtype: Any) -> Any:
    """Cast all inexact leaves of a pytree (ints left untouched)."""
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.inexact) else x, tree
    )


def tree_global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))
