#!/bin/bash
# Probe the axon TPU backend every ~4 minutes; append one status line per
# probe to data/captures/backend_probe_r05.log. Each probe is a fresh
# process under a hard timeout (JAX caches a failed backend per-process).
# Round-5 driver for "pivot to hardware work the moment the chip returns".
LOG=${1:-/root/repo/data/captures/backend_probe_r05.log}
INTERVAL=${2:-240}
mkdir -p "$(dirname "$LOG")"
while true; do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  OUT=$(timeout 150 python -u -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256, 256))
print('ALIVE', d[0].device_kind, float((x @ x).sum()))
" 2>&1)
  RC=$?  # timeout's status: 124 = hang-killed, else python's own exit
  LINE=$(printf '%s\n' "$OUT" | grep -E "ALIVE|Error" | tail -1)
  if [ -z "$LINE" ]; then LINE="DEAD (hang/timeout rc=$RC)"; fi
  echo "$TS $LINE" >> "$LOG"
  sleep "$INTERVAL"
done
