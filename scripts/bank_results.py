#!/usr/bin/env python
"""Summarize tpu_capture.jsonl into a BASELINE.md-ready markdown table.

Reads every record, keeps the LATEST successful (rc=0) record per stage,
and prints grouped markdown rows — so after a capture campaign the
documentation step is copy-paste, not JSONL archaeology.

Usage:  python scripts/bank_results.py [--in tpu_capture.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--in", dest="inp", default="tpu_capture.jsonl")
    args = ap.parse_args()
    if not os.path.exists(args.inp):
        print(f"no {args.inp}")
        return 1
    latest_ok: dict = {}
    latest_any: dict = {}
    order: list = []
    with open(args.inp) as f:
        for ln in f:
            try:
                r = json.loads(ln)
            except json.JSONDecodeError:
                continue
            stage = r.get("stage", "")
            if not stage or stage in (
                "campaign-start", "canary", "backend-recovered",
                "recovery-budget-exhausted",
            ):
                continue
            if stage not in latest_any:
                order.append(stage)
            latest_any[stage] = r
            # A later FAILED rerun must not hide an earlier banked success
            # (the docstring's contract): successes and failures tracked
            # separately; a stage is "failed" only if it never succeeded.
            if r.get("rc") == 0 and "error" not in r:
                latest_ok[stage] = r

    train_rows, other_rows, failed = [], [], []
    for stage in order:
        r = latest_ok.get(stage)
        if r is None:
            r_any = latest_any[stage]
            if "delta" in r_any and "error" not in r_any:
                # Completed measurement that FAILED its numeric bar (e.g.
                # parity delta > 0.01 now exits 1): the delta is the banked
                # result — show it, don't reduce it to a bare rc.
                failed.append((stage, f"delta {r_any['delta']} "
                                      f"(pass={r_any.get('pass')})"))
            else:
                failed.append(
                    (stage, r_any.get("error", f"rc={r_any.get('rc')}")))
            continue
        metric = r.get("metric", "")
        if metric.startswith("mfu_") and "tokens_per_sec_chip" in r:
            train_rows.append(
                f"| {r.get('attention','?')}, {r.get('remat','?')} remat, "
                f"{r.get('ce_impl','?')} CE, batch {r.get('batch','?')}"
                f"{' (' + metric[4:].replace('_train','') + ')' if 'gpt2-124m' not in metric else ''} "
                f"| {r['tokens_per_sec_chip']/1e3:.1f}k | {r.get('value',0)*100:.1f}% "
                f"| stage {stage} |"
            )
        elif metric or "value" in r:
            unit = r.get("unit", "")
            other_rows.append(
                f"| {stage} | {r.get('value','?')} {unit} "
                f"| {metric or '-'} |"
            )
        else:
            other_rows.append(f"| {stage} | ok | - |")

    if train_rows:
        print("### Train throughput rows\n")
        print("| Config | tokens/sec/chip | MFU | notes |")
        print("|---|---|---|---|")
        print("\n".join(train_rows))
    if other_rows:
        print("\n### Other stages\n")
        print("| Stage | Value | Metric |")
        print("|---|---|---|")
        print("\n".join(other_rows))
    if failed:
        print("\n### Failed / errored stages\n")
        for stage, err in failed:
            print(f"- {stage}: {str(err)[:160]}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
