#!/bin/bash
# Auto-commit newly banked capture records so bench.py's `last_banked`
# fallback can cite a COMMITTED record (value + capture path + commit
# hash) even when the campaign lands numbers while no one is driving the
# session. Polls every ~7 min; commits ONLY the campaign log file, and
# logs git failures (a lost index-lock race or missing identity must be
# visible, not silently skipped until the next interval).
LOG=${1:-/root/repo/data/captures/tpu_capture_r05.jsonl}
INTERVAL=${2:-420}
cd /root/repo || exit 1
while true; do
  sleep "$INTERVAL"
  if [ -n "$(git status --porcelain -- "$LOG" 2>/dev/null)" ]; then
    ERR=$(git add -- "$LOG" 2>&1 \
          && git commit -q -m "Capture log: bank r5 campaign records ($(date -u +%H:%M)Z)" \
               -- "$LOG" 2>&1)
    if [ $? -eq 0 ]; then
      echo "$(date -u +%H:%M)Z committed new capture records"
    else
      echo "$(date -u +%H:%M)Z commit failed: $ERR" >&2
    fi
  fi
done
