#!/bin/bash
# Round-4 session-3 capture runner: chained tpu_capture invocations in
# VERDICT-r3 priority order (profile-first after the driver race; risky
# tier unlocks because all three criticals are already banked in the
# campaign log). Each group polls for backend recovery (90 min pool per
# invocation) so a wedge mid-sequence degrades to continuous polling
# instead of a dead campaign.
cd /root/repo || exit 1
OUT=data/captures/tpu_capture_r04.jsonl
for spec in \
  "mfu|--mfu-budget 1500" \
  "batch-sweep|" \
  "profile,profile-decode|" \
  "mfu-350m,mfu-1b|" \
  "sweep2|" \
  "decode,decode-int8,decode-unroll|" \
  "trainer|" \
  "unroll-sweep,sweep-top,ctx8k|" \
; do
  stages="${spec%%|*}"; extra="${spec#*|}"
  echo "[runner $(date -u +%H:%M:%S)] starting stages=$stages"
  # shellcheck disable=SC2086
  python scripts/tpu_capture.py --stages "$stages" --out "$OUT" \
    --recovery-wait 5400 $extra
  rc=$?  # capture BEFORE the echo's $(date) resets $?
  echo "[runner $(date -u +%H:%M:%S)] stages=$stages rc=$rc"
done
echo "[runner $(date -u +%H:%M:%S)] all stage groups done"
