#!/usr/bin/env bash
# CPU smoke gate: everything must at least compile, and the resilience +
# checkpoint recovery paths must pass end-to-end (including the slow
# subprocess drills the tier-1 `-m "not slow"` run excludes).
#
# Usage: bash scripts/ci_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pretraining_llm_tpu scripts

JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py \
    "tests/test_training.py::test_checkpoint_roundtrip_and_exact_resume" \
    "tests/test_training.py::test_checkpoint_retention" \
    "tests/test_training.py::test_checkpoint_sharded_leaf_reassembly" \
    -q -p no:cacheprovider "$@"
