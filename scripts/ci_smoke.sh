#!/usr/bin/env bash
# CPU smoke gate: everything must at least compile, and the resilience +
# checkpoint recovery paths must pass end-to-end (including the slow
# subprocess drills the tier-1 `-m "not slow"` run excludes).
#
# Usage: bash scripts/ci_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pretraining_llm_tpu scripts

JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py \
    tests/test_observability.py \
    "tests/test_training.py::test_checkpoint_roundtrip_and_exact_resume" \
    "tests/test_training.py::test_checkpoint_retention" \
    "tests/test_training.py::test_checkpoint_sharded_leaf_reassembly" \
    -q -p no:cacheprovider "$@"

# Observability gate: a tiny synthetic run must emit parseable metrics +
# event streams, and the offline analyzer must accept BOTH with --strict
# (any unparseable line — e.g. a bare NaN token — fails the gate). This is
# what keeps the JSONL schema a checked contract rather than a convention.
OBS_TMP=$(mktemp -d)
trap 'rm -rf "$OBS_TMP"' EXIT
JAX_PLATFORMS=cpu python scripts/train.py --preset tiny --data synthetic \
    --no-resume --steps 8 --obs-dir "$OBS_TMP/obs" \
    --override train.metrics_path="$OBS_TMP/metrics.jsonl" \
    train.checkpoint_dir="$OBS_TMP/ckpt" train.log_interval=2 \
    train.eval_interval=4 train.eval_iters=1 train.checkpoint_interval=4 \
    > "$OBS_TMP/train.out"
test -s "$OBS_TMP/obs/events.jsonl"   # event stream must exist and be non-empty
test -s "$OBS_TMP/obs/spans.trace.json"
python scripts/obs_report.py --strict \
    "$OBS_TMP/metrics.jsonl" "$OBS_TMP/obs/events.jsonl"

# Serving decode gate: 8 requests through the deep-pipelined scheduler
# (depth 2) on a tiny random-init model must finish, emit a token count,
# and report the host-blocked window telemetry — the end-to-end proof
# that dispatch/reap/admission survive outside the pytest fixtures.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax, dataclasses
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
eng = ServingEngine(params, cfg, max_batch=4, n_blocks=32, block_size=8,
                    temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                    admit_batch=2)
rng = np.random.default_rng(0)
rids = [eng.submit(rng.integers(0, cfg.vocab_size, size=5 + i).tolist(), 8)
        for i in range(8)]
out = eng.run(pipeline=True)
assert set(out) == set(rids), (sorted(out), rids)
assert all(len(out[r]) == 8 for r in rids), {r: len(out[r]) for r in rids}
st = eng.stats
assert st["windows_reaped"] == st["windows"] > 0, st
assert st["host_blocked_s"] >= 0.0, st
print(f"serving smoke ok: {st['tokens']} tokens, {st['windows']} windows, "
      f"host_blocked_s={st['host_blocked_s']:.4f}")
EOF
