#!/usr/bin/env bash
# CPU smoke gate: everything must at least compile, and the resilience +
# checkpoint recovery paths must pass end-to-end (including the slow
# subprocess drills the tier-1 `-m "not slow"` run excludes).
#
# Usage: bash scripts/ci_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pretraining_llm_tpu scripts

JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py \
    tests/test_observability.py \
    "tests/test_training.py::test_checkpoint_roundtrip_and_exact_resume" \
    "tests/test_training.py::test_checkpoint_retention" \
    "tests/test_training.py::test_checkpoint_sharded_leaf_reassembly" \
    -q -p no:cacheprovider "$@"

# Observability gate: a tiny synthetic run must emit parseable metrics +
# event streams, and the offline analyzer must accept BOTH with --strict
# (any unparseable line — e.g. a bare NaN token — fails the gate). This is
# what keeps the JSONL schema a checked contract rather than a convention.
OBS_TMP=$(mktemp -d)
trap 'rm -rf "$OBS_TMP"' EXIT
JAX_PLATFORMS=cpu python scripts/train.py --preset tiny --data synthetic \
    --no-resume --steps 8 --obs-dir "$OBS_TMP/obs" \
    --override train.metrics_path="$OBS_TMP/metrics.jsonl" \
    train.checkpoint_dir="$OBS_TMP/ckpt" train.log_interval=2 \
    train.eval_interval=4 train.eval_iters=1 train.checkpoint_interval=4 \
    > "$OBS_TMP/train.out"
test -s "$OBS_TMP/obs/events.jsonl"   # event stream must exist and be non-empty
test -s "$OBS_TMP/obs/spans.trace.json"
python scripts/obs_report.py --strict \
    "$OBS_TMP/metrics.jsonl" "$OBS_TMP/obs/events.jsonl"
