#!/usr/bin/env bash
# CPU smoke gate: everything must at least compile, and the resilience +
# checkpoint recovery paths must pass end-to-end (including the slow
# subprocess drills the tier-1 `-m "not slow"` run excludes).
#
# Usage: bash scripts/ci_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pretraining_llm_tpu scripts

JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py \
    tests/test_observability.py \
    tests/test_integrity.py \
    tests/test_process_fleet.py \
    tests/test_multihost_fleet.py \
    "tests/test_training.py::test_checkpoint_roundtrip_and_exact_resume" \
    "tests/test_training.py::test_checkpoint_retention" \
    "tests/test_training.py::test_checkpoint_sharded_leaf_reassembly" \
    -q -p no:cacheprovider "$@"

# Observability gate: a tiny synthetic run must emit parseable metrics +
# event streams, and the offline analyzer must accept BOTH with --strict
# (any unparseable line — e.g. a bare NaN token — fails the gate). This is
# what keeps the JSONL schema a checked contract rather than a convention.
OBS_TMP=$(mktemp -d)
trap 'rm -rf "$OBS_TMP"' EXIT
JAX_PLATFORMS=cpu python scripts/train.py --preset tiny --data synthetic \
    --no-resume --steps 8 --obs-dir "$OBS_TMP/obs" \
    --override train.metrics_path="$OBS_TMP/metrics.jsonl" \
    train.checkpoint_dir="$OBS_TMP/ckpt" train.log_interval=2 \
    train.eval_interval=4 train.eval_iters=1 train.checkpoint_interval=4 \
    > "$OBS_TMP/train.out"
test -s "$OBS_TMP/obs/events.jsonl"   # event stream must exist and be non-empty
test -s "$OBS_TMP/obs/spans.trace.json"
python scripts/obs_report.py --strict \
    "$OBS_TMP/metrics.jsonl" "$OBS_TMP/obs/events.jsonl"

# Serving decode gate: 8 requests through the deep-pipelined scheduler
# (depth 2) on a tiny random-init model must finish, emit a token count,
# and report the host-blocked window telemetry — the end-to-end proof
# that dispatch/reap/admission survive outside the pytest fixtures.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax, dataclasses
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
eng = ServingEngine(params, cfg, max_batch=4, n_blocks=32, block_size=8,
                    temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                    admit_batch=2)
rng = np.random.default_rng(0)
rids = [eng.submit(rng.integers(0, cfg.vocab_size, size=5 + i).tolist(), 8)
        for i in range(8)]
out = eng.run(pipeline=True)
assert set(out) == set(rids), (sorted(out), rids)
assert all(len(out[r]) == 8 for r in rids), {r: len(out[r]) for r in rids}
st = eng.stats
assert st["windows_reaped"] == st["windows"] > 0, st
assert st["host_blocked_s"] >= 0.0, st
print(f"serving smoke ok: {st['tokens']} tokens, {st['windows']} windows, "
      f"host_blocked_s={st['host_blocked_s']:.4f}")
EOF

# Prefix-cache gate: the SAME shared-prefix workload with the cache off
# and on must produce bit-identical greedy outputs, score real hits, and
# leave the allocator fully accounted for at drain (idle + cold-cached ==
# n_blocks - 1; after flush every block is back on the free list).
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax, dataclasses
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
rng = np.random.default_rng(3)
head = rng.integers(0, cfg.vocab_size, size=16).tolist()
prompts = [head + rng.integers(0, cfg.vocab_size, size=3 + i).tolist()
           for i in range(6)]

def run(cache):
    eng = ServingEngine(params, cfg, max_batch=2, n_blocks=24, block_size=8,
                        temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                        prefix_cache=cache)
    rids = [eng.submit(p, 8) for p in prompts]
    out = eng.run(pipeline=True)
    return [out[r] for r in rids], eng

off, _ = run(False)
on, eng = run(True)
assert off == on, "prefix cache changed greedy outputs"
st = eng.stats
assert st["prefix_cache_hits"] > 0, st
assert st["prefix_cache_hit_tokens"] > 0, st
assert eng.alloc.available + eng.prefix_cache.evictable == 24 - 1, (
    eng.alloc.available, eng.prefix_cache.evictable)
eng.prefix_cache.flush()
assert eng.alloc.available == 24 - 1, eng.alloc.available
print(f"prefix cache smoke ok: {st['prefix_cache_hits']} hits, "
      f"{st['prefix_cache_hit_tokens']} cached tokens, "
      f"{st['prefill_tokens']} prefill tokens")
EOF

# Chunked-prefill gate: the SAME mixed-length workload with chunking off
# and on (6-token budget, so every longer prompt takes several chunks)
# must produce bit-identical greedy outputs, actually stream chunks, and
# leave the allocator fully accounted for at drain.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax, dataclasses
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
rng = np.random.default_rng(7)
prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
           for n in (21, 4, 17, 9, 26, 12)]

def run(chunk):
    eng = ServingEngine(params, cfg, max_batch=3, n_blocks=32, block_size=8,
                        temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                        prefill_chunk_tokens=chunk)
    rids = [eng.submit(p, 8) for p in prompts]
    out = eng.run(pipeline=True)
    return [out[r] for r in rids], eng

off, _ = run(0)
on, eng = run(6)
assert off == on, "chunked prefill changed greedy outputs"
st = eng.stats
assert st["prefill_chunks"] > len(prompts), st  # long prompts took several
assert st["prefill_chunk_tokens"] == sum(len(p) for p in prompts), st
assert eng.alloc.available == 32 - 1, eng.alloc.available
print(f"chunked prefill smoke ok: {st['prefill_chunks']} chunks, "
      f"{st['prefill_chunk_tokens']} chunk tokens, "
      f"interleaved={st['chunk_windows_interleaved']} "
      f"dedicated={st['chunk_windows_dedicated']}")
EOF

# Gateway gate: the ONLINE path end-to-end over real HTTP. A tiny random-
# init model behind EngineLoop + ServingGateway serves 4 concurrent
# requests — one SSE-streaming, one cancelled mid-generation by dropping
# the connection — all must terminate, and /metrics must report the
# request counters (completed + cancelled) in Prometheus text format.
JAX_PLATFORMS=cpu python - <<'EOF'
import dataclasses, json, socket, threading, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
eng = ServingEngine(params, cfg, max_batch=4, n_blocks=32, block_size=8,
                    temperature=0.0, steps_per_sched=2, pipeline_depth=2)
loop = EngineLoop(eng, admission=AdmissionController(max_queue_depth=8))
gw = ServingGateway(loop, port=0)
loop.start(); gw.start()
base = f"http://127.0.0.1:{gw.port}"

def post(payload):
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())

results = {}
def full(name, n):
    results[name] = post({"prompt": [1, 2, 3, int(n)], "max_new_tokens": 8})
def sse(name):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 8,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    toks, final = [], None
    with urllib.request.urlopen(req, timeout=120) as r:
        for line in r:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            ev = json.loads(line[6:])
            if ev.get("done"): final = ev
            elif "token" in ev: toks.append(ev["token"])
    results[name] = {"tokens": toks, "final": final}
def cancelled(name):
    # Open a streaming request, read one token, drop the socket: the
    # gateway must cancel the request and free its row/pool blocks.
    s = socket.create_connection(("127.0.0.1", gw.port), timeout=120)
    body = json.dumps({"prompt": [9, 9, 9], "max_new_tokens": 48,
                       "stream": True}).encode()
    s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body)
    buf = b""
    while b"data: " not in buf:
        chunk = s.recv(4096)
        assert chunk, buf
        buf += chunk
    s.close()
    results[name] = {"cancel_sent": True}

threads = [threading.Thread(target=full, args=("a", 1)),
           threading.Thread(target=full, args=("b", 2)),
           threading.Thread(target=sse, args=("c",)),
           threading.Thread(target=cancelled, args=("d",))]
for t in threads: t.start()
for t in threads: t.join(timeout=180)
assert not any(t.is_alive() for t in threads), "a gateway request hung"

assert results["a"]["status"] == "done" and results["a"]["n_tokens"] == 8, results["a"]
assert results["b"]["status"] == "done" and results["b"]["n_tokens"] == 8, results["b"]
assert results["c"]["final"]["status"] == "done", results["c"]
assert len(results["c"]["tokens"]) == 8, results["c"]

# The dropped connection must surface as a cancellation (or a completed
# request if the drop raced the final token) — and every row/block must
# be back: allocator idle == n_blocks - 1 (block 0 reserved).
import time
for _ in range(200):
    m = loop.metrics()
    if m["active_requests"] == 0 and eng.alloc.available == 32 - 1:
        break
    time.sleep(0.05)
assert eng.alloc.available == 32 - 1, eng.alloc.available
assert m["completed"] + m["cancelled"] == 4, m

with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
    assert json.loads(r.read())["status"] == "ok"
with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
assert "pllm_serving_completed" in text, text[:400]
assert "pllm_serving_submitted" in text, text[:400]
assert "pllm_serving_http_requests_total" in text, text[:400]

# Readiness is distinct from liveness: a draining loop keeps /healthz
# green (the process is fine) but must drop out of the balancer.
import urllib.error
with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
    assert json.loads(r.read())["status"] == "ready"
loop.begin_drain()
try:
    urllib.request.urlopen(f"{base}/readyz", timeout=30)
    raise AssertionError("/readyz must 503 while draining")
except urllib.error.HTTPError as e:
    assert e.code == 503, e.code
    assert json.loads(e.read())["status"] == "not-ready"
with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
    assert json.loads(r.read())["status"] == "ok"

gw.stop(); loop.stop()
print(f"gateway smoke ok: {m}")
EOF

# Tracing gate: the full observability wiring under load. A traced gateway
# serves a seeded loadgen run (every request carrying a W3C traceparent);
# /metrics must be lint-clean Prometheus with histogram counts that agree
# with the terminal-event stream, every response must echo its trace id,
# and the exported Chrome trace must contain a COMPLETE span tree per
# request — enforced by obs_report --strict --slo over the same artifacts
# a production run would ship.
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.tracing import Tracer

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
eng = ServingEngine(params, cfg, max_batch=4, n_blocks=32, block_size=8,
                    temperature=0.0, steps_per_sched=2, pipeline_depth=2)
recorder = SpanRecorder()
bus = EventBus(os.path.join(tmp, "serving_events.jsonl"))
registry = MetricsRegistry("pllm_serving_")
loop = EngineLoop(eng, admission=AdmissionController(max_queue_depth=16),
                  bus=bus, tracer=Tracer(recorder, sample=1.0, seed=11),
                  registry=registry)
gw = ServingGateway(loop, port=0, healthz_stale_after_s=30.0)
loop.start(); gw.start()
base = f"http://127.0.0.1:{gw.port}"

spec = LoadSpec(n_requests=8, mode="closed", concurrency=3, seed=5,
                vocab_size=cfg.vocab_size, max_new_min=4, max_new_max=8,
                send_traceparent=True)
report = run_http(base, spec)
by_status = {}
for o in report.outcomes:
    by_status[o.status] = by_status.get(o.status, 0) + 1
    assert o.trace_id, f"request {o.index} lost its trace id: {o}"
assert by_status == {"done": 8}, by_status

with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
count_line = next(
    l for l in text.splitlines()
    if l.startswith("pllm_serving_e2e_seconds_count")
)
assert float(count_line.split()[-1]) == 8.0, count_line

gw.stop(); loop.stop(); bus.close()
terminals = 0
with open(os.path.join(tmp, "serving_events.jsonl")) as f:
    for line in f:
        rec = json.loads(line)
        if rec.get("event") in ("req_done", "req_cancelled",
                                "req_expired", "req_error"):
            terminals += 1
            assert rec.get("trace_id"), rec
assert terminals == 8, terminals
assert recorder.dropped == 0, recorder.dropped
recorder.export(os.path.join(tmp, "serving_trace.json"))
print(f"tracing smoke ok: {by_status}, {terminals} terminal events")
EOF

# The offline analyzer must accept the traced run with --strict --slo:
# every trace tree complete, every SLO-miss attributable, segments
# summing to e2e. A generous e2e SLO keeps this a structural check, not
# a performance bet on the CI machine.
python scripts/obs_report.py --strict --slo --slo_e2e_s 60 \
    "$OBS_TMP/serving_events.jsonl" --trace "$OBS_TMP/serving_trace.json" \
    > "$OBS_TMP/slo_report.out"
grep -q "traces=8 done=8" "$OBS_TMP/slo_report.out" || {
    echo "obs_report --slo missing the expected 8 traces"; exit 1; }

# Capacity gate: the attribution pipeline under REAL pool pressure. A
# deliberately tiny pool (2 rows, 7 allocatable blocks) behind the full
# HTTP stack forces preemptions and cold-cache evictions during a seeded
# traced loadgen run; /debug/engine's pool accounting must agree with the
# allocator, and obs_report --capacity --strict must produce a waterfall
# that sums to wall time within 1% with every decision joined to a known
# trace — the same contract the unit tests check, proved over the wire.
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.tracing import Tracer

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
# 2 rows over 7 allocatable blocks of 8 tokens: two 10-12 token prompts
# decoding 20-24 tokens each cannot both fit, so growth MUST preempt and
# the prefix cache MUST shed cold blocks.
eng = ServingEngine(params, cfg, max_batch=2, n_blocks=8, block_size=8,
                    temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                    prefix_cache=True)
bus = EventBus(os.path.join(tmp, "capacity_events.jsonl"))
registry = MetricsRegistry("pllm_serving_")
loop = EngineLoop(eng, admission=AdmissionController(max_queue_depth=8),
                  bus=bus, tracer=Tracer(SpanRecorder(), sample=1.0, seed=3),
                  registry=registry)
gw = ServingGateway(loop, port=0)
loop.start(); gw.start()
base = f"http://127.0.0.1:{gw.port}"

spec = LoadSpec(n_requests=6, mode="closed", concurrency=4, seed=11,
                vocab_size=cfg.vocab_size, prompt_len_min=10,
                prompt_len_max=12, max_new_min=20, max_new_max=24,
                send_traceparent=True)
# /debug/requests only lists LIVE requests, so poll it while the load
# runs and keep the richest snapshot we see.
import threading, time
live_snap, stop_poll = [], threading.Event()
def poll():
    while not stop_poll.is_set():
        with urllib.request.urlopen(f"{base}/debug/requests", timeout=30) as r:
            snap = json.loads(r.read())["requests"]
        if len(snap) > len(live_snap):
            live_snap[:] = snap
        time.sleep(0.02)
poller = threading.Thread(target=poll); poller.start()
report = run_http(base, spec)
stop_poll.set(); poller.join(timeout=30)
assert all(o.status == "done" for o in report.outcomes), report.outcomes
assert live_snap and all(r["trace_id"] for r in live_snap), live_snap
assert any(r["phase"] == "decode" and r["row"] is not None
           for r in live_snap), live_snap

with urllib.request.urlopen(f"{base}/debug/engine", timeout=30) as r:
    dbg = json.loads(r.read())
pool = dbg["pool"]
assert pool["total"] == 8 - 1, pool
assert pool["free"] + pool["cold"] + pool["live"] == pool["total"], pool
assert pool["free"] == eng.alloc.available, (pool, eng.alloc.available)
assert pool["cold"] == eng.prefix_cache.evictable, pool
assert dbg["stats"]["preemptions"] >= 1, dbg["stats"]
assert dbg["decisions"]["counts"].get("preempt", 0) >= 1, dbg["decisions"]
assert dbg["decisions"]["counts"].get("evict_cold", 0) >= 1, dbg["decisions"]
assert dbg["windows_sampled"] > 0, dbg

gw.stop(); loop.stop(); bus.close()
print(f"capacity smoke ok: {dbg['stats']['preemptions']} preemptions, "
      f"{dbg['decisions']['counts']}")
EOF

# The analyzer must accept the pressured run with --capacity --strict:
# waterfall segments summing to wall within 1%, every decision joined to
# a known trace, and a named binding constraint.
python scripts/obs_report.py --capacity --strict \
    "$OBS_TMP/capacity_events.jsonl" > "$OBS_TMP/capacity_report.out"
grep -q "binding constraint:" "$OBS_TMP/capacity_report.out" || {
    echo "obs_report --capacity missing the binding constraint"; exit 1; }

# Fleet gate: a 2-replica fleet behind real HTTP with an injected
# replica_crash mid-burst. Every accepted request must reach a terminal
# (zero lost), at least one must have been redriven to the survivor, the
# crashed replica must relaunch, and the merged /metrics exposition must
# stay lint-clean with per-replica labels. The event stream then has to
# survive the offline fleet auditor with --strict (request conservation,
# redrive attribution, recovery timing).
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, time, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))

def make_engine():
    return ServingEngine(params, cfg, max_batch=2, n_blocks=24, block_size=8,
                         temperature=0.0, steps_per_sched=4, pipeline_depth=2)

bus = EventBus(os.path.join(tmp, "fleet_events.jsonl"))
faults = ServingFaultInjector("replica_crash@req2:r0", bus=bus)
registry = MetricsRegistry("pllm_serving_")
replicas = [
    Replica(i, make_engine, bus=bus, fault_injector=faults,
            admission_factory=lambda reg: AdmissionController(
                max_queue_depth=8, registry=reg))
    for i in range(2)
]
router = Router(replicas, bus=bus, registry=registry,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=0.2).start()
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

spec = LoadSpec(n_requests=12, mode="closed", concurrency=4, seed=9,
                vocab_size=cfg.vocab_size, max_new_min=6, max_new_max=10)
report = run_http(base, spec)

lost = spec.n_requests - len(report.outcomes)
assert lost == 0, f"{lost} requests lost"
statuses = {}
for o in report.outcomes:
    statuses[o.status] = statuses.get(o.status, 0) + 1
assert statuses == {"done": 12}, statuses
summary = report.summary()
assert summary["redrives_total"] >= 1, summary
assert router.counters["ejects"] >= 1, router.counters

# The crashed replica must come back (backoff relaunch) before we stop.
deadline = time.monotonic() + 10.0
while time.monotonic() < deadline:
    if all(rep.accepting for rep in router.replicas):
        break
    time.sleep(0.05)
assert router.replicas[0].generation >= 2, router.replicas[0].debug_snapshot()

with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
    assert json.loads(r.read())["status"] == "ready"
with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert "pllm_serving_redrives_total" in text, text[:400]
assert 'replica="0"' in text and 'replica="1"' in text, text[:400]

gw.stop(); router.stop(); bus.close()
print(f"fleet smoke ok: {statuses}, "
      f"redrives={router.counters['redrives']}, "
      f"ejects={router.counters['ejects']}")
EOF

# The fleet auditor must accept the drill with --strict: conservation
# (every fleet submit reaches exactly one terminal), redrives joined to
# known requests, and a measured recovery for the ejected replica.
python scripts/obs_report.py --fleet --strict \
    "$OBS_TMP/fleet_events.jsonl" > "$OBS_TMP/fleet_report.out"
grep -q "lost=0" "$OBS_TMP/fleet_report.out" || {
    echo "obs_report --fleet did not report lost=0"; exit 1; }
grep -q "redrive cost" "$OBS_TMP/fleet_report.out" || {
    echo "obs_report --fleet missing the redrive cost section"; exit 1; }

# Process-fleet gate: the same drill across a REAL process boundary. Two
# out-of-process workers (each its own engine in its own interpreter)
# behind the router and real HTTP; one worker is SIGKILLed right after
# accepting its 3rd request. Zero lost, at least one redrive onto the
# survivor, the dead worker relaunched as a fresh process, and — after
# shutdown — no orphaned worker processes left on the host.
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import json, os, time, urllib.request
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

tmp = os.environ["OBS_TMP"]
bus = EventBus(os.path.join(tmp, "proc_fleet_events.jsonl"))
faults = ServingFaultInjector("worker_kill@req3:r0", bus=bus)
registry = MetricsRegistry("pllm_serving_")
spec = {
    "preset": "tiny",
    "init_seed": 0,
    "model_overrides": {"compute_dtype": "float32"},
    "engine": {"max_batch": 2, "n_blocks": 24, "block_size": 8,
               "temperature": 0.0, "steps_per_sched": 4,
               "pipeline_depth": 2},
    "admission": {"max_queue_depth": 8},
}
replicas = [
    RemoteReplica(i, spec, bus=bus, fault_injector=faults)
    for i in range(2)
]
router = Router(replicas, bus=bus, registry=registry,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=0.2).start()
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

load = LoadSpec(n_requests=12, mode="closed", concurrency=4, seed=9,
                vocab_size=replicas[0].engine.cfg.vocab_size,
                max_new_min=6, max_new_max=10)
report = run_http(base, load)

lost = load.n_requests - len(report.outcomes)
assert lost == 0, f"{lost} requests lost"
statuses = {}
for o in report.outcomes:
    statuses[o.status] = statuses.get(o.status, 0) + 1
assert statuses == {"done": 12}, statuses
summary = report.summary()
assert summary["redrives_total"] >= 1, summary
assert router.counters["ejects"] >= 1, router.counters

# The killed worker must come back as a NEW process (backoff relaunch).
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    if all(rep.accepting for rep in router.replicas):
        break
    time.sleep(0.05)
assert router.replicas[0].generation >= 2, router.replicas[0].debug_snapshot()

with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
    assert json.loads(r.read())["status"] == "ready"
with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert "pllm_serving_worker_spawns_total" in text, text[:400]
assert "pllm_serving_replica_relaunch_total" in text, text[:400]

gw.stop(); router.stop(); bus.close()
print(f"process-fleet smoke ok: {statuses}, "
      f"redrives={router.counters['redrives']}, "
      f"relaunches={router.counters['relaunches']}")
EOF

# No orphaned workers may survive the shutdown (the stdin-watch orphan
# guard plus the router teardown must account for every child).
if pgrep -f "pretraining_llm_tpu.frontend.worker" > /dev/null; then
    echo "orphaned worker processes left after shutdown:"
    pgrep -af "pretraining_llm_tpu.frontend.worker"
    exit 1
fi

# The offline auditor must join the process death to the redrives it
# caused and the relaunch that recovered it.
python scripts/obs_report.py --fleet --strict \
    "$OBS_TMP/proc_fleet_events.jsonl" > "$OBS_TMP/proc_fleet_report.out"
grep -q "lost=0" "$OBS_TMP/proc_fleet_report.out" || {
    echo "obs_report --fleet (process) did not report lost=0"; exit 1; }
grep -q "worker death" "$OBS_TMP/proc_fleet_report.out" || {
    echo "obs_report --fleet missing the worker death join"; exit 1; }

# Multi-host gate: two PRE-SPAWNED workers serving on localhost TCP
# (the router does not own their lifecycle — it attaches by address with
# a shared token, exactly the cross-host deployment shape). Replica 0 is
# blackholed mid-burst: its reads hang and its writes buffer, which is a
# PARTITION, not a connection drop. The router must detect it via lease
# expiry, bump the fence generation, and redrive onto the survivor with
# zero lost requests; on heal, the frames the partitioned worker kept
# streaming (stamped with the old generation) must be counted and
# DROPPED — never forwarded as duplicate tokens. Workers must survive
# router detach (they are not the router's children).
MH_SPEC='{"preset":"tiny","init_seed":0,"model_overrides":{"compute_dtype":"float32"},"engine":{"max_batch":2,"n_blocks":24,"block_size":8,"temperature":0.0,"steps_per_sched":4,"pipeline_depth":2},"admission":{"max_queue_depth":8}}'
JAX_PLATFORMS=cpu python -m pretraining_llm_tpu.frontend.worker \
    --spec-json "$MH_SPEC" --listen 127.0.0.1:0 --token mh-smoke-token \
    > "$OBS_TMP/mh_worker0.out" 2> "$OBS_TMP/mh_worker0.err" &
MH_W0=$!
JAX_PLATFORMS=cpu python -m pretraining_llm_tpu.frontend.worker \
    --spec-json "$MH_SPEC" --listen 127.0.0.1:0 --token mh-smoke-token \
    > "$OBS_TMP/mh_worker1.out" 2> "$OBS_TMP/mh_worker1.err" &
MH_W1=$!

mh_port() {  # wait for the worker's one-line stdout announce, echo port
    local out="$1" port="" i
    for i in $(seq 1 360); do
        if [ -s "$out" ]; then
            port=$(head -n 1 "$out" | python -c 'import json,sys; print(json.loads(sys.stdin.readline())["worker"]["port"])' 2>/dev/null) && \
                [ -n "$port" ] && break
            port=""
        fi
        sleep 0.5
    done
    if [ -z "$port" ]; then
        echo "listen worker never announced a port ($out):" >&2
        cat "${out%.out}.err" >&2
        return 1
    fi
    echo "$port"
}
MH_ADDR0="127.0.0.1:$(mh_port "$OBS_TMP/mh_worker0.out")"
MH_ADDR1="127.0.0.1:$(mh_port "$OBS_TMP/mh_worker1.out")"

JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" MH_ADDR0="$MH_ADDR0" \
    MH_ADDR1="$MH_ADDR1" python - <<'EOF'
import json, os, time, urllib.request
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

tmp = os.environ["OBS_TMP"]
bus = EventBus(os.path.join(tmp, "mh_events.jsonl"))
faults = ServingFaultInjector("partition@req2:r0", bus=bus)
registry = MetricsRegistry("pllm_serving_")
spec = {
    "preset": "tiny",
    "init_seed": 0,
    "model_overrides": {"compute_dtype": "float32"},
    "engine": {"max_batch": 2, "n_blocks": 24, "block_size": 8,
               "temperature": 0.0, "steps_per_sched": 4,
               "pipeline_depth": 2},
    "admission": {"max_queue_depth": 8},
}
replicas = []
for i in range(2):
    s = dict(spec)
    s["attach"] = os.environ[f"MH_ADDR{i}"]
    s["token"] = "mh-smoke-token"
    replicas.append(RemoteReplica(i, s, bus=bus, fault_injector=faults,
                                  lease_s=0.8))
# eject_backoff must outlast the drill: a relaunch attempt would tear
# down the blackholed gate and discard the stale frames heal must count.
router = Router(replicas, bus=bus, registry=registry,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=60.0).start()
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

load = LoadSpec(n_requests=12, mode="closed", concurrency=4, seed=9,
                vocab_size=replicas[0].engine.cfg.vocab_size,
                max_new_min=6, max_new_max=10)
report = run_http(base, load)

lost = load.n_requests - len(report.outcomes)
assert lost == 0, f"{lost} requests lost"
statuses = {}
for o in report.outcomes:
    statuses[o.status] = statuses.get(o.status, 0) + 1
assert statuses == {"done": 12}, statuses
summary = report.summary()
assert summary["redrives_total"] >= 1, summary
assert router.counters["ejects"] >= 1, router.counters
assert replicas[0].mode == "attach" and replicas[0].proc is None
assert replicas[0]._c_lease.value >= 1, "lease never expired"
assert replicas[0].fence >= 1, "fence generation never bumped"

# Heal the partition: everything the blackholed worker streamed while
# fenced must now arrive, be counted as stale, and be dropped.
replicas[0].heal()
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    if replicas[0]._c_fenced.value >= 1:
        break
    time.sleep(0.05)
assert replicas[0]._c_fenced.value >= 1, "no stale frames were fenced"

with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert "pllm_serving_lease_expiries_total" in text, text[:400]
assert "pllm_serving_fenced_frames_total" in text, text[:400]

gw.stop(); router.stop(); bus.close()
print(f"multi-host smoke ok: {statuses}, "
      f"redrives={router.counters['redrives']}, "
      f"lease_expiries={int(replicas[0]._c_lease.value)}, "
      f"fenced={int(replicas[0]._c_fenced.value)}")
EOF

# Detach is not death: the pre-spawned workers must still be alive after
# the router shut down (attach mode never owns the worker lifecycle).
for pid in "$MH_W0" "$MH_W1"; do
    kill -0 "$pid" 2>/dev/null || {
        echo "pre-spawned worker $pid died across router detach"; exit 1; }
done
kill "$MH_W0" "$MH_W1" 2>/dev/null || true
wait "$MH_W0" "$MH_W1" 2>/dev/null || true

# The offline auditor must join the injected partition to its detection
# (lease expiry, not fence drop — the fence notice lands at heal) and to
# the redrives it caused, with zero lost requests.
python scripts/obs_report.py --fleet --strict \
    "$OBS_TMP/mh_events.jsonl" > "$OBS_TMP/mh_report.out"
grep -q "lost=0" "$OBS_TMP/mh_report.out" || {
    echo "obs_report --fleet (multi-host) did not report lost=0"; exit 1; }
grep -q "detected by lease_expiry" "$OBS_TMP/mh_report.out" || {
    echo "obs_report --fleet missing the partition detection join"; exit 1; }

# Integrity gate: a 2-replica fleet with golden probes on and a
# corrupt_kv_page injected on replica 0 mid-burst — the flipped page is
# the probes' own shared prefix block (kv_checksum stays OFF, so the ONLY
# signal is wrong probe output). The sentinel must quarantine the replica,
# zero client requests may be lost and every output must be served by a
# healthy path, the merged /metrics must stay lint-clean with the typed
# integrity counters, and the offline auditor must accept the event
# stream with --integrity --strict (detection attributed, no orphan
# divergence, no unanswered corruption).
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, time, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))

def make_engine():
    return ServingEngine(params, cfg, max_batch=2, n_blocks=24, block_size=8,
                         temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                         prefix_cache=True)

bus = EventBus(os.path.join(tmp, "integrity_events.jsonl"))
faults = ServingFaultInjector("corrupt_kv_page@req1:r0", bus=bus)
registry = MetricsRegistry("pllm_serving_")
replicas = [
    Replica(i, make_engine, bus=bus, fault_injector=faults)
    for i in range(2)
]
router = Router(replicas, bus=bus, registry=registry,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=0.2, probe_interval_s=0.05,
                probe_timeout_s=60.0).start()
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

# Let probe #0 publish its shared prefix page on replica 0 — the fault
# targets the lowest cached block id, i.e. exactly that page.
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    eng = router.replicas[0].engine
    if eng is not None and eng.prefix_cache.cached_block_ids():
        break
    time.sleep(0.05)
assert router.replicas[0].engine.prefix_cache.cached_block_ids(), \
    "probe page never published"

spec = LoadSpec(n_requests=12, mode="closed", concurrency=4, seed=9,
                vocab_size=cfg.vocab_size, max_new_min=6, max_new_max=10)
report = run_http(base, spec)

lost = spec.n_requests - len(report.outcomes)
assert lost == 0, f"{lost} requests lost"
statuses = {}
for o in report.outcomes:
    statuses[o.status] = statuses.get(o.status, 0) + 1
assert statuses == {"done": 12}, statuses

deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    if router.counters["quarantines"] >= 1:
        break
    time.sleep(0.05)
assert router.counters["quarantines"] >= 1, router.counters
quar = [d for d in router.decisions.tail()
        if d["decision"] == "quarantine"]
assert quar and quar[0]["replica"] == 0, quar

# The quarantined replica relaunches with fresh weights and a clean pool.
deadline = time.monotonic() + 10.0
while time.monotonic() < deadline:
    if all(rep.accepting for rep in router.replicas):
        break
    time.sleep(0.05)
assert router.replicas[0].generation >= 2, router.replicas[0].debug_snapshot()

with urllib.request.urlopen(f"{base}/debug/engine", timeout=30) as r:
    dbg = json.loads(r.read())
integ = dbg["fleet"]["integrity"]
assert integ["enabled"] and integ["quarantines"] >= 1, integ
with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert "pllm_serving_integrity_probes_total" in text, text[:400]
assert "pllm_serving_quarantines_total" in text, text[:400]

gw.stop(); router.stop(); bus.close()
print(f"integrity smoke ok: {statuses}, "
      f"probes={router.counters['probes']}, "
      f"quarantines={router.counters['quarantines']}")
EOF

# The integrity auditor must accept the drill with --strict: the fired
# corruption attributed to a detector, every strict probe divergence
# answered by a quarantine, and no unanswered quarantine.
python scripts/obs_report.py --integrity --strict \
    "$OBS_TMP/integrity_events.jsonl" > "$OBS_TMP/integrity_report.out"
grep -q "detected by" "$OBS_TMP/integrity_report.out" || {
    echo "obs_report --integrity missing the detection attribution"; exit 1; }

# Quantized serving gate: the int8-kv engine behind the full HTTP stack.
# Weights are quantized ONCE up front (per-channel int8 + scale leaves),
# the KV pool holds int8 codes + bf16 scales, and the SAME seeded
# workload (shared prefix + chunked prefill + depth-2 pipelining) run
# twice must produce bit-identical greedy outputs — determinism is the
# contract that makes the integrity sentinel's bit-exact probes possible
# at all. The gate also proves the capacity claim (an equal HBM budget
# holds strictly more int8-kv blocks than bf16) and that /metrics stays
# lint-clean with the quant_dtype const-label and the KV-pool-bytes
# gauges wired.
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, threading, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import quantize as quantize_mod
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.tracing import Tracer

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
qparams = quantize_mod.quantize_params_for_serving(params, cfg)

# Capacity claim at equal HBM: blocks the int8-kv layout fits into the
# bf16 pool's byte budget must strictly exceed the bf16 block count.
eng_bf = ServingEngine(params, cfg, max_batch=2, n_blocks=24, block_size=8,
                       temperature=0.0)
eng_q = ServingEngine(qparams, cfg, max_batch=2, n_blocks=24, block_size=8,
                      temperature=0.0, quantize="int8-kv")
info_bf, info_q = eng_bf.pool_info(), eng_q.pool_info()
assert info_q["kv_dtype"] == "int8", info_q
assert info_q["kv_scale_dtype"] == "bfloat16", info_q
assert info_q["bytes_per_block"] < info_bf["bytes_per_block"], (info_q, info_bf)
blocks_at_budget = info_bf["pool_bytes"] // info_q["bytes_per_block"]
assert blocks_at_budget > info_bf["n_blocks"], (blocks_at_budget, info_bf)
del eng_bf, eng_q

head = [7, 3, 11, 2, 19, 5, 23, 1, 13, 4, 17, 6]   # shared 12-token prefix
prompts = [head + [31 + 7 * i, 41 + 3 * i, 9 + i][: 2 + i % 3]
           for i in range(8)]

def run_stack(tag):
    eng = ServingEngine(qparams, cfg, max_batch=2, n_blocks=24, block_size=8,
                        temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                        prefix_cache=True, prefill_chunk_tokens=6,
                        quantize="int8-kv")
    bus = EventBus(os.path.join(tmp, f"quant_events_{tag}.jsonl"))
    registry = MetricsRegistry("pllm_serving_",
                               const_labels={"quant_dtype": "int8-kv"})
    loop = EngineLoop(eng, admission=AdmissionController(max_queue_depth=16),
                      bus=bus, tracer=Tracer(SpanRecorder(), sample=1.0,
                                             seed=13),
                      registry=registry)
    gw = ServingGateway(loop, port=0)
    loop.start(); gw.start()
    base = f"http://127.0.0.1:{gw.port}"
    outs = {}
    def post(i, p):
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt": p, "max_new_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            outs[i] = json.loads(r.read())
    threads = [threading.Thread(target=post, args=(i, p))
               for i, p in enumerate(prompts)]
    for t in threads: t.start()
    for t in threads: t.join(timeout=180)
    assert not any(t.is_alive() for t in threads), "a quantized request hung"
    assert all(outs[i]["status"] == "done" and len(outs[i]["tokens"]) == 8
               for i in range(len(prompts))), outs
    with urllib.request.urlopen(f"{base}/debug/engine", timeout=30) as r:
        dbg = json.loads(r.read())
    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        text = r.read().decode()
    gw.stop(); loop.stop(); bus.close()
    return [outs[i]["tokens"] for i in range(len(prompts))], dbg, text

out1, dbg, text = run_stack("run1")
out2, _, _ = run_stack("run2")
assert out1 == out2, "int8-kv greedy outputs are not run-to-run identical"

layout = dbg["pool_layout"]
assert layout["quantize"] == "int8-kv", layout
assert layout["kv_dtype"] == "int8", layout
problems = lint_exposition(text)
assert not problems, problems
assert 'quant_dtype="int8-kv"' in text, text[:400]
assert "pllm_serving_kv_pool_bytes" in text, text[:400]
assert "pllm_serving_kv_pool_bytes_per_block" in text, text[:400]
print(f"quantized smoke ok: {len(prompts)} bit-identical requests, "
      f"{layout['bytes_per_block']}B/block int8-kv vs "
      f"{info_bf['bytes_per_block']}B/block bf16 "
      f"({blocks_at_budget} blocks at the bf16 budget)")
EOF

# The capacity auditor must accept the quantized run with --strict: the
# cap_window records now carry the pool's dtype/bytes-per-block identity,
# and the waterfall must still sum and join as before.
python scripts/obs_report.py --capacity --strict \
    "$OBS_TMP/quant_events_run1.jsonl" > "$OBS_TMP/quant_capacity_report.out"
grep -q "binding constraint:" "$OBS_TMP/quant_capacity_report.out" || {
    echo "obs_report --capacity missing the binding constraint (quantized)"; exit 1; }

# Quantized sentinel gate: the corrupt_weights drill on an int8-kv fleet.
# Both replicas serve the SAME pre-quantized params (one quantization up
# front is what keeps the fleet's weight fingerprints and golden probes
# unanimous); the probes are therefore pinned WITHIN the quantized graph
# and compared quantized-vs-quantized, bit-for-bit. Negating a weight
# leaf on replica 0 must trip the sentinel (fingerprint drift / probe
# divergence), quarantine the replica, and redrive its in-flight long
# request to the survivor. Tokens committed inside the detection window
# ran on corrupted weights — that latency is the sentinel's documented
# cost — so bit-identity is asserted where the contract actually holds:
# a post-recovery replay of the whole workload on the healed fleet must
# match a clean single-engine int8-kv reference exactly.
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, threading, time, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import quantize as quantize_mod
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
qparams = quantize_mod.quantize_params_for_serving(params, cfg)

prompts = [[7, 3, 11, 2, 19, 5] + [31 + 7 * i, 9 + i] for i in range(6)]

# Clean reference: every prompt through a single healthy int8-kv engine.
ref_eng = ServingEngine(qparams, cfg, max_batch=2, n_blocks=24, block_size=8,
                        temperature=0.0, steps_per_sched=4,
                        quantize="int8-kv")
rids = [ref_eng.submit(p, 8) for p in prompts]
ref_out = ref_eng.run()
reference = [ref_out[r] for r in rids]
del ref_eng

def make_engine():
    return ServingEngine(qparams, cfg, max_batch=2, n_blocks=24, block_size=8,
                         temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                         prefix_cache=True, quantize="int8-kv")

bus = EventBus(os.path.join(tmp, "quant_integrity_events.jsonl"))
faults = ServingFaultInjector("corrupt_weights@req1:r0", bus=bus)
registry = MetricsRegistry("pllm_serving_",
                           const_labels={"quant_dtype": "int8-kv"})
replicas = [
    Replica(i, make_engine, bus=bus, fault_injector=faults,
            registry_labels={"quant_dtype": "int8-kv"})
    for i in range(2)
]
router = Router(replicas, bus=bus, registry=registry,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=0.2, probe_interval_s=0.05,
                probe_timeout_s=60.0).start()
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

def post(p, max_new, out, key):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": p, "max_new_tokens": max_new}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=180) as r:
        out[key] = json.loads(r.read())

# A long decode pinned in flight while the drill lands: short requests
# walk replica 0's per-replica request count up to the fault trigger,
# and the long one must survive its replica's quarantine via redrive.
drill = {}
long_t = threading.Thread(target=post, args=(prompts[0], 48, drill, "long"))
long_t.start()
for i in range(4):
    post(prompts[1 + i % 4], 4, drill, f"warm{i}")
    if router.counters["quarantines"]:
        break

deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    if router.counters["quarantines"] >= 1:
        break
    time.sleep(0.05)
assert router.counters["quarantines"] >= 1, router.counters
long_t.join(timeout=180)
assert not long_t.is_alive(), "the in-flight long request hung"
assert drill["long"]["status"] == "done", drill["long"]
assert len(drill["long"]["tokens"]) == 48, len(drill["long"]["tokens"])
assert drill["long"].get("redrives", 0) >= 1, drill["long"]

# The quarantined replica must relaunch (fresh quantized weights, clean
# pool) and re-pass the quantized-pinned probe/fingerprint checks.
deadline = time.monotonic() + 15.0
while time.monotonic() < deadline:
    if (all(rep.accepting for rep in router.replicas)
            and router.replicas[0].generation >= 2):
        break
    time.sleep(0.05)
assert router.replicas[0].generation >= 2, router.replicas[0].debug_snapshot()

# Post-recovery replay: the healed fleet must be bit-identical to the
# clean int8-kv reference on every prompt.
replay = {}
threads = [threading.Thread(target=post, args=(p, 8, replay, i))
           for i, p in enumerate(prompts)]
for t in threads: t.start()
for t in threads: t.join(timeout=180)
assert not any(t.is_alive() for t in threads), "a replay request hung"
for i, want in enumerate(reference):
    got = replay[i]
    assert got["status"] == "done", got
    assert got["tokens"] == want, (i, got["tokens"], want)

with urllib.request.urlopen(f"{base}/debug/engine", timeout=30) as r:
    dbg = json.loads(r.read())
integ = dbg["fleet"]["integrity"]
assert integ["enabled"] and integ["quarantines"] >= 1, integ
with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert 'quant_dtype="int8-kv"' in text, text[:400]
assert "pllm_serving_integrity_probes_total" in text, text[:400]
assert "pllm_serving_quarantines_total" in text, text[:400]

gw.stop(); router.stop(); bus.close()
print(f"quantized sentinel smoke ok: quarantines="
      f"{router.counters['quarantines']}, "
      f"redrives={router.counters['redrives']}, "
      f"{len(prompts)} replayed prompts bit-identical")
EOF

# The integrity auditor must accept the quantized drill with --strict:
# the fired corruption attributed to a detector, every divergence
# answered, no unanswered quarantine.
python scripts/obs_report.py --integrity --strict \
    "$OBS_TMP/quant_integrity_events.jsonl" \
    > "$OBS_TMP/quant_integrity_report.out"
grep -q "detected by" "$OBS_TMP/quant_integrity_report.out" || {
    echo "obs_report --integrity missing the detection attribution (quantized)"; exit 1; }

# Cross-host tracing gate: the distributed-tracing wiring over a REAL
# process boundary. Two pre-spawned TCP workers (proto v2: clock samples
# in hello/heartbeat, batched span-export frames) attach behind a traced
# router; replica 0 is partitioned mid-burst so one request is redriven
# across hosts. The router recorder must end up holding ONE merged
# Chrome trace: worker decode spans clock-aligned into the router
# timeline (offset from the min-RTT estimator, error bound recorded on
# every ingested span) and nested under the owning req.attempt span of
# the router's lineage tree; terminal bodies must carry replica +
# redrives next to trace_id; /metrics must stay lint-clean with the
# span/drop counters and clock gauges; and the offline analyzer must
# accept the artifacts with --fleet-trace --strict.
JAX_PLATFORMS=cpu python -m pretraining_llm_tpu.frontend.worker \
    --spec-json "$MH_SPEC" --listen 127.0.0.1:0 --token trace-smoke-token \
    > "$OBS_TMP/tr_worker0.out" 2> "$OBS_TMP/tr_worker0.err" &
TR_W0=$!
JAX_PLATFORMS=cpu python -m pretraining_llm_tpu.frontend.worker \
    --spec-json "$MH_SPEC" --listen 127.0.0.1:0 --token trace-smoke-token \
    > "$OBS_TMP/tr_worker1.out" 2> "$OBS_TMP/tr_worker1.err" &
TR_W1=$!
TR_ADDR0="127.0.0.1:$(mh_port "$OBS_TMP/tr_worker0.out")"
TR_ADDR1="127.0.0.1:$(mh_port "$OBS_TMP/tr_worker1.out")"

JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" TR_ADDR0="$TR_ADDR0" \
    TR_ADDR1="$TR_ADDR1" python - <<'EOF'
import json, os, time, urllib.request
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_http
from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.tracing import Tracer
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

tmp = os.environ["OBS_TMP"]
bus = EventBus(os.path.join(tmp, "fleet_trace_events.jsonl"))
faults = ServingFaultInjector("partition@req2:r0", bus=bus)
registry = MetricsRegistry("pllm_serving_")
# ONE recorder for the whole fleet: the router's own spans and every
# worker's exported spans land in the same buffer, so a single export
# at the end IS the merged cross-host trace.
recorder = SpanRecorder(max_events=50000)
tracer = Tracer(recorder, sample=1.0, seed=17)
spec = {
    "preset": "tiny",
    "init_seed": 0,
    "model_overrides": {"compute_dtype": "float32"},
    "engine": {"max_batch": 2, "n_blocks": 24, "block_size": 8,
               "temperature": 0.0, "steps_per_sched": 4,
               "pipeline_depth": 2},
    "admission": {"max_queue_depth": 8},
}
replicas = []
for i in range(2):
    s = dict(spec)
    s["attach"] = os.environ[f"TR_ADDR{i}"]
    s["token"] = "trace-smoke-token"
    replicas.append(RemoteReplica(i, s, bus=bus, fault_injector=faults,
                                  lease_s=0.8, recorder=recorder))
router = Router(replicas, bus=bus, registry=registry, tracer=tracer,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=60.0).start()
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

load = LoadSpec(n_requests=12, mode="closed", concurrency=4, seed=9,
                vocab_size=replicas[0].engine.cfg.vocab_size,
                max_new_min=6, max_new_max=10, send_traceparent=True)
report = run_http(base, load)

lost = load.n_requests - len(report.outcomes)
assert lost == 0, f"{lost} requests lost"
statuses = {}
for o in report.outcomes:
    statuses[o.status] = statuses.get(o.status, 0) + 1
    assert o.trace_id, f"request {o.index} lost its trace id: {o}"
assert statuses == {"done": 12}, statuses
assert report.summary()["redrives_total"] >= 1, report.summary()
assert replicas[0].fence >= 1, "fence generation never bumped"
assert all(rep._peer_proto >= 2 for rep in replicas), \
    [rep._peer_proto for rep in replicas]

# Terminal bodies carry the lineage summary next to the trace id.
req = urllib.request.Request(
    f"{base}/v1/generate",
    data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4}).encode(),
    headers={"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=120) as r:
    body = json.loads(r.read())
assert body["status"] == "done" and body.get("trace_id"), body
assert "replica" in body and "redrives" in body, body

# Span export piggybacks on stream ends — wait for the survivor's
# batches to settle before snapshotting the merged trace.
deadline = time.monotonic() + 30.0
last = -1.0
while time.monotonic() < deadline:
    cur = replicas[1]._c_spans.value
    if cur > 0 and cur == last:
        break
    last = cur
    time.sleep(0.5)
assert replicas[1]._c_spans.value > 0, "survivor exported no spans"

with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert "pllm_serving_worker_spans_total" in text, text[:400]
assert "pllm_serving_worker_span_drops_total" in text, text[:400]
assert "pllm_serving_clock_offset_seconds" in text, text[:400]
assert "pllm_serving_clock_error_bound_seconds" in text, text[:400]

gw.stop(); router.stop(); bus.close()
recorder.export(os.path.join(tmp, "fleet_trace.json"))

# The merged trace: worker subtrees clock-aligned and nested under the
# router's attempt spans, with at least one redriven lineage tree.
with open(os.path.join(tmp, "fleet_trace.json")) as f:
    events = json.load(f)["traceEvents"]
spans = [e for e in events
         if e.get("ph") == "X" and (e.get("args") or {}).get("trace_id")]
remote = [e for e in spans if e["args"].get("remote")]
assert remote, "no worker spans reached the router recorder"
assert not any(e["args"].get("unaligned") for e in remote), \
    "worker spans ingested without a clock offset estimate"
assert all(e["args"].get("clock_err_s") is not None
           and float(e["args"]["clock_err_s"]) < 0.25 for e in remote), \
    "ingested worker span missing a sane clock error bound"
assert any(e["name"] == "req.window" for e in remote), \
    "no worker decode window in the merged trace"
by_trace = {}
for e in spans:
    by_trace.setdefault(e["args"]["trace_id"], []).append(e)
nested = 0
for tid, grp in by_trace.items():
    attempts = {e["args"].get("span_id") for e in grp
                if e["name"] == "req.attempt" and not e["args"].get("remote")}
    for e in grp:
        if e["args"].get("remote") and e["name"] == "req.request":
            assert e["args"].get("parent_span_id") in attempts, (tid, e)
            nested += 1
assert nested >= 1, "no worker subtree nested under a router attempt"
redriven = [e for e in spans
            if e["name"] == "req.request" and not e["args"].get("remote")
            and int(e["args"].get("redrives") or 0) >= 1]
assert redriven, "no redriven lineage tree in the merged trace"
print(f"cross-host tracing smoke ok: {statuses}, "
      f"{len(remote)} worker spans ({nested} subtrees), "
      f"{len(redriven)} redriven trees, dropped={recorder.dropped}")
EOF

kill "$TR_W0" "$TR_W1" 2>/dev/null || true
wait "$TR_W0" "$TR_W1" 2>/dev/null || true

# The offline analyzer must accept the cross-host artifacts with
# --fleet-trace --strict: every worker span clock-aligned into its
# attempt window, every subtree parented into its lineage tree, and the
# per-request cross-host decomposition summing to e2e.
python scripts/obs_report.py --fleet-trace --strict \
    "$OBS_TMP/fleet_trace_events.jsonl" --trace "$OBS_TMP/fleet_trace.json" \
    > "$OBS_TMP/fleet_trace_report.out"
grep -q "== fleet trace ==" "$OBS_TMP/fleet_trace_report.out" || {
    echo "obs_report --fleet-trace missing the fleet trace section"; exit 1; }
grep -Eq "redriven=[1-9]" "$OBS_TMP/fleet_trace_report.out" || {
    echo "obs_report --fleet-trace saw no redriven lineage tree"; exit 1; }

# Disaggregation gate: a real prefill/decode tier split over TCP. One
# prefill worker + one decode worker (separate processes, roles in the
# spec), hot-prefix traffic through real HTTP: at least one KV page must
# migrate prefill->decode, every request must be served by the decode
# tier with greedy outputs BIT-IDENTICAL to a colocated single engine,
# /metrics must stay lint-clean with the typed migration counters, and
# the offline auditor must join each migration to the prefill it saved.
JAX_PLATFORMS=cpu OBS_TMP="$OBS_TMP" python - <<'EOF'
import dataclasses, json, os, threading, urllib.request
import jax
import numpy as np
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry

tmp = os.environ["OBS_TMP"]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
ekw = {"max_batch": 2, "n_blocks": 24, "block_size": 8,
       "temperature": 0.0, "steps_per_sched": 4, "pipeline_depth": 2,
       "prefix_cache": True, "kv_checksum": True}

# Hot-prefix workload: six requests sharing a 12-token prefix — one
# migration of the shared chain warms the decode tier for the rest.
rng = np.random.default_rng(20)
head = rng.integers(0, cfg.vocab_size, size=12).tolist()
prompts = [head + rng.integers(0, cfg.vocab_size, size=3).tolist()
           for _ in range(6)]
n_new = 8

# Colocated reference: one engine, no fleet, no migration.
eng = ServingEngine(params, cfg, **ekw)
rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
ref = {rids[r]: t for r, t in eng.run().items()}

bus = EventBus(os.path.join(tmp, "disagg_events.jsonl"))
registry = MetricsRegistry("pllm_serving_")
def spec(role):
    return {"preset": "tiny", "init_seed": 0,
            "model_overrides": {"compute_dtype": "float32"},
            "engine": dict(ekw), "admission": {"max_queue_depth": 8},
            "role": role}
replicas = [RemoteReplica(0, spec("prefill"), bus=bus),
            RemoteReplica(1, spec("decode"), bus=bus)]
router = Router(replicas, bus=bus, registry=registry,
                admission=AdmissionController(max_queue_depth=16),
                eject_backoff_s=60.0).start()
assert replicas[0].role == "prefill" and replicas[1].role == "decode"
assert all(rep.kv_capable for rep in replicas)
gw = ServingGateway(router, port=0)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

outs = {}
def post(i, p):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": p, "max_new_tokens": n_new}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=180) as r:
        outs[i] = json.loads(r.read())
threads = [threading.Thread(target=post, args=(i, p))
           for i, p in enumerate(prompts)]
for t in threads: t.start()
for t in threads: t.join(timeout=300)
assert not any(t.is_alive() for t in threads), "a disagg request hung"

for i in range(len(prompts)):
    body = outs[i]
    assert body["status"] == "done", body
    # bit-identity vs colocated: migration must never change a token
    assert body["tokens"] == ref[i], (i, body["tokens"], ref[i])
    # the prefill tier never serves client traffic
    assert body["replica"] == 1, body

assert router.counters["kv_migrations"] >= 1, router.counters
assert router.counters["kv_pages_migrated"] >= 1, router.counters
assert router.counters["kv_migration_rejects"] == 0, router.counters

with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
problems = lint_exposition(text)
assert not problems, problems
assert "pllm_serving_kv_pages_migrated_total" in text, text[:400]
assert "pllm_serving_kv_migrated_bytes_total" in text, text[:400]
assert "pllm_serving_kv_migration_rejects_total" in text, text[:400]

gw.stop(); router.stop(); bus.close()
print(f"disaggregation smoke ok: migrations="
      f"{router.counters['kv_migrations']}, pages="
      f"{router.counters['kv_pages_migrated']}, bit-identical over TCP")
EOF

if pgrep -f "pretraining_llm_tpu.frontend.worker" > /dev/null; then
    echo "orphaned worker processes left after disaggregation gate:"
    pgrep -af "pretraining_llm_tpu.frontend.worker"
    exit 1
fi

# The offline auditor must report the migration section: every
# kv_migrate joined to its request, with the prefill tokens it saved.
python scripts/obs_report.py --fleet --strict \
    "$OBS_TMP/disagg_events.jsonl" > "$OBS_TMP/disagg_report.out"
grep -q "lost=0" "$OBS_TMP/disagg_report.out" || {
    echo "obs_report --fleet (disagg) did not report lost=0"; exit 1; }
grep -q "kv migration" "$OBS_TMP/disagg_report.out" || {
    echo "obs_report --fleet missing the kv migration section"; exit 1; }

# Live SLO gate: boot a 2-replica fleet with the SLO engine attached,
# serve a healthy batch over real HTTP, then poll GET /slo — the snapshot
# must be well-formed (distributions, budgets, fleet health) with ZERO
# alerts on a clean run, and obs_report --live must reconcile the live
# sketch quantiles against the exact offline percentiles computed from
# the same run's event stream.
JAX_PLATFORMS=cpu python - "$OBS_TMP" <<'EOF'
import dataclasses, json, subprocess, sys, threading, urllib.request
import jax
import numpy as np
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.capacity import DecisionLog
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.slo import (
    SLOEngine, default_slo_classes,
)

tmp = sys.argv[1]
cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
events_path = f"{tmp}/slo_events.jsonl"
bus = EventBus(events_path)
# Generous objectives (this is a structural gate, not a perf bet) and a
# window wide enough that nothing rotates out before reconciliation.
slo = SLOEngine(
    classes=default_slo_classes(ttft_s=120.0, e2e_s=600.0),
    bus=bus, decisions=DecisionLog(bus=bus), window_s=600.0,
)

def factory():
    return ServingEngine(
        params, cfg, temperature=0.0, max_batch=2, n_blocks=24,
        block_size=8, steps_per_sched=4, pipeline_depth=2,
    )

replicas = [Replica(i, factory, bus=bus) for i in range(2)]
router = Router(replicas, bus=bus, slo=slo, eject_backoff_s=0.1)
router.start()
gw = ServingGateway(router, port=0, slo=slo)
gw.start()
base = f"http://127.0.0.1:{gw.port}"

rng = np.random.default_rng(0)
lengths = (5, 9, 14, 7, 11, 3, 16, 6) * 3  # 24 requests: >= the 20 the
# reconciliation needs before it checks quantiles instead of skipping
prompts = [
    rng.integers(0, cfg.vocab_size, size=int(n)).tolist() for n in lengths
]
outs = {}

def post(i, p):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": p, "max_new_tokens": 8}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as r:
        outs[i] = json.loads(r.read())

threads = [threading.Thread(target=post, args=(i, p))
           for i, p in enumerate(prompts)]
for t in threads: t.start()
for t in threads: t.join(timeout=600)
assert not any(t.is_alive() for t in threads), "an SLO-gate request hung"
assert all(outs[i]["status"] == "done" for i in range(len(prompts))), outs

with urllib.request.urlopen(base + "/slo", timeout=30) as r:
    snap = json.loads(r.read())
# Well-formed: distributions + budgets + alerts + aggregated fleet health.
assert snap["alerts"]["active"] == [], snap["alerts"]
assert snap["alerts"]["fired_total"] == 0, snap["alerts"]
fleet = snap["latency"]["fleet"]
assert fleet["e2e_s"]["count"] == len(prompts), fleet
assert fleet["ttft_s"]["p99"] > 0
cls = snap["classes"]["interactive"]
assert cls["events"] == len(prompts) and cls["bad"] == 0, cls
fh = snap["fleet_health"]["fleet"]
assert fh["replicas_total"] == 2 and fh["replicas_active"] == 2, fh
assert fh["gauges"]["rows_capacity"] == 4.0, fh["gauges"]

with urllib.request.urlopen(base + "/metricsz", timeout=30) as r:
    mz = json.loads(r.read())
assert "gauges" in mz and "http" in mz, list(mz)

# The analyzer's --live fetch against the SAME gateway + event stream:
# sketch quantiles must land inside the exact offline rank bands.
rc = subprocess.run(
    [sys.executable, "scripts/obs_report.py", "--strict",
     "--live", base, events_path],
).returncode
assert rc == 0, f"obs_report --live --strict failed (rc={rc})"

gw.stop(); router.stop(); bus.close()
print(f"live SLO smoke ok: {len(prompts)} requests, 0 alerts, "
      f"ttft_p99={fleet['ttft_s']['p99']:.3f}s, live reconciled")
EOF

# Ragged-kernel speed push gate: (a) the KV-split / AMLA kernel variants
# must reproduce the gather reference on a small identity grid
# (interpret mode — numerics, not speed), and (b) fused-vs-unfused
# greedy decode must be bit-identical through the serving engine. Fast
# versions of the exhaustive tier-1 grids, run on every smoke.
JAX_PLATFORMS=cpu python - <<'EOF'
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.ops.pallas_ragged import (
    ragged_gather_attention, ragged_paged_attention)

rng = np.random.default_rng(0)
b, t, h, g, d, bs, nb = 2, 4, 4, 2, 32, 8, 16
q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
kp = jnp.asarray(rng.normal(size=(nb, bs, g, d)), jnp.float32)
vp = jnp.asarray(rng.normal(size=(nb, bs, g, d)), jnp.float32)
tbl = jnp.asarray(rng.integers(1, nb, size=(b, 4)), jnp.int32)
seq = jnp.asarray([15, 17], jnp.int32)  # straddle the splits=2 edge (16)
ql = jnp.asarray([1, t], jnp.int32)
ref = ragged_gather_attention(q, kp, vp, tbl, seq, ql)
for kv_splits, amla in [(1, False), (2, False), (2, True), (None, True)]:
    out = ragged_paged_attention(
        q, kp, vp, tbl, seq, ql, kv_splits=kv_splits, amla=amla)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4 if amla else 2e-5,
        err_msg=f"kv_splits={kv_splits} amla={amla}")
print("ragged kernel identity ok: splits x amla match gather")

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
           for n in (5, 9, 14)]
outs = {}
for fused in (True, False):
    eng = ServingEngine(
        params, cfg, temperature=0.0, max_batch=2, n_blocks=24,
        block_size=8, steps_per_sched=3, fused_sampling=fused)
    for p in prompts:
        eng.submit(p, 8)
    outs[fused] = eng.run(pipeline=True)
    host_bytes = eng.stats["logits_bytes_host"]
    assert (host_bytes == 0) == fused, (fused, host_bytes)
assert outs[True] == outs[False], "fused vs unfused greedy drift"
print("decode-fused sampling ok: greedy bit-identical, "
      "0 logits bytes to host when fused")
EOF
