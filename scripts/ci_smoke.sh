#!/usr/bin/env bash
# CPU smoke gate: everything must at least compile, and the resilience +
# checkpoint recovery paths must pass end-to-end (including the slow
# subprocess drills the tier-1 `-m "not slow"` run excludes).
#
# Usage: bash scripts/ci_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q pretraining_llm_tpu scripts

JAX_PLATFORMS=cpu python -m pytest \
    tests/test_resilience.py \
    tests/test_observability.py \
    "tests/test_training.py::test_checkpoint_roundtrip_and_exact_resume" \
    "tests/test_training.py::test_checkpoint_retention" \
    "tests/test_training.py::test_checkpoint_sharded_leaf_reassembly" \
    -q -p no:cacheprovider "$@"

# Observability gate: a tiny synthetic run must emit parseable metrics +
# event streams, and the offline analyzer must accept BOTH with --strict
# (any unparseable line — e.g. a bare NaN token — fails the gate). This is
# what keeps the JSONL schema a checked contract rather than a convention.
OBS_TMP=$(mktemp -d)
trap 'rm -rf "$OBS_TMP"' EXIT
JAX_PLATFORMS=cpu python scripts/train.py --preset tiny --data synthetic \
    --no-resume --steps 8 --obs-dir "$OBS_TMP/obs" \
    --override train.metrics_path="$OBS_TMP/metrics.jsonl" \
    train.checkpoint_dir="$OBS_TMP/ckpt" train.log_interval=2 \
    train.eval_interval=4 train.eval_iters=1 train.checkpoint_interval=4 \
    > "$OBS_TMP/train.out"
test -s "$OBS_TMP/obs/events.jsonl"   # event stream must exist and be non-empty
test -s "$OBS_TMP/obs/spans.trace.json"
python scripts/obs_report.py --strict \
    "$OBS_TMP/metrics.jsonl" "$OBS_TMP/obs/events.jsonl"

# Serving decode gate: 8 requests through the deep-pipelined scheduler
# (depth 2) on a tiny random-init model must finish, emit a token count,
# and report the host-blocked window telemetry — the end-to-end proof
# that dispatch/reap/admission survive outside the pytest fixtures.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax, dataclasses
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
eng = ServingEngine(params, cfg, max_batch=4, n_blocks=32, block_size=8,
                    temperature=0.0, steps_per_sched=4, pipeline_depth=2,
                    admit_batch=2)
rng = np.random.default_rng(0)
rids = [eng.submit(rng.integers(0, cfg.vocab_size, size=5 + i).tolist(), 8)
        for i in range(8)]
out = eng.run(pipeline=True)
assert set(out) == set(rids), (sorted(out), rids)
assert all(len(out[r]) == 8 for r in rids), {r: len(out[r]) for r in rids}
st = eng.stats
assert st["windows_reaped"] == st["windows"] > 0, st
assert st["host_blocked_s"] >= 0.0, st
print(f"serving smoke ok: {st['tokens']} tokens, {st['windows']} windows, "
      f"host_blocked_s={st['host_blocked_s']:.4f}")
EOF

# Gateway gate: the ONLINE path end-to-end over real HTTP. A tiny random-
# init model behind EngineLoop + ServingGateway serves 4 concurrent
# requests — one SSE-streaming, one cancelled mid-generation by dropping
# the connection — all must terminate, and /metrics must report the
# request counters (completed + cancelled) in Prometheus text format.
JAX_PLATFORMS=cpu python - <<'EOF'
import dataclasses, json, socket, threading, urllib.request
import jax
from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

cfg = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
params = transformer.init_params(cfg, jax.random.key(0))
eng = ServingEngine(params, cfg, max_batch=4, n_blocks=32, block_size=8,
                    temperature=0.0, steps_per_sched=2, pipeline_depth=2)
loop = EngineLoop(eng, admission=AdmissionController(max_queue_depth=8))
gw = ServingGateway(loop, port=0)
loop.start(); gw.start()
base = f"http://127.0.0.1:{gw.port}"

def post(payload):
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())

results = {}
def full(name, n):
    results[name] = post({"prompt": [1, 2, 3, int(n)], "max_new_tokens": 8})
def sse(name):
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 8,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    toks, final = [], None
    with urllib.request.urlopen(req, timeout=120) as r:
        for line in r:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            ev = json.loads(line[6:])
            if ev.get("done"): final = ev
            elif "token" in ev: toks.append(ev["token"])
    results[name] = {"tokens": toks, "final": final}
def cancelled(name):
    # Open a streaming request, read one token, drop the socket: the
    # gateway must cancel the request and free its row/pool blocks.
    s = socket.create_connection(("127.0.0.1", gw.port), timeout=120)
    body = json.dumps({"prompt": [9, 9, 9], "max_new_tokens": 48,
                       "stream": True}).encode()
    s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body)
    buf = b""
    while b"data: " not in buf:
        chunk = s.recv(4096)
        assert chunk, buf
        buf += chunk
    s.close()
    results[name] = {"cancel_sent": True}

threads = [threading.Thread(target=full, args=("a", 1)),
           threading.Thread(target=full, args=("b", 2)),
           threading.Thread(target=sse, args=("c",)),
           threading.Thread(target=cancelled, args=("d",))]
for t in threads: t.start()
for t in threads: t.join(timeout=180)
assert not any(t.is_alive() for t in threads), "a gateway request hung"

assert results["a"]["status"] == "done" and results["a"]["n_tokens"] == 8, results["a"]
assert results["b"]["status"] == "done" and results["b"]["n_tokens"] == 8, results["b"]
assert results["c"]["final"]["status"] == "done", results["c"]
assert len(results["c"]["tokens"]) == 8, results["c"]

# The dropped connection must surface as a cancellation (or a completed
# request if the drop raced the final token) — and every row/block must
# be back: allocator idle == n_blocks - 1 (block 0 reserved).
import time
for _ in range(200):
    m = loop.metrics()
    if m["active_requests"] == 0 and eng.alloc.available == 32 - 1:
        break
    time.sleep(0.05)
assert eng.alloc.available == 32 - 1, eng.alloc.available
assert m["completed"] + m["cancelled"] == 4, m

with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
    assert json.loads(r.read())["status"] == "ok"
with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
    text = r.read().decode()
assert "pllm_serving_completed" in text, text[:400]
assert "pllm_serving_submitted" in text, text[:400]
assert "pllm_serving_http_requests_total" in text, text[:400]

gw.stop(); loop.stop()
print(f"gateway smoke ok: {m}")
EOF
