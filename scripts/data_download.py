#!/usr/bin/env python
"""Download a pretraining dataset into the HF cache.

Mirror of `/root/reference/scripts/data_download.py:7-23` (openwebtext by
default, prints a sample), with a clear failure mode in air-gapped
environments instead of a deep urllib traceback.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def download_dataset(name: str = "openwebtext") -> None:
    try:
        from datasets import load_dataset

        ds = load_dataset(name, split="train", trust_remote_code=True)
    except Exception as e:
        raise SystemExit(
            f"could not download {name!r} ({type(e).__name__}: {e}). Offline? "
            "Use `scripts/data_preprocess.py --input <files>` on a local corpus instead."
        )
    print(f"{name}: {len(ds)} documents cached")
    print("sample:", ds[0]["text"][:200].replace("\n", " "))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dataset", default="openwebtext")
    args = parser.parse_args()
    download_dataset(args.dataset)


if __name__ == "__main__":
    main()
