#!/usr/bin/env python
"""Tokenize a corpus into train/val uint16 memmaps.

Mirror of `/root/reference/scripts/data_preprocess.py` (HF dataset -> tiktoken
-> uint16 .bin), extended to local files and in-repo tokenizers so it runs
offline.

Examples:
  python scripts/data_preprocess.py --input my_corpus.txt --out_dir data --tokenizer byte
  python scripts/data_preprocess.py --dataset openwebtext --out_dir data --tokenizer gpt2
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.data.preprocess import preprocess


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", nargs="*", default=None, help=".txt or .jsonl files")
    parser.add_argument("--dataset", default=None, help="HF dataset name (needs cache/network)")
    parser.add_argument("--out_dir", default="data")
    parser.add_argument("--tokenizer", default="gpt2", help="gpt2 | byte | path/to/bpe.json")
    parser.add_argument("--val_fraction", type=float, default=0.0005)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--num_proc", type=int, default=None)
    parser.add_argument("--max_docs", type=int, default=None)
    args = parser.parse_args()

    preprocess(
        input_files=args.input,
        dataset_name=args.dataset,
        out_dir=args.out_dir,
        tokenizer_name=args.tokenizer,
        val_fraction=args.val_fraction,
        seed=args.seed,
        num_proc=args.num_proc,
        max_docs=args.max_docs,
    )


if __name__ == "__main__":
    main()
