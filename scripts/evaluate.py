#!/usr/bin/env python
"""Evaluate a checkpoint on a validation set: loss / perplexity / bits-per-token.

Standalone counterpart of the trainer's periodic eval (the reference has no
eval entry point at all — its eval lives inline in the training loop,
scripts/train_transformer.py:51-62). Deterministic: the same seeded batches
every run, so numbers are comparable across checkpoints.

Usage:
  python scripts/evaluate.py --model_path checkpoints --data data/val.bin
  python scripts/evaluate.py --model_path checkpoints/step-4000 \
      --data data/val.bin --iters 100 --batch 16
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model_path", required=True, help="checkpoint dir (or step-N dir)")
    ap.add_argument("--data", required=True, help="uint16 token .bin")
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--batch", type=int, default=0, help="0 = checkpoint's train batch")
    ap.add_argument("--ema", action="store_true",
                    help="evaluate the EMA shadow params (train.ema_decay runs)")
    ap.add_argument(
        "--seed", type=int, default=-1,
        help="-1 = the trainer's own eval seed (data.sample_seed + 104729), "
        "so the number matches the training log's val_loss exactly",
    )
    args = ap.parse_args()

    import jax.numpy as jnp

    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.generation.generate import load_model_for_inference
    from pretraining_llm_tpu.training import train_step as ts

    params, cfg = load_model_for_inference(args.model_path, use_ema=args.ema)
    batch = args.batch or cfg.train.batch_size
    seed = args.seed if args.seed >= 0 else cfg.data.sample_seed + 104729
    it = loader.get_batch_iterator(args.data, batch, cfg.model.context_length, seed=seed)
    # Same single-dispatch scan the trainer's periodic eval uses — one device
    # round trip for all iters, not one per batch.
    eval_loop = ts.build_eval_loop(cfg, mesh=None)
    xs, ys = zip(*(next(it) for _ in range(args.iters)))
    loss = float(
        eval_loop({"params": params}, (jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ys))))
    )
    n = args.iters
    print(
        json.dumps(
            {
                "val_loss": round(loss, 6),
                # inf past the float64 exp bound — never a silently-clamped
                # finite value (same convention as the trainer's metrics).
                "val_ppl": round(math.exp(loss), 3) if loss < 700 else float("inf"),
                "val_bits_per_token": round(loss / math.log(2), 4),
                "iters": n,
                "batch": batch,
                "context_length": cfg.model.context_length,
                "tokens_evaluated": n * batch * cfg.model.context_length,
                "checkpoint": os.path.abspath(args.model_path),
            }
        )
    )


if __name__ == "__main__":
    main()
