#!/usr/bin/env python
"""Export a framework checkpoint as a Hugging Face GPT-2 model directory.

Inverse of import_hf_checkpoint.py: a model trained here (GPT-2 shape —
learned positions, LayerNorm, gelu, fused qkv with bias, output projection,
tied head) becomes a `GPT2LMHeadModel.from_pretrained`-loadable directory,
so the wider HF ecosystem (generation pipelines, evaluation harnesses,
quantizers) can consume checkpoints trained on TPU with this framework.

Usage:
  python scripts/export_hf_checkpoint.py checkpoints --out_dir hf_model
  # then anywhere:  GPT2LMHeadModel.from_pretrained("hf_model")
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()


def export_params_to_hf(params, cfg):
    """(framework params, ModelConfig) -> HF GPT2LMHeadModel (torch, CPU)."""
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    required = {
        "pos_embed": cfg.pos_embed == "learned",
        "norm": cfg.norm == "layernorm",
        "activation": cfg.activation in ("gelu",),
        "use_output_proj": cfg.use_output_proj,
        "tie_embeddings": cfg.tie_embeddings,
        "qkv_bias": cfg.qkv_bias,
        "mlp_bias": cfg.mlp_bias,
        "mha (no GQA)": cfg.kv_heads == cfg.n_heads,
        "no MoE": cfg.n_experts == 0,
        # HF GPT-2 runs FULL causal attention: a windowed or doc-masked
        # model would load cleanly but compute different outputs.
        "no sliding_window": cfg.sliding_window == 0,
        "no doc_mask": cfg.doc_mask_token < 0,
    }
    bad = [k for k, ok in required.items() if not ok]
    if bad:
        raise ValueError(
            f"model is not the GPT-2 architecture HF expects; failing "
            f"properties: {bad}"
        )

    d, h, dh, nl = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.n_layers
    hf_cfg = GPT2Config(
        vocab_size=cfg.vocab_size,
        n_positions=cfg.context_length,
        n_embd=d,
        n_layer=nl,
        n_head=h,
        n_inner=int(cfg.mlp_ratio * d),
        activation_function="gelu_new",
        layer_norm_epsilon=cfg.norm_eps,
        # No dropout: this framework trains without it (SURVEY §2.5), and
        # an exported model should evaluate identically by default.
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    model = GPT2LMHeadModel(hf_cfg)

    def t(a) -> "torch.Tensor":
        return torch.from_numpy(np.asarray(a, np.float32))

    blocks = params["blocks"]
    sd = {
        "transformer.wte.weight": t(params["tok_embed"]["embedding"]),
        "transformer.wpe.weight": t(params["pos_embed"]["embedding"]),
        "transformer.ln_f.weight": t(params["final_norm"]["scale"]),
        "transformer.ln_f.bias": t(params["final_norm"]["bias"]),
        "lm_head.weight": t(params["tok_embed"]["embedding"]),  # tied
    }
    for i in range(nl):
        pre = f"transformer.h.{i}."
        sd[pre + "ln_1.weight"] = t(blocks["ln1"]["scale"][i])
        sd[pre + "ln_1.bias"] = t(blocks["ln1"]["bias"][i])
        sd[pre + "attn.c_attn.weight"] = t(
            np.asarray(blocks["attn"]["wqkv"][i]).reshape(d, 3 * h * dh)
        )
        sd[pre + "attn.c_attn.bias"] = t(
            np.asarray(blocks["attn"]["bqkv"][i]).reshape(3 * h * dh)
        )
        sd[pre + "attn.c_proj.weight"] = t(
            np.asarray(blocks["attn"]["wo"][i]).reshape(h * dh, d)
        )
        sd[pre + "attn.c_proj.bias"] = t(blocks["attn"]["bo"][i])
        sd[pre + "ln_2.weight"] = t(blocks["ln2"]["scale"][i])
        sd[pre + "ln_2.bias"] = t(blocks["ln2"]["bias"][i])
        sd[pre + "mlp.c_fc.weight"] = t(blocks["mlp"]["w1"][i])
        sd[pre + "mlp.c_fc.bias"] = t(blocks["mlp"]["b1"][i])
        sd[pre + "mlp.c_proj.weight"] = t(blocks["mlp"]["w2"][i])
        sd[pre + "mlp.c_proj.bias"] = t(blocks["mlp"]["b2"][i])

    missing, unexpected = model.load_state_dict(sd, strict=False)
    # The causal-mask buffers (h.*.attn.bias) are allowed to be missing —
    # they are constants the model rebuilds; anything else missing means a
    # mapping bug and must fail loudly.
    real_missing = [k for k in missing if not k.endswith(_MASK_SUFFIXES)]
    if real_missing or unexpected:
        raise ValueError(
            f"state_dict mismatch: missing={real_missing[:5]} "
            f"unexpected={list(unexpected)[:5]}"
        )
    return model


_MASK_SUFFIXES = (".attn.bias", ".attn.masked_bias")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("checkpoint", help="framework checkpoint directory")
    ap.add_argument("--out_dir", required=True)
    ap.add_argument("--ema", action="store_true",
                    help="export the EMA shadow params instead of the raw params")
    args = ap.parse_args()

    from pretraining_llm_tpu.generation.generate import load_model_for_inference

    params, cfg = load_model_for_inference(args.checkpoint, use_ema=args.ema)
    model = export_params_to_hf(params, cfg.model)
    model.save_pretrained(args.out_dir)
    n = sum(p.numel() for p in model.parameters())
    print(f"exported {n/1e6:.1f}M params -> {args.out_dir} "
          f"(GPT2LMHeadModel.from_pretrained-loadable)")


if __name__ == "__main__":
    main()
