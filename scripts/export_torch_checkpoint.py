#!/usr/bin/env python
"""Export a framework checkpoint to the reference's PyTorch .pt layout.

The inverse of scripts/import_torch_checkpoint.py: takes a checkpoint of a
reference-shaped model (use_output_proj=False, untied biased lm_head, ReLU,
learned positions — e.g. the `reference-3b` preset or an imported
checkpoint) and writes `torch.save({'model_state_dict': ...})` with the
reference's module names (per-head K/Q/V Linears split back out of the fused
wqkv), so the weights load into the reference codebase —
`generate_text.py:21,31` there — or any torch tooling.

Usage:
  python scripts/export_torch_checkpoint.py <ckpt_dir_or_step_dir> --out ref.pt
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Dict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()


def export_params(cfg, params) -> Dict[str, np.ndarray]:
    """(ModelConfig, params pytree) -> reference-named state dict (numpy)."""
    if cfg.use_output_proj or cfg.tie_embeddings or not cfg.lm_head_bias:
        raise ValueError(
            "only reference-shaped models export (use_output_proj=False, "
            "untied embeddings, biased lm_head) — e.g. the reference-3b "
            f"preset or an imported checkpoint; got use_output_proj="
            f"{cfg.use_output_proj} tie_embeddings={cfg.tie_embeddings} "
            f"lm_head_bias={cfg.lm_head_bias}"
        )
    if cfg.activation != "relu" or cfg.pos_embed != "learned" or cfg.norm != "layernorm":
        raise ValueError(
            "reference-shaped layout is ReLU/learned-positions/LayerNorm; got "
            f"{cfg.activation}/{cfg.pos_embed}/{cfg.norm}"
        )
    if cfg.qkv_bias or not cfg.mlp_bias or cfg.kv_heads != cfg.n_heads or cfg.n_experts:
        raise ValueError(
            "reference-shaped attention/MLP is biasless fused-MHA QKV with "
            "biased dense MLP (no GQA, no MoE); got qkv_bias="
            f"{cfg.qkv_bias} mlp_bias={cfg.mlp_bias} kv_heads={cfg.kv_heads} "
            f"n_experts={cfg.n_experts}"
        )
    p = {k: np.asarray(v, np.float32) for k, v in _flatten(params).items()}
    unused = set(p)

    def take(key: str) -> np.ndarray:
        unused.discard(key)
        return p[key]

    sd: Dict[str, np.ndarray] = {
        "token_embed.weight": take("tok_embed.embedding"),
        "position_embed.weight": take("pos_embed.embedding"),
        "layer_norm.weight": take("final_norm.scale"),
        "layer_norm.bias": take("final_norm.bias"),
        "lm_head.weight": take("lm_head.kernel").T,
        "lm_head.bias": take("lm_head.bias"),
    }
    wqkv = take("blocks.attn.wqkv")  # (L, D, 3, H, Dh)
    ln1_s, ln1_b = take("blocks.ln1.scale"), take("blocks.ln1.bias")
    ln2_s, ln2_b = take("blocks.ln2.scale"), take("blocks.ln2.bias")
    w1, b1 = take("blocks.mlp.w1"), take("blocks.mlp.b1")
    w2, b2 = take("blocks.mlp.w2"), take("blocks.mlp.b2")
    t = cfg.context_length
    for i in range(cfg.n_layers):
        sd[f"attn_blocks.{i}.ln1.weight"] = ln1_s[i]
        sd[f"attn_blocks.{i}.ln1.bias"] = ln1_b[i]
        for h in range(cfg.n_heads):
            for c, name in enumerate(("query", "key", "value")):
                sd[f"attn_blocks.{i}.attn.heads.{h}.{name}.weight"] = (
                    wqkv[i, :, c, h, :].T
                )
            # Registered buffers the reference's strict load_state_dict
            # expects (its per-head causal masks, B10).
            sd[f"attn_blocks.{i}.attn.heads.{h}.tril"] = np.tril(
                np.ones((t, t), np.float32)
            )
        sd[f"attn_blocks.{i}.ln2.weight"] = ln2_s[i]
        sd[f"attn_blocks.{i}.ln2.bias"] = ln2_b[i]
        sd[f"attn_blocks.{i}.mlp.hidden.weight"] = w1[i].T
        sd[f"attn_blocks.{i}.mlp.hidden.bias"] = b1[i]
        sd[f"attn_blocks.{i}.mlp.proj.weight"] = w2[i].T
        sd[f"attn_blocks.{i}.mlp.proj.bias"] = b2[i]
    sd["pos_idxs"] = np.arange(t, dtype=np.int64)
    if unused:
        raise ValueError(
            "checkpoint has weights the reference layout cannot hold "
            f"(would be silently dropped): {sorted(unused)[:8]}"
        )
    return sd


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("checkpoint", help="framework checkpoint dir (or step-N dir)")
    ap.add_argument("--out", required=True, help="output .pt path")
    ap.add_argument("--ema", action="store_true",
                    help="export the EMA shadow params instead of the raw params")
    args = ap.parse_args()

    import torch

    from pretraining_llm_tpu.generation.generate import load_model_for_inference

    params, cfg = load_model_for_inference(args.checkpoint, use_ema=args.ema)
    sd = export_params(cfg.model, params)
    torch.save(
        {
            "model_state_dict": {
                # np.array(..) copies: some leaves view read-only mmap pages,
                # which torch.from_numpy refuses to wrap quietly.
                k: torch.from_numpy(np.array(v, copy=True)) for k, v in sd.items()
            }
        },
        args.out,
    )
    n = sum(v.size for v in sd.values())
    print(f"exported {n/1e6:.1f}M params -> {args.out} ({len(sd)} tensors)")


if __name__ == "__main__":
    main()
