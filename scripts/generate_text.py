#!/usr/bin/env python
"""Sampling CLI — mirror of the reference's `scripts/generate_text.py`
interface (`--model_path --input_text --max_new_tokens`,
/root/reference/scripts/generate_text.py:49-58), extended with sampling knobs.

Example:
  python scripts/generate_text.py --model_path checkpoints \
      --input_text "Once upon a time" --max_new_tokens 100 --temperature 0.8 --top_k 50
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()

from pretraining_llm_tpu.generation.generate import generate_text


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model_path", required=True, help="checkpoint dir (or a step-N dir)")
    group = parser.add_mutually_exclusive_group(required=True)
    group.add_argument("--input_text")
    group.add_argument(
        "--input_file",
        help="file with one prompt per line: the whole batch decodes in ONE "
        "compiled ragged program (different prompt lengths supported)",
    )
    parser.add_argument("--max_new_tokens", type=int, default=100)
    parser.add_argument("--temperature", type=float, default=1.0, help="0 = greedy")
    parser.add_argument("--top_k", type=int, default=None)
    parser.add_argument("--top_p", type=float, default=None)
    parser.add_argument("--min_p", type=float, default=None,
                        help="keep tokens with prob >= min_p * max prob")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--tokenizer", default=None,
        help="override the tokenizer name stored in the checkpoint config",
    )
    parser.add_argument(
        "--stop_token", type=int, default=None,
        help="token id that ends a row's generation (output truncates there)",
    )
    parser.add_argument(
        "--draft_model_path", default=None,
        help="a smaller checkpoint sharing the vocab: enables speculative "
        "decoding (draft proposes --spec_k tokens/round, target verifies "
        "in one forward; greedy output equals target-only decoding)",
    )
    parser.add_argument("--spec_k", type=int, default=4,
                        help="speculative proposals per round")
    parser.add_argument("--ema", action="store_true",
                        help="decode from the EMA shadow params")
    args = parser.parse_args()

    if args.draft_model_path:
        from pretraining_llm_tpu.generation.generate import (
            generate_text_speculative,
        )

        if args.input_file:
            parser.error("--draft_model_path is the batch-1 latency path; "
                         "use --input_text")
        if args.stop_token is not None or args.top_k or args.top_p or args.ema:
            parser.error("--draft_model_path supports --temperature only "
                         "(no stop_token/top_k/top_p/ema yet)")
        print(generate_text_speculative(
            args.model_path, args.draft_model_path, args.input_text,
            args.max_new_tokens, k=args.spec_k,
            temperature=args.temperature, seed=args.seed,
            tokenizer=args.tokenizer,
        ))
        return

    if args.input_file:
        from pretraining_llm_tpu.generation.generate import generate_text_batch

        with open(args.input_file) as f:
            prompts = [line.rstrip("\r\n") for line in f if line.strip()]
        outs = generate_text_batch(
            args.model_path,
            prompts,
            args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            min_p=args.min_p,
            seed=args.seed,
            tokenizer=args.tokenizer,
            stop_token=args.stop_token,
            ema=args.ema,
        )
        for text in outs:
            print(text)
            print("---")
        return

    text = generate_text(
        args.model_path,
        args.input_text,
        args.max_new_tokens,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        min_p=args.min_p,
        seed=args.seed,
        tokenizer=args.tokenizer,
        stop_token=args.stop_token,
        ema=args.ema,
    )
    print(text)


if __name__ == "__main__":
    main()
