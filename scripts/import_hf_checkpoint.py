#!/usr/bin/env python
"""Import a Hugging Face GPT-2 checkpoint into this framework.

Beyond the reference's own checkpoint schema (import_torch_checkpoint.py):
users migrating from the HF ecosystem bring `GPT2LMHeadModel` weights
(config.json + model weights in a local directory). This tool maps them onto
this framework's stacked functional pytree and writes a framework checkpoint
directory that `scripts/generate_text.py --model_path <out_dir>`,
`scripts/evaluate.py`, and `scripts/train.py` (resume/fine-tune) load
directly.

Architecture facts relied on (and asserted): GPT-2 is pre-LN with learned
absolute positions, fused Conv1D qkv (weights stored (in, out) — exactly
this framework's orientation, no transposes), gelu_new activation (== this
framework's tanh-approximate "gelu"), LayerNorm eps 1e-5, tied lm_head with
no bias.

Mapping (HF state_dict key -> params leaf):
  transformer.wte.weight (V, D)          -> tok_embed.embedding (tied head)
  transformer.wpe.weight (T, D)          -> pos_embed.embedding
  transformer.h.{i}.ln_1.{weight,bias}   -> blocks.ln1.{scale,bias}[i]
  transformer.h.{i}.attn.c_attn.weight (D, 3D) -> blocks.attn.wqkv[i]
                                            reshaped (D, 3, H, Dh)
  transformer.h.{i}.attn.c_attn.bias (3D,)     -> blocks.attn.bqkv[i] (3, H, Dh)
  transformer.h.{i}.attn.c_proj.weight (D, D)  -> blocks.attn.wo[i] (H, Dh, D)
  transformer.h.{i}.attn.c_proj.bias (D,)      -> blocks.attn.bo[i]
  transformer.h.{i}.mlp.c_fc.{weight,bias}     -> blocks.mlp.{w1,b1}[i]
  transformer.h.{i}.mlp.c_proj.{weight,bias}   -> blocks.mlp.{w2,b2}[i]
  transformer.ln_f.{weight,bias}         -> final_norm.{scale,bias}
  lm_head.weight                         -> dropped (tied to wte)
  *.attn.bias / *.attn.masked_bias       -> dropped (causal-mask buffers; this
                                            framework masks by index arithmetic)

Usage:
  python scripts/import_hf_checkpoint.py /path/to/hf_gpt2_dir --out_dir imported
  python scripts/generate_text.py --model_path imported --input_text "..."
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import re
import sys
from typing import Dict, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()

_DROP_SUFFIXES = (".attn.bias", ".attn.masked_bias")


def check_hf_config(hf_cfg) -> float:
    """Reject GPT-2-family configs whose NUMERICS deviate from what the
    mapped weights will run under here (state-dict shapes alone cannot
    catch these). Returns the layer-norm epsilon to carry over."""
    problems = []
    if getattr(hf_cfg, "activation_function", "gelu_new") != "gelu_new":
        problems.append(
            f"activation_function={hf_cfg.activation_function!r} (only "
            "gelu_new == this framework's tanh-approx gelu is supported)"
        )
    if getattr(hf_cfg, "scale_attn_by_inverse_layer_idx", False):
        problems.append("scale_attn_by_inverse_layer_idx=True")
    if getattr(hf_cfg, "reorder_and_upcast_attn", False):
        problems.append("reorder_and_upcast_attn=True")
    if problems:
        raise ValueError(
            "HF config numerics differ from this framework's forward; a "
            f"silent import would corrupt outputs: {problems}"
        )
    return float(getattr(hf_cfg, "layer_norm_epsilon", 1e-5))


def import_hf_model(model):
    """(GPT2LMHeadModel) -> (ModelConfig, params), config-validated."""
    norm_eps = check_hf_config(model.config)
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    return import_hf_state_dict(sd, int(model.config.n_head), norm_eps=norm_eps)


def import_hf_state_dict(sd: Dict[str, np.ndarray], n_heads: int,
                         norm_eps: float = 1e-5):
    """(HF GPT2LMHeadModel state_dict as numpy, n_head) -> (ModelConfig, params).

    Every key must be consumed — leftovers mean the checkpoint is not the
    GPT-2 architecture this importer maps, and silently dropping trained
    weights would corrupt the import.
    """
    from pretraining_llm_tpu.config import ModelConfig

    sd = {
        k[len("transformer."):] if k.startswith("transformer.") else k: v
        for k, v in sd.items()
        if not k.endswith(_DROP_SUFFIXES)
    }
    # lm_head.weight is tied storage of wte — assert, then drop.
    if "lm_head.weight" in sd:
        if not np.array_equal(sd["lm_head.weight"], sd["wte.weight"]):
            raise ValueError(
                "lm_head.weight is not tied to wte.weight; untied GPT-2 "
                "variants are not supported by this importer"
            )
        del sd["lm_head.weight"]
    sd = {k: np.asarray(v, np.float32) for k, v in sd.items()}
    unused = set(sd)

    def take(key: str) -> np.ndarray:
        unused.discard(key)
        return sd[key]

    vocab_size, d_model = take("wte.weight").shape
    context_length = take("wpe.weight").shape[0]
    n_layers = 1 + max(
        int(m.group(1)) for k in sd if (m := re.match(r"h\.(\d+)\.", k))
    )
    if d_model % n_heads:
        raise ValueError(f"n_heads={n_heads} does not divide d_model={d_model}")
    dh = d_model // n_heads
    d_ff = sd["h.0.mlp.c_fc.weight"].shape[1]

    cfg = ModelConfig(
        vocab_size=vocab_size,
        context_length=context_length,
        d_model=d_model,
        n_heads=n_heads,
        n_layers=n_layers,
        # +0.5 so int(mlp_ratio * d_model) reconstructs d_ff EXACTLY —
        # the bare ratio truncates one low for some integer pairs
        # (e.g. int((220/49)*49) == 219).
        mlp_ratio=(d_ff + 0.5) / d_model,
        activation="gelu",  # == HF gelu_new (tanh approximation)
        norm="layernorm",
        pos_embed="learned",
        use_output_proj=True,
        tie_embeddings=True,
        lm_head_bias=False,
        qkv_bias=True,
        mlp_bias=True,
        norm_eps=norm_eps,
    )
    assert cfg.d_ff == d_ff, (cfg.d_ff, d_ff)

    def stack(fmt: str, transform=lambda a: a):
        return np.stack(
            [transform(take(fmt.format(i=i))) for i in range(n_layers)]
        )

    params = {
        "tok_embed": {"embedding": sd["wte.weight"]},
        "pos_embed": {"embedding": sd["wpe.weight"]},
        "blocks": {
            "ln1": {
                "scale": stack("h.{i}.ln_1.weight"),
                "bias": stack("h.{i}.ln_1.bias"),
            },
            "attn": {
                # Conv1D stores (in, out): (D, 3D) -> (D, 3, H, Dh) directly.
                "wqkv": stack(
                    "h.{i}.attn.c_attn.weight",
                    lambda a: a.reshape(d_model, 3, n_heads, dh),
                ),
                "bqkv": stack(
                    "h.{i}.attn.c_attn.bias",
                    lambda a: a.reshape(3, n_heads, dh),
                ),
                "wo": stack(
                    "h.{i}.attn.c_proj.weight",
                    lambda a: a.reshape(n_heads, dh, d_model),
                ),
                "bo": stack("h.{i}.attn.c_proj.bias"),
            },
            "ln2": {
                "scale": stack("h.{i}.ln_2.weight"),
                "bias": stack("h.{i}.ln_2.bias"),
            },
            "mlp": {
                "w1": stack("h.{i}.mlp.c_fc.weight"),
                "b1": stack("h.{i}.mlp.c_fc.bias"),
                "w2": stack("h.{i}.mlp.c_proj.weight"),
                "b2": stack("h.{i}.mlp.c_proj.bias"),
            },
        },
        "final_norm": {
            "scale": take("ln_f.weight"),
            "bias": take("ln_f.bias"),
        },
    }
    if unused:
        raise ValueError(
            "checkpoint has weights this importer does not map (not the "
            f"GPT-2 architecture): {sorted(unused)[:8]}"
        )
    return cfg, params


def load_hf_model_dir(path: str):
    """(ModelConfig, params) from a local HF GPT-2 directory."""
    from transformers import GPT2LMHeadModel

    return import_hf_model(
        GPT2LMHeadModel.from_pretrained(path, local_files_only=True)
    )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("hf_path", help="local HF GPT-2 model directory")
    ap.add_argument("--out_dir", required=True)
    args = ap.parse_args()

    cfg, params = load_hf_model_dir(args.hf_path)

    import jax

    from pretraining_llm_tpu.config import Config, DataConfig
    from pretraining_llm_tpu.training import checkpoint as ckpt

    full_cfg = Config(
        model=cfg,
        data=DataConfig(tokenizer_name="gpt2"),
        name="imported-hf-gpt2",
    )
    params = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
    path = ckpt.save_checkpoint(
        args.out_dir, 0, {"params": params},
        extra={"step": 0, "config": dataclasses.asdict(full_cfg),
               "preset": full_cfg.name, "source": os.path.abspath(args.hf_path)},
    )
    n = sum(int(np.prod(np.shape(l))) for l in jax.tree.leaves(params))
    print(f"imported {n/1e6:.1f}M params ({cfg.n_layers}L d{cfg.d_model} "
          f"h{cfg.n_heads} ctx{cfg.context_length} V{cfg.vocab_size}) -> {path}")


if __name__ == "__main__":
    main()
