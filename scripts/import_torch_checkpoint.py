#!/usr/bin/env python
"""Import a reference (PyTorch) checkpoint into this framework.

A user switching from the reference brings checkpoints shaped like
`torch.save({'model_state_dict': ..., 'optimizer_state_dict': ...})`
(reference: scripts/train_transformer.py:104-109) for its exact architecture
(SURVEY §2.5: per-head biasless K/Q/V Linears, no attention output
projection, ReLU MLP with biases, learned positions, untied biased lm_head).
This tool maps those weights onto this framework's stacked functional pytree
(fused wqkv, scanned blocks) under the matching `reference_parity`-style
ModelConfig, and writes a framework checkpoint directory that
`scripts/generate_text.py --model_path <out_dir>` and `scripts/train.py`
(resume) load directly.

Mapping (reference state_dict key -> params leaf):
  token_embed.weight    (V, D)  -> tok_embed.embedding
  position_embed.weight (T, D)  -> pos_embed.embedding
  attn_blocks.{i}.ln1.{weight,bias}            -> blocks.ln1.{scale,bias}[i]
  attn_blocks.{i}.attn.heads.{h}.{query,key,value}.weight (dh, D)
        -> blocks.attn.wqkv[i, :, {0,1,2}, h, :] (transposed to (D, dh))
  attn_blocks.{i}.ln2.{weight,bias}            -> blocks.ln2.{scale,bias}[i]
  attn_blocks.{i}.mlp.hidden.{weight,bias}     -> blocks.mlp.{w1,b1}[i] (w T)
  attn_blocks.{i}.mlp.proj.{weight,bias}       -> blocks.mlp.{w2,b2}[i] (w T)
  layer_norm.{weight,bias}                     -> final_norm.{scale,bias}
  lm_head.{weight,bias}        (V, D) / (V,)   -> lm_head.{kernel (D,V), bias}
  *.tril / pos_idxs buffers                    -> dropped (mask buffers, B10)

Usage:
  python scripts/import_torch_checkpoint.py ckpt.pt --out_dir imported_ckpt
  python scripts/generate_text.py --model_path imported_ckpt --input_text "..."
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import re
import sys
from typing import Any, Dict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()


def _strip_prefixes(sd: Dict[str, Any]) -> Dict[str, Any]:
    """Drop DDP ('module.') and torch.compile ('_orig_mod.') wrappers, in
    any nesting order (compile-of-DDP gives '_orig_mod.module.*')."""
    out = {}
    for k, v in sd.items():
        changed = True
        while changed:
            changed = False
            for pre in ("module.", "_orig_mod."):
                if k.startswith(pre):
                    k = k[len(pre):]
                    changed = True
        out[k] = v
    return out


def import_state_dict(sd: Dict[str, np.ndarray]):
    """(reference state_dict of numpy arrays) -> (ModelConfig, params).

    Every key must be consumed — leftover keys mean the checkpoint's
    architecture deviates from the reference spec and a silent import would
    drop trained weights; that is an error, not a warning.
    """
    from pretraining_llm_tpu.config import ModelConfig

    sd = {k: np.asarray(v, np.float32) for k, v in sd.items()}
    unused = set(sd)

    def take(key: str) -> np.ndarray:
        unused.discard(key)
        return sd[key]

    vocab_size, d_model = take("token_embed.weight").shape
    context_length = take("position_embed.weight").shape[0]
    n_layers = 1 + max(
        int(m.group(1))
        for k in sd
        if (m := re.match(r"attn_blocks\.(\d+)\.", k))
    )
    n_heads = 1 + max(
        int(m.group(1))
        for k in sd
        if (m := re.match(r"attn_blocks\.0\.attn\.heads\.(\d+)\.", k))
    )
    dh = sd["attn_blocks.0.attn.heads.0.key.weight"].shape[0]
    d_ff = sd["attn_blocks.0.mlp.hidden.weight"].shape[0]
    cfg = ModelConfig(
        vocab_size=vocab_size,
        context_length=context_length,
        d_model=d_model,
        n_heads=n_heads,
        d_head=dh,
        n_layers=n_layers,
        mlp_ratio=d_ff / d_model,
        activation="relu",
        norm="layernorm",
        pos_embed="learned",
        use_output_proj=False,
        tie_embeddings=False,
        lm_head_bias=True,
        qkv_bias=False,
        mlp_bias=True,
    )

    def stack(fmt: str, transform=lambda a: a):
        return np.stack([transform(take(fmt.format(i=i))) for i in range(n_layers)])

    # Fused QKV: slot order (q, k, v) matches _attention_block's unpacking.
    wqkv = np.zeros((n_layers, d_model, 3, n_heads, dh), np.float32)
    for i in range(n_layers):
        for h in range(n_heads):
            for c, name in enumerate(("query", "key", "value")):
                w = take(f"attn_blocks.{i}.attn.heads.{h}.{name}.weight")  # (dh, D)
                wqkv[i, :, c, h, :] = w.T

    params = {
        "tok_embed": {"embedding": sd["token_embed.weight"]},
        "pos_embed": {"embedding": sd["position_embed.weight"]},
        "blocks": {
            "ln1": {
                "scale": stack("attn_blocks.{i}.ln1.weight"),
                "bias": stack("attn_blocks.{i}.ln1.bias"),
            },
            "attn": {"wqkv": wqkv},
            "ln2": {
                "scale": stack("attn_blocks.{i}.ln2.weight"),
                "bias": stack("attn_blocks.{i}.ln2.bias"),
            },
            "mlp": {
                "w1": stack("attn_blocks.{i}.mlp.hidden.weight", lambda a: a.T),
                "b1": stack("attn_blocks.{i}.mlp.hidden.bias"),
                "w2": stack("attn_blocks.{i}.mlp.proj.weight", lambda a: a.T),
                "b2": stack("attn_blocks.{i}.mlp.proj.bias"),
            },
        },
        "final_norm": {
            "scale": take("layer_norm.weight"),
            "bias": take("layer_norm.bias"),
        },
        "lm_head": {
            "kernel": take("lm_head.weight").T,
            "bias": take("lm_head.bias"),
        },
    }
    if unused:
        raise ValueError(
            "checkpoint has weights this importer does not map (architecture "
            f"deviates from the reference spec): {sorted(unused)[:8]}"
        )
    return cfg, params


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("checkpoint", help="reference .pt file (torch.save format)")
    ap.add_argument("--out_dir", required=True)
    ap.add_argument(
        "--tokenizer", default="gpt2",
        help="tokenizer name recorded for generate_text (reference uses gpt2/r50k)",
    )
    args = ap.parse_args()

    import torch

    raw = torch.load(args.checkpoint, map_location="cpu", weights_only=True)
    sd = raw.get("model_state_dict", raw)  # reference schema or a bare state_dict
    sd = _strip_prefixes({k: v.numpy() for k, v in sd.items() if hasattr(v, "numpy")})
    sd = {k: v for k, v in sd.items() if not k.endswith((".tril", "pos_idxs"))}

    cfg, params = import_state_dict(sd)

    import jax

    from pretraining_llm_tpu.config import Config, DataConfig
    from pretraining_llm_tpu.training import checkpoint as ckpt

    full_cfg = Config(
        model=cfg,
        data=DataConfig(tokenizer_name=args.tokenizer),
        name="imported-reference",
    )
    params = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
    path = ckpt.save_checkpoint(
        args.out_dir, 0, {"params": params},
        extra={"step": 0, "config": dataclasses.asdict(full_cfg),
               "preset": full_cfg.name, "source": os.path.abspath(args.checkpoint)},
    )
    n = sum(int(np.prod(np.shape(l))) for l in jax.tree.leaves(params))
    print(f"imported {n/1e6:.1f}M params ({cfg.n_layers}L d{cfg.d_model} "
          f"h{cfg.n_heads} ctx{cfg.context_length} V{cfg.vocab_size}) -> {path}")


if __name__ == "__main__":
    main()
