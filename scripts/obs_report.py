#!/usr/bin/env python
"""Offline run analyzer: metrics/events JSONL in, run report out.

The online half (pretraining_llm_tpu/observability/) streams events and
metrics to JSONL during the run; this script is the post-hoc fold over those
files — usable on a laptop against files scp'd off a pod, and run in CI over
the smoke run so the JSONL schema stays a checked contract.

    python scripts/obs_report.py run/obs/events.jsonl run/metrics.jsonl
    python scripts/obs_report.py --json --strict ...   # CI: machine output,
                                                       # nonzero on bad lines

Pass any mix of files: records carrying ``event`` + ``t_wall`` are treated as
run events (folded into the goodput decomposition and the event timeline);
records carrying ``step_ms`` feed the step-time histogram. ``--strict`` makes
unparseable lines fatal — a corrupt metrics stream (e.g. bare NaN tokens)
must fail CI, not be skipped.

``--trace trace.json --slo`` adds the SERVING view: the Chrome-trace export
(scripts/serve.py --trace, or any SpanRecorder export) is reconstructed into
per-request span trees keyed by ``trace_id``, each request's end-to-end
latency is decomposed into queue / admission / prefill / decode /
host-blocked / other segments that sum exactly to the root span, and every
SLO miss (``--slo_ttft_s`` / ``--slo_e2e_s``) is attributed to its dominant
segment — "why we missed", not just "that we missed". Under ``--strict``,
an incomplete span tree (missing root, terminal, or orphaned children) is
fatal, which is the CI tracing gate.

``--capacity`` adds the ENGINE view over the same events JSONL: the
``cap_window`` occupancy samples (one per reaped decode window) become a
slot-second waterfall — productive / admission-starved / pool-starved /
preempted-rework / spec-wasted, summing to wall time — the run's binding
constraint is named (slots vs. pool blocks vs. admission budget vs.
arrival rate), and every scheduler ``decision`` record (preempt, evict,
shed) is joined to its trace so "why was trace X preempted" is
answerable offline. ``--strict`` makes a >1% sum error or an unjoinable
decision fatal, which is the CI capacity gate.

``--fleet`` adds the ROUTER view: the ``fleet_req_submit`` /
``fleet_req_terminal`` streams are joined by ``frid`` to assert request
conservation (every accepted request reaches exactly one terminal — the
zero-lost invariant a replica-crash drill is checking), the
replica-tagged ``req_*`` streams become per-replica waterfalls,
``redrive`` events are folded into failover cost (requests redriven,
committed tokens carried over, e2e penalty vs. undisturbed), and
``replica_state`` transitions into per-incident recovery times. Injected
network partitions (``partition_injected``) are joined to whichever
mechanism detected them — ``lease_expired`` (heartbeats stopped) or
``fenced_frames_dropped`` (stale-generation frames arrived after heal) —
plus the redrives they caused; ``journal_replay`` events summarize a
router restart recovering from its fleet journal. Under ``--strict`` a
lost request, dangling redrive, or UNDETECTED partition is fatal, which
is the CI fleet gate.

``--fleet-trace`` adds the CROSS-HOST view over the same ``--trace``
export: each request becomes a lineage tree — the router's root span,
one ``req.attempt`` child per placement attempt (tagged replica + fence
generation + redrive index), and per-attempt worker subtrees shipped
over the span-export frame and clock-aligned into the router timeline by
the per-connection min-RTT offset estimator. The per-request waterfall
decomposes e2e ACROSS attempts (placement / attempts / redrive gaps /
finish, summing to the root), and inter-attempt gaps are joined to the
``redrive``/``lease_expired`` events that explain them. ``--strict``
fails unalignable spans, orphaned attempts or subtrees, and worker spans
outside their attempt's window beyond the recorded clock error bound,
which is the CI cross-host tracing gate.

``--live URL`` adds the ONLINE view: polls a running gateway's
``GET /slo`` (scripts/serve.py --http) for the rolling-window latency
sketches, per-class error budgets, burn rates, active alerts, and the
router's fleet-health snapshot. Given events JSONL paths too, the live
sketch percentiles are reconciled against exact percentiles computed
offline from the same run's terminal events — each live quantile must
land inside the exact-rank band ``[q-eps, q+eps]`` (the sketch's
accuracy contract). ``--strict`` makes a mismatch fatal, which is the
CI live-SLO gate.

Deliberately jax-free: imports only the stdlib + the observability package
(itself stdlib-only at import), so it runs where the training stack doesn't.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.observability.goodput import CATEGORIES, GoodputAccountant
from pretraining_llm_tpu.observability.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
)

# Events worth a line each in the timeline; step_window/device_memory are
# high-rate telemetry and only counted.
_NOTABLE = (
    "run_start", "run_end", "eval", "ckpt_save", "ckpt_restore", "rollback",
    "recompile", "wedge", "preempt", "relaunch", "failure", "fault_injected",
)


def _reject_constant(const: str) -> float:
    raise ValueError(f"non-finite JSON constant {const!r} (invalid strict JSON)")


def read_jsonl(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse one JSONL file; returns (records, bad_line_count)."""
    records: List[Dict[str, Any]] = []
    bad = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                # parse_constant: Python's json ACCEPTS bare NaN/Infinity by
                # default, but they are invalid JSON — exactly the corruption
                # --strict exists to catch (a logger writing a NaN loss raw).
                rec = json.loads(line, parse_constant=_reject_constant)
            except ValueError:
                bad += 1
                print(f"{path}:{lineno}: unparseable JSON line", file=sys.stderr)
                continue
            if not isinstance(rec, dict):
                bad += 1
                print(f"{path}:{lineno}: not a JSON object", file=sys.stderr)
                continue
            records.append(rec)
    return records, bad


def split_records(
    records: List[Dict[str, Any]],
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """(events, metrics): stamped run events vs per-step metric records."""
    events = [r for r in records if "event" in r and "t_wall" in r]
    metrics = [r for r in records if "step_ms" in r]
    return events, metrics


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def step_time_stats(metrics: List[Dict[str, Any]], bins: int = 10) -> Dict[str, Any]:
    vals = sorted(
        float(r["step_ms"]) for r in metrics
        if isinstance(r.get("step_ms"), (int, float))
    )
    if not vals:
        return {"count": 0}
    lo, hi = vals[0], vals[-1]
    width = (hi - lo) / bins if hi > lo else 1.0
    counts = [0] * bins
    for v in vals:
        counts[min(bins - 1, int((v - lo) / width))] += 1
    return {
        "count": len(vals),
        "mean_ms": sum(vals) / len(vals),
        "p50_ms": _percentile(vals, 0.50),
        "p90_ms": _percentile(vals, 0.90),
        "max_ms": hi,
        "histogram": [
            {"lo_ms": lo + i * width, "hi_ms": lo + (i + 1) * width, "count": c}
            for i, c in enumerate(counts)
        ],
    }


def timeline(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chronological notable events, timestamped relative to the first."""
    stamped = sorted(events, key=lambda e: e["t_wall"])
    if not stamped:
        return []
    t0 = stamped[0]["t_wall"]
    out = []
    for e in stamped:
        if e["event"] not in _NOTABLE:
            continue
        entry = {"t_rel_s": round(e["t_wall"] - t0, 3), "event": e["event"]}
        for key in (
            "step", "dur_s", "to_step", "why", "rc", "exit_reason",
            "anomaly", "fault",
        ):
            if key in e:
                entry[key] = e[key]
        out.append(entry)
    return out


# -- serving trace analysis (--trace / --slo) ------------------------------

# Span names the request tracer emits (tracing.RequestTrace); the segment
# decomposition below keys on them.
_ROOT = "req.request"
_TERMINAL = "req.terminal"
_SEGMENT_SPANS = ("req.queue", "req.admission", "req.prefill",
                  "req.prefill_chunk", "req.window")


def load_trace(path: str) -> Dict[str, Any]:
    """Load a Chrome trace-event JSON export (SpanRecorder.to_chrome_trace
    shape: {"traceEvents": [...], "otherData": {...}})."""
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError(f"{path}: not a Chrome trace export (no traceEvents)")
    return obj


def group_request_spans(trace: Dict[str, Any]) -> Dict[str, List[Dict[str, Any]]]:
    """Group complete ("X") events by ``args.trace_id``. Host spans without
    a trace_id (the engine loop's own rows) are not request spans and are
    skipped."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            groups.setdefault(tid, []).append(ev)
    return groups


def _union_s(intervals: List[Tuple[float, float]]) -> float:
    """Total length (seconds) of the union of [t0, t1] intervals in µs —
    decode windows OVERLAP under deep pipelining, so summing their
    durations would double-count device time the request shared."""
    total = 0.0
    end = float("-inf")
    for t0, t1 in sorted(intervals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total / 1e6


_ATTEMPT = "req.attempt"


def _is_remote(ev: Dict[str, Any]) -> bool:
    return bool((ev.get("args") or {}).get("remote"))


def check_trace_tree(trace_id: str, spans: List[Dict[str, Any]]) -> List[str]:
    """Structural completeness for ONE request's span tree; returns
    problems (empty = complete). What 'complete' means depends on how the
    request ended: a done request must show the whole journey (queue,
    prefill, at least one decode window, first token, terminal); a
    rejected one only its admission verdict; cancelled/expired/error at
    minimum the queue time they burned before dying.

    Cross-host traces are three-level: the ROUTER owns the root and the
    terminal, each placement attempt is a ``req.attempt`` child, and a
    worker that served an attempt contributes its own subtree — a
    ``remote`` ``req.request`` parented to the attempt's span_id, with the
    engine spans (queue/prefill/window/first_token) under it. So remote
    spans are exempt from the parented-to-root rule (they parent through
    their attempt) but still count toward the journey: a done request's
    prefill may live on the worker, not the router."""
    problems: List[str] = []
    short = trace_id[:12]
    local = [ev for ev in spans if not _is_remote(ev)]
    remote = [ev for ev in spans if _is_remote(ev)]
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for ev in spans:
        by_name.setdefault(ev["name"], []).append(ev)
    roots = [ev for ev in local if ev["name"] == _ROOT]
    if len(roots) != 1:
        problems.append(f"trace {short}: {len(roots)} root spans (want 1)")
        return problems  # nothing else is checkable without the root
    root = roots[0]
    root_sid = root["args"].get("span_id")
    status = root["args"].get("status")
    terminals = [ev for ev in local if ev["name"] == _TERMINAL]
    if len(terminals) != 1:
        problems.append(f"trace {short}: {len(terminals)} terminal events (want 1)")
    elif terminals[0]["args"].get("status") != status:
        problems.append(
            f"trace {short}: terminal status "
            f"{terminals[0]['args'].get('status')!r} != root {status!r}"
        )
    for ev in local:
        if ev is root:
            continue
        if ev["args"].get("parent_span_id") != root_sid:
            problems.append(
                f"trace {short}: {ev['name']} span not parented to root"
            )
    # Worker subtrees: each remote root must hang off one of THIS trace's
    # attempt spans; every other remote span must hang off a remote root.
    # (A redriven worker-side attempt keeps its own local terminal status
    # — only the ROUTER's terminal speaks for the request, so remote
    # statuses are not cross-checked here.)
    attempt_ids = {
        ev["args"].get("span_id") for ev in local if ev["name"] == _ATTEMPT
    }
    remote_root_ids = {
        ev["args"].get("span_id") for ev in remote if ev["name"] == _ROOT
    }
    for ev in remote:
        parent = ev["args"].get("parent_span_id")
        if ev["name"] == _ROOT:
            if parent not in attempt_ids:
                problems.append(
                    f"trace {short}: worker subtree (replica "
                    f"{ev['args'].get('worker')}) not parented to any "
                    f"req.attempt span"
                )
        elif parent not in remote_root_ids:
            # A stray child whose subtree root never arrived (the root
            # dies with a fenced partition while earlier export batches
            # already shipped the child) is honest loss, tolerated — but
            # the journey check below still requires the SURVIVING
            # attempt's subtree to be whole.
            continue
    need = {
        "done": ("req.queue", "req.prefill", "req.window",
                 "req.first_token", _TERMINAL),
        "rejected": ("req.admission", _TERMINAL),
    }.get(status, ("req.queue", _TERMINAL))
    for name in need:
        if name == "req.prefill" and "req.prefill_chunk" in by_name:
            # Chunked prefill replaces the monolithic prefill span with
            # one span per chunk; either form proves the prompt landed.
            continue
        if name not in by_name:
            problems.append(
                f"trace {short} ({status}): missing {name} span"
            )
    return problems


def request_waterfall(trace_id: str, spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One request's latency decomposition. Segments sum to the root e2e
    exactly: decode is the UNION of the (possibly overlapping) window
    intervals, host_blocked is carved out of it from the per-window
    ``host_blocked_s`` meta, and ``other`` is the residual no child span
    claims (scheduler turnaround, token reap-to-notify, SSE write).

    Cross-host traces decompose the same way: the ROUTER's root anchors
    e2e, and a worker's clock-aligned queue/prefill/window spans fill the
    segments exactly as in-process ones would (they are clipped to the
    root, so any clock-mapping slop at the edges cannot break the
    sums-to-e2e contract)."""
    root = next(
        ev for ev in spans if ev["name"] == _ROOT and not _is_remote(ev)
    )
    r0, r1 = root["ts"], root["ts"] + root["dur"]

    def clipped(name: str) -> List[Tuple[float, float]]:
        return [
            (max(ev["ts"], r0), min(ev["ts"] + ev["dur"], r1))
            for ev in spans
            if ev["name"] == name and ev["ts"] + ev["dur"] > r0 and ev["ts"] < r1
        ]

    e2e_s = root["dur"] / 1e6
    queue_s = _union_s(clipped("req.queue"))
    admission_s = _union_s(clipped("req.admission"))
    prefill_s = _union_s(clipped("req.prefill"))
    # Chunked prefill emits one span per chunk instead of one monolithic
    # req.prefill; union them into their own segment so the waterfall
    # shows how much of TTFT the chunk lane itself consumed.
    chunked_prefill_s = _union_s(clipped("req.prefill_chunk"))
    windows = [ev for ev in spans if ev["name"] == "req.window"]
    decode_union_s = _union_s(clipped("req.window"))
    host_blocked_s = min(
        decode_union_s,
        sum(float(ev["args"].get("host_blocked_s", 0.0)) for ev in windows),
    )
    claimed = (queue_s + admission_s + prefill_s + chunked_prefill_s
               + decode_union_s)
    segments = {
        "queue_s": queue_s,
        "admission_s": admission_s,
        "prefill_s": prefill_s,
        "chunked_prefill_s": chunked_prefill_s,
        "decode_s": decode_union_s - host_blocked_s,
        "host_blocked_s": host_blocked_s,
        "other_s": max(0.0, e2e_s - claimed),
    }
    first = [ev for ev in spans if ev["name"] == "req.first_token"]
    out = {
        "trace_id": trace_id,
        "status": root["args"].get("status"),
        "e2e_s": e2e_s,
        "ttft_s": (min(ev["ts"] for ev in first) - r0) / 1e6 if first else None,
        "n_windows": len(windows),
        "segments": segments,
        # >0 means child spans overlapped beyond the model (a tracer bug);
        # the acceptance bound is |error| <= 1% of e2e.
        "sum_error_s": sum(segments.values()) - e2e_s,
    }
    return out


def _tail(vals: List[float]) -> Dict[str, float]:
    """Bucket-estimated tail percentiles via the SAME histogram class the
    live /metrics endpoint uses — the offline report and the dashboard
    quantiles disagree only by bucket width, never by method."""
    h = Histogram("tail", {}, buckets=DEFAULT_LATENCY_BUCKETS)
    for v in vals:
        h.observe(v)
    if not vals:
        return {}
    return {q: h.percentile(p) for q, p in
            (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))}


def build_slo_report(
    trace: Dict[str, Any],
    *,
    slo_ttft_s: float = 0.0,
    slo_e2e_s: float = 0.0,
) -> Dict[str, Any]:
    """Fold a trace export into the per-request SLO attribution view."""
    groups = group_request_spans(trace)
    problems: List[str] = []
    waterfalls: List[Dict[str, Any]] = []
    for trace_id, spans in sorted(groups.items()):
        ps = check_trace_tree(trace_id, spans)
        problems.extend(ps)
        if any(ev["name"] == _ROOT and not _is_remote(ev) for ev in spans):
            waterfalls.append(request_waterfall(trace_id, spans))
    waterfalls.sort(key=lambda w: w["e2e_s"], reverse=True)

    def _missed(w: Dict[str, Any]) -> Optional[str]:
        if w["status"] != "done":
            return f"status={w['status']}"
        if slo_ttft_s > 0 and (w["ttft_s"] is None or w["ttft_s"] > slo_ttft_s):
            return f"ttft {w['ttft_s']:.3f}s > {slo_ttft_s}s" if w["ttft_s"] \
                is not None else "no first token"
        if slo_e2e_s > 0 and w["e2e_s"] > slo_e2e_s:
            return f"e2e {w['e2e_s']:.3f}s > {slo_e2e_s}s"
        return None

    misses = []
    for w in waterfalls:
        why = _missed(w)
        if why is None:
            continue
        dominant = max(w["segments"], key=lambda k: w["segments"][k])
        misses.append({**w, "why": why, "dominant_segment": dominant})
    done = [w for w in waterfalls if w["status"] == "done"]
    dropped = int((trace.get("otherData") or {}).get("dropped_spans", 0))
    return {
        "n_traces": len(groups),
        "n_done": len(done),
        "statuses": {
            s: sum(1 for w in waterfalls if w["status"] == s)
            for s in sorted({w["status"] for w in waterfalls} - {None})
        },
        "slo": {"ttft_s": slo_ttft_s, "e2e_s": slo_e2e_s},
        "misses": misses,
        "waterfalls": waterfalls,
        "tails": {
            "e2e_s": _tail([w["e2e_s"] for w in done]),
            "ttft_s": _tail([w["ttft_s"] for w in done if w["ttft_s"] is not None]),
        },
        "max_sum_error_s": max(
            (abs(w["sum_error_s"]) for w in waterfalls), default=0.0
        ),
        "dropped_spans": dropped,
        "problems": problems,
    }


_SEG_ORDER = ("queue_s", "admission_s", "prefill_s", "chunked_prefill_s",
              "decode_s", "host_blocked_s", "other_s")


def print_slo_report(report: Dict[str, Any]) -> None:
    print("== serving slo ==")
    print(
        f"traces={report['n_traces']} done={report['n_done']} "
        f"statuses={report['statuses']}"
    )
    for metric, tails in report["tails"].items():
        if tails:
            print(
                f"  {metric:<8} " + " ".join(
                    f"{q}={v:.4f}s" for q, v in tails.items()
                )
            )
    if report["dropped_spans"]:
        print(
            f"!! trace is INCOMPLETE: {report['dropped_spans']} spans "
            f"dropped at record time — waterfalls below may be partial",
        )
    print("== waterfalls (slowest first) ==")
    hdr = "  trace_id      status     e2e_s " + " ".join(
        f"{s[:-2]:>9}" for s in _SEG_ORDER
    )
    print(hdr)
    for w in report["waterfalls"][:20]:
        segs = " ".join(f"{w['segments'][s]:9.4f}" for s in _SEG_ORDER)
        print(
            f"  {w['trace_id'][:12]:<12} {w['status'] or '?':<9} "
            f"{w['e2e_s']:6.3f} {segs}"
        )
    if len(report["waterfalls"]) > 20:
        print(f"  ... {len(report['waterfalls']) - 20} more")
    if report["misses"]:
        print("== slo misses: why ==")
        for m in report["misses"]:
            seg = m["dominant_segment"]
            print(
                f"  {m['trace_id'][:12]:<12} {m['why']:<28} dominant="
                f"{seg[:-2]} ({m['segments'][seg]:.3f}s of {m['e2e_s']:.3f}s)"
            )
    elif report["slo"]["ttft_s"] or report["slo"]["e2e_s"]:
        print("== slo misses: none ==")
    for p in report["problems"]:
        print(f"!! {p}")


# -- cross-host trace analysis (--fleet-trace) ------------------------------

# Slack added to each span's recorded clock error bound when checking
# containment: covers send/receive latency between the router stamping the
# attempt edges and the worker stamping its own spans.
_ALIGN_SLACK_S = 0.002


def build_fleet_trace_report(
    trace: Dict[str, Any],
    events: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Fold a merged cross-host trace into the fleet-trace view.

    Each request is a LINEAGE TREE: the router's root span anchors e2e,
    every placement attempt is a ``req.attempt`` child tagged (replica,
    fence, redrive), and a worker that served an attempt contributes a
    clock-aligned subtree ingested over the span-export frame. The
    decomposition here is ACROSS attempts and sums to the root e2e by
    construction: placement (root start to first attempt), the union of
    attempt intervals, inter-attempt gaps (the redrive/partition-detection
    dead time — joined to ``redrive``/``lease_expired`` events when an
    events JSONL rides along), and finish (last attempt to terminal).

    Problems (all strict): a span the ingester could not clock-align
    (``unaligned`` meta — no offset estimate existed yet), a worker
    subtree root orphaned from its attempt, a worker span lying outside
    its attempt's window by more than its recorded clock error bound
    (+ a small send-latency slack), a worker-span group with no router
    root at all, and an attempt-union/e2e sum error > 1%. Remote CHILD
    spans whose subtree root never arrived are honest loss, not a lie —
    a partitioned worker's root dies with the fenced connection while
    earlier export batches already shipped some children — so they are
    counted (``n_stray_spans``), excluded from alignment, never strict.
    """
    groups = group_request_spans(trace)
    events = events or []
    redrive_ev = [e for e in events if e.get("event") == "redrive"]
    lease_ev = [e for e in events if e.get("event") == "lease_expired"]
    problems: List[str] = []
    requests: List[Dict[str, Any]] = []
    n_worker_spans = 0
    n_unaligned = 0
    n_stray_spans = 0
    max_clock_err_s = 0.0
    for trace_id, spans in sorted(groups.items()):
        short = trace_id[:12]
        local = [ev for ev in spans if not _is_remote(ev)]
        remote = [ev for ev in spans if _is_remote(ev)]
        n_worker_spans += len(remote)
        for ev in remote:
            err = ev["args"].get("clock_err_s")
            if err is not None:
                max_clock_err_s = max(max_clock_err_s, float(err))
            if ev["args"].get("unaligned"):
                n_unaligned += 1
                problems.append(
                    f"trace {short}: unalignable worker span "
                    f"{ev['name']!r} (replica {ev['args'].get('worker')}: "
                    f"no clock offset estimate at ingest)"
                )
        roots = [ev for ev in local if ev["name"] == _ROOT]
        if len(roots) != 1:
            problems.append(
                f"trace {short}: {len(roots)} router root spans (want 1) "
                f"— worker spans cannot join a lineage tree"
            )
            continue
        root = roots[0]
        r0, r1 = float(root["ts"]), float(root["ts"]) + float(root["dur"])
        e2e_s = float(root["dur"]) / 1e6
        attempts = sorted(
            (ev for ev in local if ev["name"] == _ATTEMPT),
            key=lambda ev: float(ev["ts"]),
        )
        attempt_by_sid = {ev["args"].get("span_id"): ev for ev in attempts}

        # Worker subtree -> owning attempt (remote roots parent to the
        # attempt's span_id; other remote spans parent to a remote root).
        subtree_attempt: Dict[Any, Dict[str, Any]] = {}
        for ev in remote:
            if ev["name"] != _ROOT:
                continue
            att = attempt_by_sid.get(ev["args"].get("parent_span_id"))
            if att is None:
                problems.append(
                    f"trace {short}: worker subtree (replica "
                    f"{ev['args'].get('worker')}) orphaned — its parent "
                    f"attempt span is missing from the tree"
                )
            else:
                subtree_attempt[ev["args"].get("span_id")] = att

        def _owning_attempt(ev: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            if ev["name"] == _ROOT:
                return subtree_attempt.get(ev["args"].get("span_id"))
            return subtree_attempt.get(ev["args"].get("parent_span_id"))

        # Clock-alignment acceptance: every aligned worker span must lie
        # inside its attempt's window, within the error bound recorded at
        # ingest (the live min-RTT estimate) plus the send-latency slack.
        for ev in remote:
            if ev["args"].get("unaligned"):
                continue
            att = _owning_attempt(ev)
            if att is None:
                if ev["name"] != _ROOT:
                    # Stray child: its subtree root never arrived (lost
                    # behind a fenced partition after earlier batches
                    # shipped the child) — counted, not strict.
                    n_stray_spans += 1
                continue
            tol_us = (
                float(ev["args"].get("clock_err_s", 0.0)) + _ALIGN_SLACK_S
            ) * 1e6
            a0, a1 = float(att["ts"]), float(att["ts"]) + float(att["dur"])
            if (float(ev["ts"]) < a0 - tol_us
                    or float(ev["ts"]) + float(ev["dur"]) > a1 + tol_us):
                problems.append(
                    f"trace {short}: worker span {ev['name']!r} lies "
                    f"outside its attempt window by more than the clock "
                    f"error bound ({ev['args'].get('clock_err_s', 0.0)}s)"
                )

        # Cross-attempt decomposition: merge the (clipped) attempt
        # intervals, then placement/attempts/gaps/finish sum to e2e.
        ivs = sorted(
            (max(float(ev["ts"]), r0),
             min(float(ev["ts"]) + float(ev["dur"]), r1))
            for ev in attempts
            if float(ev["ts"]) < r1 and float(ev["ts"]) + float(ev["dur"]) > r0
        )
        merged: List[List[float]] = []
        for s, e in ivs:
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        attempts_s = sum(e - s for s, e in merged) / 1e6
        placement_s = (merged[0][0] - r0) / 1e6 if merged else e2e_s
        finish_s = (r1 - merged[-1][1]) / 1e6 if merged else 0.0
        gaps: List[Dict[str, Any]] = []
        for (_, g0), (g1, _) in zip(merged, merged[1:]):
            causes = []
            for ev in redrive_ev:
                if (ev.get("trace_id") == trace_id
                        and g0 / 1e6 - 0.5 <= float(ev.get("t_wall", 0.0))
                        <= g1 / 1e6 + 0.5):
                    causes.append(f"redrive:{ev.get('reason', '?')}")
            for ev in lease_ev:
                if g0 / 1e6 - 0.5 <= float(ev.get("t_wall", 0.0)) \
                        <= g1 / 1e6 + 0.5:
                    causes.append(
                        f"partition_detect:lease_expired"
                        f"(replica {ev.get('replica')})"
                    )
            gaps.append({
                "t_rel_s": (g0 - r0) / 1e6,
                "dur_s": (g1 - g0) / 1e6,
                "causes": causes,
            })
        gap_s = sum(g["dur_s"] for g in gaps)
        sum_error_s = (placement_s + attempts_s + gap_s + finish_s) - e2e_s
        if e2e_s > 0 and abs(sum_error_s) > 0.01 * e2e_s:
            problems.append(
                f"trace {short}: cross-host segments sum to "
                f"{placement_s + attempts_s + gap_s + finish_s:.4f}s but "
                f"e2e is {e2e_s:.4f}s (error > 1%)"
            )

        att_rows = []
        for ev in attempts:
            sid = ev["args"].get("span_id")
            sub = [
                rv for rv in remote
                if _owning_attempt(rv) is attempt_by_sid.get(sid)
            ]
            att_rows.append({
                "outcome": ev["args"].get("outcome"),
                "replica": ev["args"].get("replica"),
                "fence": ev["args"].get("fence"),
                "redrive": ev["args"].get("redrive"),
                "t_rel_s": (float(ev["ts"]) - r0) / 1e6,
                "dur_s": float(ev["dur"]) / 1e6,
                "worker_spans": len(sub),
                "worker_decode_s": _union_s([
                    (float(rv["ts"]), float(rv["ts"]) + float(rv["dur"]))
                    for rv in sub if rv["name"] == "req.window"
                ]),
                "clock_err_s": max(
                    (float(rv["args"].get("clock_err_s", 0.0))
                     for rv in sub), default=None,
                ) if sub else None,
            })
        requests.append({
            "trace_id": trace_id,
            "status": root["args"].get("status"),
            "redrives": root["args"].get("redrives"),
            "e2e_s": e2e_s,
            "segments": {
                "placement_s": placement_s,
                "attempts_s": attempts_s,
                "redrive_gap_s": gap_s,
                "finish_s": finish_s,
            },
            "sum_error_s": sum_error_s,
            "attempts": att_rows,
            "gaps": gaps,
        })
    requests.sort(key=lambda r: r["e2e_s"], reverse=True)
    return {
        "n_requests": len(requests),
        "n_attempts": sum(len(r["attempts"]) for r in requests),
        "n_worker_spans": n_worker_spans,
        "n_unaligned": n_unaligned,
        "n_stray_spans": n_stray_spans,
        "max_clock_err_s": max_clock_err_s,
        "redriven_requests": sum(
            1 for r in requests if len(r["attempts"]) > 1
        ),
        "requests": requests,
        "problems": problems,
    }


def print_fleet_trace_report(report: Dict[str, Any]) -> None:
    print("== fleet trace ==")
    print(
        f"requests={report['n_requests']} attempts={report['n_attempts']} "
        f"worker_spans={report['n_worker_spans']} "
        f"unaligned={report['n_unaligned']} "
        f"stray={report['n_stray_spans']} "
        f"max_clock_err={report['max_clock_err_s'] * 1e3:.3f}ms "
        f"redriven={report['redriven_requests']}"
    )
    for r in report["requests"][:20]:
        seg = r["segments"]
        print(
            f"  {r['trace_id'][:12]:<12} {r['status'] or '?':<9} "
            f"e2e={r['e2e_s']:.3f}s placement={seg['placement_s']:.4f}s "
            f"attempts={seg['attempts_s']:.4f}s "
            f"gaps={seg['redrive_gap_s']:.4f}s "
            f"finish={seg['finish_s']:.4f}s "
            f"(err={r['sum_error_s']:+.4f}s)"
        )
        for a in r["attempts"]:
            err = (
                f" clock_err={a['clock_err_s'] * 1e3:.3f}ms"
                if a["clock_err_s"] is not None else ""
            )
            print(
                f"    attempt r{a['replica']} g{a['fence']} "
                f"#{a['redrive']}: {a['outcome'] or '?':<11} "
                f"+{a['t_rel_s']:.4f}s {a['dur_s']:.4f}s "
                f"worker_spans={a['worker_spans']} "
                f"decode={a['worker_decode_s']:.4f}s{err}"
            )
        for g in r["gaps"]:
            why = " ".join(g["causes"]) or "?"
            print(
                f"    gap +{g['t_rel_s']:.4f}s {g['dur_s']:.4f}s <- {why}"
            )
    if len(report["requests"]) > 20:
        print(f"  ... {len(report['requests']) - 20} more")
    for p in report["problems"]:
        print(f"!! {p}")


_TERMINAL_EVENTS = ("req_done", "req_cancelled", "req_expired", "req_error")


def prefix_cache_summary(
    events: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Fold terminal request events into the prefix-cache view: hit rate
    over requests that reached the engine and total prompt tokens served
    from cache instead of prefill. ``cached_tokens`` only appears in
    terminal events when the engine ran with the cache on (it accumulates
    across preemption re-admissions), so absence means no section."""
    term = [
        e for e in events
        if e.get("event") in _TERMINAL_EVENTS and "cached_tokens" in e
    ]
    if not term:
        return None
    hit = sum(1 for e in term if int(e["cached_tokens"]) > 0)
    return {
        "requests": len(term),
        "hit_requests": hit,
        "hit_rate": hit / len(term),
        "prefill_tokens_saved": sum(int(e["cached_tokens"]) for e in term),
    }


# -- capacity attribution (--capacity) --------------------------------------

_CAP_SEGMENTS = ("productive", "admission_starved", "pool_starved",
                 "preempted_rework", "spec_wasted")


def build_capacity_report(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold ``cap_window`` + ``decision`` events into the capacity view:
    decode-slot-seconds decomposed into segments that sum to wall time,
    the run's binding constraint, and decision records joined to traces.

    The engine dispatches windows sequentially and reaps them FIFO, so
    ``cap_window`` records (stamped at reap with perf_counter dispatch/
    reap times) arrive in dispatch order; under deep pipelining
    consecutive windows OVERLAP, so each window is charged only its NEW
    coverage (``d_eff``, the same interval-union idea as ``_union_s``)
    and the residual gaps are host time between device windows. Within a
    window's coverage: the active-row share splits into productive
    (committed tokens) vs. spec-wasted (dispatched slot-tokens never
    committed — rejected speculative drafts or overrun past stop/
    max_new); the idle-row share is pool-starved when requests were
    waiting (rows existed to fill, blocks did not) and admission-starved
    when the queue was empty. Gaps split by the rework fraction of the
    prefill they contain (recompute-on-resume re-prefill is pure
    preemption cost); the remainder follows the same waiting test. Every
    charge is a disjoint share of [first dispatch, last reap], so the
    segments sum to wall time by construction — the strict gate checks
    the arithmetic anyway."""
    wins = sorted(
        (e for e in events if e.get("event") == "cap_window"),
        key=lambda e: (float(e["t_dispatch_s"]), float(e["t_reap_s"])),
    )
    decisions = [e for e in events if e.get("event") == "decision"]
    decision_counts: Dict[str, int] = {}
    for d in decisions:
        k = d.get("decision", "?")
        decision_counts[k] = decision_counts.get(k, 0) + 1

    # Join decisions to the request stream: every trace_id a decision
    # carries must name a request the req_* events know about.
    known = {
        e["trace_id"] for e in events
        if str(e.get("event", "")).startswith("req_") and e.get("trace_id")
    }
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    problems: List[str] = []
    for d in decisions:
        tid = d.get("trace_id")
        if not tid:
            continue
        by_trace.setdefault(tid, []).append(
            {k: v for k, v in d.items()
             if k not in ("event", "seq", "t_wall", "t_mono")}
        )
        if tid not in known:
            problems.append(
                f"decision {d.get('decision')} carries trace_id "
                f"{tid[:12]} with no matching req_* event"
            )

    report: Dict[str, Any] = {
        "n_windows": len(wins),
        "decisions": decision_counts,
        "decisions_by_trace": by_trace,
        "problems": problems,
    }
    if not wins:
        problems.append("no cap_window events (capacity sampling off?)")
        return report

    rows_cap = int(wins[0]["rows_capacity"])
    t0 = min(float(w["t_dispatch_s"]) for w in wins)
    t1 = max(float(w["t_reap_s"]) for w in wins)
    wall = t1 - t0
    segments = dict.fromkeys(_CAP_SEGMENTS, 0.0)
    slot_bound_s = 0.0
    admission_saturated = decision_counts.get("reject_busy", 0) > 0
    cover_end = t0
    prev_waiting = 0
    prev_prefill = prev_rework = None
    for w in wins:
        t_d, t_r = float(w["t_dispatch_s"]), float(w["t_reap_s"])
        cum_prefill = int(w.get("cum_prefill_tokens", 0))
        cum_rework = int(w.get("cum_rework_prefill_tokens", 0))
        gap = max(0.0, t_d - cover_end)
        if gap > 0.0:
            dp = cum_prefill - (prev_prefill or 0)
            dr = cum_rework - (prev_rework or 0)
            rework_frac = min(1.0, dr / dp) if dp > 0 else 0.0
            segments["preempted_rework"] += gap * rework_frac
            rest = gap * (1.0 - rework_frac)
            if prev_waiting > 0 or dp > 0:
                # Host between windows with real work queued (or fresh
                # prefill landing): scheduling/prefill of useful tokens.
                segments["productive"] += rest
            else:
                segments["admission_starved"] += rest
        d_eff = max(0.0, t_r - max(t_d, cover_end))
        cover_end = max(cover_end, t_r)
        rows = int(w["rows"])
        slot_tokens = rows * int(w["steps"])
        committed = min(int(w["tokens_committed"]), slot_tokens)
        frac = committed / slot_tokens if slot_tokens else 0.0
        active_s = d_eff * rows / rows_cap if rows_cap else 0.0
        segments["productive"] += active_s * frac
        segments["spec_wasted"] += active_s * (1.0 - frac)
        idle_s = max(0.0, d_eff - active_s)
        waiting = int(w["waiting"])
        if waiting > 0:
            segments["pool_starved"] += idle_s
            if rows >= rows_cap:
                slot_bound_s += d_eff
        else:
            segments["admission_starved"] += idle_s
        limit = w.get("admission_depth_limit")
        if limit and int(w.get("admission_depth", 0)) >= int(limit):
            admission_saturated = True
        prev_waiting = waiting
        prev_prefill, prev_rework = cum_prefill, cum_rework

    total = sum(segments.values())
    pool_bound_s = segments["pool_starved"] + segments["preempted_rework"]
    scores = {
        "slots": slot_bound_s,
        "pool_blocks": pool_bound_s,
        "admission_budget":
            segments["admission_starved"] if admission_saturated else 0.0,
        "arrival_rate":
            0.0 if admission_saturated else segments["admission_starved"],
    }
    report.update({
        "rows_capacity": rows_cap,
        "pool_total": int(wins[0].get("pool_total", 0)),
        "wall_s": wall,
        "segments": segments,
        "sum_error_s": total - wall,
        "binding_constraint": max(scores, key=lambda k: scores[k]),
        "constraint_scores": scores,
    })
    if wall > 0 and abs(total - wall) > 0.01 * wall:
        problems.append(
            f"capacity segments sum to {total:.4f}s but wall is "
            f"{wall:.4f}s (error {abs(total - wall) / wall:.2%} > 1%)"
        )
    return report


def print_capacity_report(report: Dict[str, Any]) -> None:
    print("== capacity ==")
    if "segments" not in report:
        print("no cap_window events")
    else:
        wall = report["wall_s"]
        print(
            f"wall={wall:.3f}s windows={report['n_windows']} "
            f"rows_capacity={report['rows_capacity']} "
            f"pool_blocks={report['pool_total']}"
        )
        for seg in _CAP_SEGMENTS:
            sec = report["segments"][seg]
            pct = 100.0 * sec / wall if wall > 0 else 0.0
            bar = "#" * int(round(pct / 2))
            print(f"  {seg:<17} {sec:9.3f}s {pct:5.1f}% {bar}")
        print(
            f"sum_error={report['sum_error_s']:+.4f}s  binding constraint: "
            f"{report['binding_constraint']} (" + " ".join(
                f"{k}={v:.3f}s"
                for k, v in report["constraint_scores"].items()
            ) + ")"
        )
    if report["decisions"]:
        print("== scheduler decisions ==")
        for kind, n in sorted(report["decisions"].items()):
            print(f"  {kind:<18} {n}")
    if report["decisions_by_trace"]:
        print("== decisions by trace (why was my request shed?) ==")
        items = sorted(report["decisions_by_trace"].items())
        for tid, recs in items[:20]:
            kinds = " ".join(
                r.get("decision", "?") + (
                    f"(-{r['blocks_reclaimed']}blk)"
                    if "blocks_reclaimed" in r else ""
                )
                for r in recs
            )
            print(f"  {tid[:12]:<12} {kinds}")
        if len(items) > 20:
            print(f"  ... {len(items) - 20} more")
    for p in report["problems"]:
        print(f"!! {p}")


# -- fleet attribution (--fleet) --------------------------------------------


def build_fleet_report(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the fleet event streams into the router view:

      conservation  every ``fleet_req_submit`` frid must reach exactly one
                    ``fleet_req_terminal`` — the zero-lost-requests
                    invariant a crash/drain drill is asserting (strict);
      per-replica   request waterfalls from the replica-tagged ``req_*``
                    streams: what each replica accepted, finished, failed
                    (its loop died mid-decode) — failure here is NORMAL
                    fleet operation as long as conservation holds;
      redrive cost  how many requests failed over, the committed-token
                    frontier they carried (tokens NOT regenerated), and
                    the e2e penalty vs. undisturbed requests;
      recovery      per-replica lifecycle from ``replica_state`` events:
                    active -> ejected/draining -> active, with the
                    out-of-service interval measured from the bus clock.
    """
    submits = [e for e in events if e.get("event") == "fleet_req_submit"]
    terms = [e for e in events if e.get("event") == "fleet_req_terminal"]
    redrives = [e for e in events if e.get("event") == "redrive"]
    states = [e for e in events if e.get("event") == "replica_state"]
    brownouts = [e for e in events if e.get("event") == "brownout"]

    problems: List[str] = []
    sub_frids: Dict[Any, Dict[str, Any]] = {}
    for e in submits:
        frid = e.get("frid")
        if frid in sub_frids:
            problems.append(f"duplicate fleet_req_submit for frid {frid}")
        sub_frids[frid] = e
    term_frids: Dict[Any, Dict[str, Any]] = {}
    for e in terms:
        frid = e.get("frid")
        if frid in term_frids:
            problems.append(f"duplicate fleet_req_terminal for frid {frid}")
        term_frids[frid] = e
    lost = sorted(set(sub_frids) - set(term_frids))
    for frid in lost:
        problems.append(
            f"LOST request: frid {frid} submitted but never reached a "
            f"terminal (conservation violated)"
        )
    for frid in sorted(set(term_frids) - set(sub_frids)):
        problems.append(
            f"orphan fleet_req_terminal for frid {frid} (no submit)"
        )
    for e in redrives:
        if e.get("frid") not in sub_frids:
            problems.append(
                f"redrive references unknown frid {e.get('frid')}"
            )

    status_counts: Dict[str, int] = {}
    for e in terms:
        s = str(e.get("status", "?"))
        status_counts[s] = status_counts.get(s, 0) + 1

    # Per-replica waterfalls from the replica-tagged EngineLoop streams.
    per_replica: Dict[int, Dict[str, int]] = {}

    def _rep_slot(r: Any) -> Dict[str, int]:
        return per_replica.setdefault(
            int(r),
            {"submits": 0, "done": 0, "errors": 0, "expired": 0,
             "cancelled": 0, "tokens": 0, "redrives_in": 0,
             "redrives_out": 0},
        )

    for e in events:
        r = e.get("replica")
        if r is None:
            continue
        kind = e.get("event")
        if kind == "req_submit":
            _rep_slot(r)["submits"] += 1
        elif kind == "req_done":
            slot = _rep_slot(r)
            slot["done"] += 1
            slot["tokens"] += int(e.get("n_tokens", 0))
        elif kind == "req_error":
            _rep_slot(r)["errors"] += 1
        elif kind == "req_expired":
            _rep_slot(r)["expired"] += 1
        elif kind == "req_cancelled":
            _rep_slot(r)["cancelled"] += 1
    for e in redrives:
        if e.get("from_replica") is not None:
            _rep_slot(e["from_replica"])["redrives_out"] += 1
        if e.get("to_replica") is not None:
            _rep_slot(e["to_replica"])["redrives_in"] += 1

    # Redrive cost: the committed frontier carried over is decode work the
    # failover did NOT repeat; the e2e delta vs undisturbed is what it cost.
    redriven_e2e = sorted(
        float(e["e2e_s"]) for e in terms
        if int(e.get("redrives", 0)) > 0 and e.get("e2e_s") is not None
    )
    clean_e2e = sorted(
        float(e["e2e_s"]) for e in terms
        if int(e.get("redrives", 0)) == 0 and e.get("e2e_s") is not None
    )
    redrive_cost = {
        "redriven_requests": sum(
            1 for e in terms if int(e.get("redrives", 0)) > 0
        ),
        "redrive_events": len(redrives),
        "tokens_carried_over": sum(
            int(e.get("n_committed", 0)) for e in redrives
        ),
        "reasons": {},
        "e2e_p50_redriven_s": _percentile(redriven_e2e, 0.50),
        "e2e_p50_clean_s": _percentile(clean_e2e, 0.50),
    }
    for e in redrives:
        rs = str(e.get("reason", "?"))
        redrive_cost["reasons"][rs] = redrive_cost["reasons"].get(rs, 0) + 1

    # Recovery: replica_state transitions, out-of-service span per incident.
    lifecycle: Dict[int, List[Dict[str, Any]]] = {}
    for e in sorted(states, key=lambda e: float(e.get("t_mono", 0.0))):
        lifecycle.setdefault(int(e.get("replica", -1)), []).append({
            "t_mono": float(e.get("t_mono", 0.0)),
            "state": e.get("state"),
            "reason": e.get("reason"),
            "generation": e.get("generation"),
        })
    incidents: List[Dict[str, Any]] = []
    for rep, trail in lifecycle.items():
        down_at: Optional[Dict[str, Any]] = None
        for rec in trail:
            if rec["state"] in ("ejected", "draining") and down_at is None:
                down_at = rec
            elif rec["state"] == "active" and down_at is not None:
                incidents.append({
                    "replica": rep,
                    "kind": down_at["state"],
                    "reason": down_at["reason"],
                    "recovery_s": rec["t_mono"] - down_at["t_mono"],
                })
                down_at = None
        if down_at is not None:
            incidents.append({
                "replica": rep,
                "kind": down_at["state"],
                "reason": down_at["reason"],
                "recovery_s": None,  # still down at end of log
            })

    # Out-of-process workers: join each unclean worker death to the
    # redrives it caused and the replica_state recovery that followed —
    # the incident story ACROSS a real process boundary. Clean exits
    # (drain/shutdown/upgrade teardown) are routine and not incidents.
    w_spawns = [e for e in events if e.get("event") == "worker_spawn"]
    w_exits = [e for e in events if e.get("event") == "worker_exit"]
    w_conn_lost = [e for e in events if e.get("event") == "worker_conn_lost"]
    rpc_retry_ev = [e for e in events if e.get("event") == "rpc_retry"]
    process_deaths: List[Dict[str, Any]] = []
    for e in w_exits:
        if e.get("clean"):
            continue
        rep = int(e.get("replica", -1))
        t0 = float(e.get("t_mono", 0.0))
        recovery = next(
            (
                s for s in sorted(
                    states, key=lambda s: float(s.get("t_mono", 0.0))
                )
                if int(s.get("replica", -2)) == rep
                and s.get("state") == "active"
                and float(s.get("t_mono", 0.0)) > t0
            ),
            None,
        )
        t_end = (
            float(recovery.get("t_mono", 0.0))
            if recovery is not None else float("inf")
        )
        # conn-loss detection can precede the reaped exit by a beat; give
        # the join a small backwards grace window.
        caused = [
            r for r in redrives
            if r.get("from_replica") == rep
            and t0 - 1.0 <= float(r.get("t_mono", 0.0)) <= t_end
        ]
        process_deaths.append({
            "replica": rep,
            "pid": e.get("pid"),
            "returncode": e.get("returncode"),
            "redrives_caused": len(caused),
            "tokens_carried_over": sum(
                int(r.get("n_committed", 0)) for r in caused
            ),
            "recovered_in_s": (
                float(recovery.get("t_mono", 0.0)) - t0
                if recovery is not None else None
            ),
            "respawned": any(
                int(s.get("replica", -2)) == rep
                and float(s.get("t_mono", 0.0)) > t0
                for s in w_spawns
            ),
        })
    workers = None
    if w_spawns or w_exits or w_conn_lost or rpc_retry_ev:
        workers = {
            "spawns": len(w_spawns),
            "exits_clean": sum(1 for e in w_exits if e.get("clean")),
            "exits_unclean": sum(1 for e in w_exits if not e.get("clean")),
            "conn_lost": len(w_conn_lost),
            "rpc_retries": len(rpc_retry_ev),
            "process_deaths": process_deaths,
        }

    # Rolling upgrades: every refusal must be followed by a rollback that
    # restored the old weights — a refused upgrade that left the replica
    # on the new (probe-failing) checkpoint is the one unacceptable end
    # state, so it is strict.
    up_starts = [e for e in events if e.get("event") == "upgrade_start"]
    up_vetted = [e for e in events if e.get("event") == "upgrade_vetted"]
    up_refused = [e for e in events if e.get("event") == "upgrade_refused"]
    up_rolled = [e for e in events if e.get("event") == "upgrade_rolled_back"]
    for e in up_refused:
        rep = e.get("replica")
        t0 = float(e.get("t_mono", 0.0))
        rb = next(
            (
                r for r in up_rolled
                if r.get("replica") == rep
                and float(r.get("t_mono", 0.0)) >= t0
            ),
            None,
        )
        if rb is None:
            problems.append(
                f"upgrade_refused on replica {rep} has no matching "
                f"upgrade_rolled_back (replica left in limbo)"
            )
    upgrades = None
    if up_starts or up_refused or up_rolled:
        upgrades = {
            "started": len(up_starts),
            "vetted": len(up_vetted),
            "refused": len(up_refused),
            "rolled_back": len(up_rolled),
            "restored": sum(1 for e in up_rolled if e.get("restored")),
        }

    # Partitions: join each injected blackhole to the mechanism that
    # detected it — lease expiry (the router stopped hearing heartbeats)
    # or a fence drop (stale-generation frames arrived after heal) —
    # and to the redrives it caused. An injected partition that NOTHING
    # detected means a worker can stream stale tokens unnoticed, which
    # is the one unacceptable end state, so it is strict.
    p_inject = [e for e in events if e.get("event") == "partition_injected"]
    p_heal = [e for e in events if e.get("event") == "partition_healed"]
    leases = [e for e in events if e.get("event") == "lease_expired"]
    fenced = [e for e in events if e.get("event") == "fenced_frames_dropped"]
    f_bumps = [e for e in events if e.get("event") == "fence_bump"]
    j_replays = [e for e in events if e.get("event") == "journal_replay"]
    partitions = None
    if p_inject or p_heal or leases or fenced:
        part_incidents: List[Dict[str, Any]] = []
        for e in p_inject:
            rep = int(e.get("replica", -1))
            t0 = float(e.get("t_mono", 0.0))
            # Detection events carry their own bus timestamps; give the
            # join a small backwards grace window for clock skew between
            # the injector thread and the health/reader threads.
            lease_hit = next(
                (
                    le for le in sorted(
                        leases, key=lambda x: float(x.get("t_mono", 0.0))
                    )
                    if int(le.get("replica", -2)) == rep
                    and float(le.get("t_mono", 0.0)) >= t0 - 1.0
                ),
                None,
            )
            fence_hit = next(
                (
                    fe for fe in sorted(
                        fenced, key=lambda x: float(x.get("t_mono", 0.0))
                    )
                    if int(fe.get("replica", -2)) == rep
                    and float(fe.get("t_mono", 0.0)) >= t0 - 1.0
                ),
                None,
            )
            hits = [
                ("lease_expiry", lease_hit),
                ("fence_drop", fence_hit),
            ]
            hits = [
                (k, h) for k, h in hits if h is not None
            ]
            hits.sort(key=lambda kh: float(kh[1].get("t_mono", 0.0)))
            detected_by = hits[0][0] if hits else None
            detect_s = (
                max(0.0, float(hits[0][1].get("t_mono", 0.0)) - t0)
                if hits else None
            )
            heal = next(
                (
                    h for h in sorted(
                        p_heal, key=lambda x: float(x.get("t_mono", 0.0))
                    )
                    if int(h.get("replica", -2)) == rep
                    and float(h.get("t_mono", 0.0)) >= t0
                ),
                None,
            )
            t_end = (
                float(heal.get("t_mono", 0.0))
                if heal is not None else float("inf")
            )
            caused = [
                r for r in redrives
                if r.get("from_replica") == rep
                and t0 - 1.0 <= float(r.get("t_mono", 0.0)) <= t_end + 1.0
            ]
            if detected_by is None:
                problems.append(
                    f"UNDETECTED partition on replica {rep}: neither a "
                    f"lease expiry nor a fenced-frame drop followed the "
                    f"injection (stale tokens could stream unnoticed)"
                )
            part_incidents.append({
                "replica": rep,
                "detected_by": detected_by,
                "detect_s": detect_s,
                "healed": heal is not None,
                "redrives_caused": len(caused),
                "tokens_carried_over": sum(
                    int(r.get("n_committed", 0)) for r in caused
                ),
            })
        partitions = {
            "injected": len(p_inject),
            "healed": len(p_heal),
            "lease_expiries": len(leases),
            "fence_drop_notices": len(fenced),
            "fence_bumps": len(f_bumps),
            "incidents": part_incidents,
        }

    journal = None
    if j_replays:
        journal = {
            "replays": len(j_replays),
            "tokens_resumed_from": sum(
                int(e.get("n_committed", 0)) for e in j_replays
            ),
        }

    # Disaggregated prefill/decode: join each kv_migrate to the request
    # whose prefill it saved (saved_tokens = pages * block_size the
    # decode tier did NOT recompute), and every reject to its typed
    # refusal reason — a reasonless drop is unauditable, so it is
    # strict, as is a migration for a request the router never saw.
    migrates = [e for e in events if e.get("event") == "kv_migrate"]
    mig_rejects = [
        e for e in events if e.get("event") == "kv_migration_reject"
    ]
    kv_migration = None
    if migrates or mig_rejects:
        mig_rows: List[Dict[str, Any]] = []
        for e in migrates:
            frid = e.get("frid")
            if frid not in sub_frids:
                problems.append(
                    f"kv_migrate references unknown frid {frid}"
                )
            term = term_frids.get(frid)
            mig_rows.append({
                "frid": frid,
                "from_replica": e.get("from_replica"),
                "to_replica": e.get("to_replica"),
                "pages": int(e.get("pages", 0)),
                "bytes": int(e.get("bytes", 0)),
                "rejected": int(e.get("rejected", 0)),
                "saved_tokens": int(e.get("saved_tokens", 0)),
                "request_status": (
                    str(term.get("status")) if term is not None else None
                ),
            })
        reject_reasons: Dict[str, int] = {}
        for e in mig_rejects:
            reason = e.get("reason")
            if not reason:
                problems.append(
                    f"kv_migration_reject for frid {e.get('frid')} "
                    f"carries no reason (unauditable page drop)"
                )
            reason = str(reason or "?")
            reject_reasons[reason] = reject_reasons.get(reason, 0) + 1
        kv_migration = {
            "migrations": len(migrates),
            "pages_migrated": sum(r["pages"] for r in mig_rows),
            "bytes_migrated": sum(r["bytes"] for r in mig_rows),
            "saved_prefill_tokens": sum(
                r["saved_tokens"] for r in mig_rows
            ),
            "pages_rejected": sum(r["rejected"] for r in mig_rows),
            "reject_reasons": reject_reasons,
            "migrations_detail": mig_rows,
        }

    return {
        "n_submitted": len(submits),
        "n_terminal": len(terms),
        "lost_requests": len(lost),
        "statuses": status_counts,
        "per_replica": {str(k): v for k, v in sorted(per_replica.items())},
        "redrive_cost": redrive_cost,
        "incidents": incidents,
        "brownout_transitions": len(brownouts),
        "workers": workers,
        "upgrades": upgrades,
        "partitions": partitions,
        "journal": journal,
        "kv_migration": kv_migration,
        "problems": problems,
    }


def print_fleet_report(report: Dict[str, Any]) -> None:
    print("== fleet ==")
    print(
        f"submitted={report['n_submitted']} terminal={report['n_terminal']} "
        f"lost={report['lost_requests']} statuses={report['statuses']}"
    )
    if report["per_replica"]:
        print("== per-replica waterfall ==")
        hdr = ("replica", "submits", "done", "errors", "expired",
               "tokens", "rd_out", "rd_in")
        print("  " + " ".join(f"{h:>8}" for h in hdr))
        for rep, row in report["per_replica"].items():
            print("  " + " ".join(f"{v:>8}" for v in (
                rep, row["submits"], row["done"], row["errors"],
                row["expired"], row["tokens"], row["redrives_out"],
                row["redrives_in"],
            )))
    rc = report["redrive_cost"]
    if rc["redrive_events"]:
        print("== redrive cost ==")
        print(
            f"requests_redriven={rc['redriven_requests']} "
            f"events={rc['redrive_events']} "
            f"tokens_carried_over={rc['tokens_carried_over']}"
        )
        print(
            f"e2e_p50 redriven={rc['e2e_p50_redriven_s']:.4f}s "
            f"vs clean={rc['e2e_p50_clean_s']:.4f}s"
        )
        for reason, n in sorted(rc["reasons"].items()):
            print(f"  {reason:<40} {n}")
    if report["incidents"]:
        print("== replica incidents ==")
        for inc in report["incidents"]:
            rec = (
                f"{inc['recovery_s']:.3f}s"
                if inc["recovery_s"] is not None else "STILL DOWN"
            )
            print(
                f"  replica {inc['replica']}: {inc['kind']} "
                f"({inc['reason']}) -> recovered in {rec}"
            )
    if report["brownout_transitions"]:
        print(f"brownout transitions: {report['brownout_transitions']}")
    w = report.get("workers")
    if w:
        print("== workers ==")
        print(
            f"spawns={w['spawns']} exits_clean={w['exits_clean']} "
            f"exits_unclean={w['exits_unclean']} conn_lost={w['conn_lost']} "
            f"rpc_retries={w['rpc_retries']}"
        )
        for d in w["process_deaths"]:
            rec = (
                f"{d['recovered_in_s']:.3f}s"
                if d["recovered_in_s"] is not None else "STILL DOWN"
            )
            print(
                f"  worker death: replica {d['replica']} pid {d['pid']} "
                f"(rc={d['returncode']}) -> {d['redrives_caused']} redrives "
                f"({d['tokens_carried_over']} tokens carried), "
                f"respawned={d['respawned']}, recovered in {rec}"
            )
    u = report.get("upgrades")
    if u:
        print("== upgrades ==")
        print(
            f"started={u['started']} vetted={u['vetted']} "
            f"refused={u['refused']} rolled_back={u['rolled_back']} "
            f"restored={u['restored']}"
        )
    pt = report.get("partitions")
    if pt:
        print("== partitions ==")
        print(
            f"injected={pt['injected']} healed={pt['healed']} "
            f"lease_expiries={pt['lease_expiries']} "
            f"fence_drop_notices={pt['fence_drop_notices']} "
            f"fence_bumps={pt['fence_bumps']}"
        )
        for inc in pt["incidents"]:
            det = (
                f"{inc['detected_by']} in {inc['detect_s']:.3f}s"
                if inc["detected_by"] is not None else "UNDETECTED"
            )
            print(
                f"  partition: replica {inc['replica']} -> detected by "
                f"{det}, {inc['redrives_caused']} redrives "
                f"({inc['tokens_carried_over']} tokens carried), "
                f"healed={inc['healed']}"
            )
    j = report.get("journal")
    if j:
        print("== journal recovery ==")
        print(
            f"replays={j['replays']} "
            f"tokens_resumed_from={j['tokens_resumed_from']}"
        )
    kv = report.get("kv_migration")
    if kv:
        print("== kv migration ==")
        print(
            f"migrations={kv['migrations']} "
            f"pages={kv['pages_migrated']} "
            f"bytes={kv['bytes_migrated']} "
            f"saved_prefill_tokens={kv['saved_prefill_tokens']} "
            f"pages_rejected={kv['pages_rejected']}"
        )
        for m in kv["migrations_detail"]:
            print(
                f"  frid {m['frid']}: replica {m['from_replica']} -> "
                f"{m['to_replica']}, {m['pages']} pages "
                f"({m['bytes']} bytes), saved {m['saved_tokens']} "
                f"prefill tokens, request {m['request_status']}"
            )
        for reason, n in sorted(kv["reject_reasons"].items()):
            print(f"  rejected: {reason:<32} {n}")
    for p in report["problems"]:
        print(f"!! {p}")


# -- integrity attribution (--integrity) ------------------------------------


_CORRUPTION_KINDS = ("corrupt_kv_page", "corrupt_weights", "wrong_token")


def build_integrity_report(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the integrity sentinel's event streams into the audit view:

      detection    every corruption that actually FIRED (``fault_fired``)
                   must be answered by a detector on that replica —
                   a ``integrity_quarantine`` (probe/fingerprint verdict),
                   an ``integrity_invalid_token`` (reap guard), or an
                   ``integrity_kv_mismatch`` (verify-on-acquire) — and the
                   fire-to-detection latency is the headline number;
      exposure     tokens delivered (``req_done``) by the corrupted replica
                   between fire and detection: an UPPER bound on wrong
                   tokens served (requests not touching the corrupted
                   state are counted too — the bound is what the operator
                   can prove, not what the model emitted);
      join         each ``quarantine`` decision should carry the failing
                   probe's trace_id when tracing is on, so the verdict
                   joins to a span tree (strict);
      hygiene      a strict probe failure (completed, wrong tokens) must
                   be followed by a quarantine; a quarantine must be
                   preceded by a detector signal.
    """
    probes = [e for e in events if e.get("event") == "integrity_probe"]
    quars = [e for e in events if e.get("event") == "integrity_quarantine"]
    kv_mm = [e for e in events if e.get("event") == "integrity_kv_mismatch"]
    w_mm = [
        e for e in events if e.get("event") == "integrity_weight_mismatch"
    ]
    invalid = [
        e for e in events if e.get("event") == "integrity_invalid_token"
    ]
    fired = [
        e for e in events
        if e.get("event") == "fault_fired"
        and e.get("fault") in _CORRUPTION_KINDS
    ]
    armed = [
        e for e in events
        if e.get("event") == "fault_injected"
        and e.get("fault") in _CORRUPTION_KINDS
    ]
    quar_decisions = [
        e for e in events
        if e.get("event") == "decision" and e.get("decision") == "quarantine"
    ]
    drop_decisions = [
        e for e in events
        if e.get("event") == "decision"
        and e.get("decision") == "drop_corrupt_block"
    ]

    problems: List[str] = []

    def _t(e: Dict[str, Any]) -> float:
        return float(e.get("t_mono", 0.0))

    # Detection: first detector record on the fired replica at or after
    # the fire instant. Ejection for an IntegrityError surfaces as
    # integrity_invalid_token (reap guard), so all three streams count.
    detectors = sorted(quars + invalid + kv_mm + w_mm, key=_t)
    detections: List[Dict[str, Any]] = []
    for f in sorted(fired, key=_t):
        rep = f.get("replica")
        hit = next(
            (
                d for d in detectors
                if _t(d) >= _t(f)
                and (d.get("replica") is None or d.get("replica") == rep)
            ),
            None,
        )
        rec: Dict[str, Any] = {
            "fault": f.get("fault"),
            "replica": rep,
            "detected": hit is not None,
            "detector": hit.get("event") if hit is not None else None,
            "detection_latency_s": (
                _t(hit) - _t(f) if hit is not None else None
            ),
        }
        # Exposure bound: completed requests the corrupted replica kept
        # answering between fire and detection (end of log if undetected).
        t_end = _t(hit) if hit is not None else float("inf")
        rec["wrong_tokens_served_bound"] = sum(
            int(e.get("n_tokens", 0)) for e in events
            if e.get("event") == "req_done"
            and e.get("replica") == rep
            and _t(f) <= _t(e) <= t_end
        )
        detections.append(rec)
        if hit is None:
            problems.append(
                f"UNDETECTED corruption: {f.get('fault')} fired on replica "
                f"{rep} and no detector answered (quarantine/invalid_token/"
                f"kv_mismatch/weight_mismatch)"
            )

    # Hygiene: a COMPLETED probe with wrong tokens is the sentinel's own
    # verdict — a quarantine must follow (probes that error/expire/time
    # out are the health loop's business and don't count here).
    strict_failures = [
        e for e in probes
        if not e.get("ok") and str(e.get("status")) == "done"
    ]
    for e in strict_failures:
        rep = e.get("replica")
        if not any(
            q.get("replica") == rep and _t(q) >= _t(e) for q in quars
        ):
            problems.append(
                f"probe divergence on replica {rep} (t_mono={_t(e):.3f}) "
                f"was never answered by a quarantine"
            )
    for q in quars:
        rep = q.get("replica")
        preceded = any(
            e.get("replica") == rep and _t(e) <= _t(q)
            for e in strict_failures + w_mm + invalid
        )
        if not preceded:
            problems.append(
                f"quarantine of replica {rep} (t_mono={_t(q):.3f}) has no "
                f"preceding detector signal"
            )
    # Join: when any probe carried a trace, the quarantine decision must
    # too — that's what lets the verdict join the span tree.
    traced_probes = any(e.get("trace_id") for e in probes)
    for d in quar_decisions:
        if traced_probes and not d.get("trace_id"):
            problems.append(
                "quarantine decision lacks a trace_id while probes are "
                "traced (decision-to-trace join broken)"
            )

    per_replica: Dict[str, Dict[str, int]] = {}

    def _slot(r: Any) -> Dict[str, int]:
        return per_replica.setdefault(
            str(r), {"probes": 0, "probe_failures": 0, "quarantines": 0},
        )

    for e in probes:
        slot = _slot(e.get("replica"))
        slot["probes"] += 1
        if not e.get("ok"):
            slot["probe_failures"] += 1
    for e in quars:
        _slot(e.get("replica"))["quarantines"] += 1

    latencies = sorted(
        d["detection_latency_s"] for d in detections
        if d["detection_latency_s"] is not None
    )
    return {
        "probes_run": len(probes),
        "probes_failed": sum(1 for e in probes if not e.get("ok")),
        "quarantines": len(quars),
        "kv_mismatches": len(kv_mm),
        "weight_mismatches": len(w_mm),
        "invalid_tokens": len(invalid),
        "corruptions_armed": len(armed),
        "corruptions_fired": len(fired),
        "corrupt_blocks_dropped": len(drop_decisions),
        "detections": detections,
        "detection_latency_p50_s": _percentile(latencies, 0.50),
        "detection_latency_max_s": latencies[-1] if latencies else None,
        "per_replica": dict(sorted(per_replica.items())),
        "problems": problems,
    }


def print_integrity_report(report: Dict[str, Any]) -> None:
    print("== integrity ==")
    print(
        f"probes={report['probes_run']} "
        f"failed={report['probes_failed']} "
        f"quarantines={report['quarantines']} "
        f"kv_mismatches={report['kv_mismatches']} "
        f"invalid_tokens={report['invalid_tokens']}"
    )
    if report["corruptions_armed"] or report["corruptions_fired"]:
        print(
            f"corruptions: armed={report['corruptions_armed']} "
            f"fired={report['corruptions_fired']} "
            f"blocks_dropped={report['corrupt_blocks_dropped']}"
        )
    for d in report["detections"]:
        if d["detected"]:
            print(
                f"  {d['fault']} on replica {d['replica']}: detected by "
                f"{d['detector']} in {d['detection_latency_s']:.3f}s, "
                f"wrong-tokens-served bound {d['wrong_tokens_served_bound']}"
            )
        else:
            print(
                f"  {d['fault']} on replica {d['replica']}: NOT DETECTED"
            )
    if report["per_replica"]:
        print("== per-replica probes ==")
        hdr = ("replica", "probes", "failed", "quarant")
        print("  " + " ".join(f"{h:>8}" for h in hdr))
        for rep, row in report["per_replica"].items():
            print("  " + " ".join(f"{v:>8}" for v in (
                rep, row["probes"], row["probe_failures"],
                row["quarantines"],
            )))
    for p in report["problems"]:
        print(f"!! {p}")


def build_report(records: List[Dict[str, Any]], bins: int) -> Dict[str, Any]:
    events, metrics = split_records(records)
    counts: Dict[str, int] = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    report: Dict[str, Any] = {
        "n_records": len(records),
        "n_events": len(events),
        "n_metric_records": len(metrics),
        "event_counts": dict(sorted(counts.items())),
        "step_time": step_time_stats(metrics, bins),
        "timeline": timeline(events),
    }
    if events:
        report["goodput"] = GoodputAccountant.fold(events)
        pc = prefix_cache_summary(events)
        if pc is not None:
            report["prefix_cache"] = pc
    return report


def print_report(report: Dict[str, Any]) -> None:
    good = report.get("goodput")
    if good:
        total = good["total_s"]
        print("== goodput ==")
        print(f"total wall-clock  {total:.3f}s over {good['runs']} run(s)")
        print(f"goodput           {good['goodput']:.3f}")
        for cat in CATEGORIES:
            sec = good["categories"][cat]
            pct = 100.0 * sec / total if total > 0 else 0.0
            bar = "#" * int(round(pct / 2))
            print(f"  {cat:<11} {sec:9.3f}s {pct:5.1f}% {bar}")
        print(f"rollbacks={good['rollbacks']} recompiles={good['recompiles']} "
              f"max_step={good['max_step']} exit={good['exit_reason']}")
    st = report["step_time"]
    print("== step time ==")
    if st["count"] == 0:
        print("no step_ms records")
    else:
        print(f"windows={st['count']} mean={st['mean_ms']:.2f}ms "
              f"p50={st['p50_ms']:.2f}ms p90={st['p90_ms']:.2f}ms "
              f"max={st['max_ms']:.2f}ms")
        peak = max(b["count"] for b in st["histogram"]) or 1
        for b in st["histogram"]:
            bar = "#" * int(round(30 * b["count"] / peak))
            print(f"  [{b['lo_ms']:9.2f}, {b['hi_ms']:9.2f}) {b['count']:5d} {bar}")
    print("== events ==")
    if not report["event_counts"]:
        print("no events")
    for kind, n in report["event_counts"].items():
        print(f"  {kind:<15} {n}")
    pc = report.get("prefix_cache")
    if pc:
        print("== prefix cache ==")
        print(
            f"requests={pc['requests']} hit_requests={pc['hit_requests']} "
            f"hit_rate={pc['hit_rate']:.3f} "
            f"prefill_tokens_saved={pc['prefill_tokens_saved']}"
        )
    if report["timeline"]:
        print("== timeline ==")
        for entry in report["timeline"]:
            extra = " ".join(
                f"{k}={v}" for k, v in entry.items()
                if k not in ("t_rel_s", "event")
            )
            print(f"  +{entry['t_rel_s']:9.3f}s {entry['event']:<13} {extra}")


# ---------------------------------------------------------------------------
# live view: poll a running gateway's GET /slo, reconcile with offline events
# ---------------------------------------------------------------------------

LIVE_METRICS = ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s")
_LIVE_TERMINALS = ("req_done", "req_expired", "req_error", "req_cancelled")
_LIVE_QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))


def fetch_live(url: str, timeout_s: float = 5.0) -> Dict[str, Any]:
    """GET <url>/slo from a running scripts/serve.py gateway (stdlib only)."""
    import urllib.request

    target = url.rstrip("/")
    if not target.endswith("/slo"):
        target += "/slo"
    with urllib.request.urlopen(target, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def build_live_report(
    snap: Dict[str, Any],
    events: List[Dict[str, Any]],
    rank_eps: float = 0.05,
) -> Dict[str, Any]:
    """Fold a GET /slo snapshot; reconcile sketches vs offline events.

    The live figures come from fixed-size mergeable sketches over a
    ROLLING window; the offline figures are exact percentiles over the
    full events JSONL. When the run fits inside the live window (the CI
    smoke case) the two must agree within the sketch's rank-error bound:
    each live quantile must land between the exact values at ranks
    q-rank_eps and q+rank_eps. A live window that saw far fewer events
    than the file (a long run, window already rotated) is reported as
    ``window_truncated`` and skipped rather than failed — the contract
    is accuracy, not that a 60s window summarizes an hour.
    """
    fleet = snap.get("latency", {}).get("fleet", {})
    problems: List[str] = []
    reconcile: Dict[str, Any] = {}
    offline: Dict[str, List[float]] = {m: [] for m in LIVE_METRICS}
    for ev in events:
        if ev.get("event") not in _LIVE_TERMINALS:
            continue
        for m in LIVE_METRICS:
            v = ev.get(m)
            if isinstance(v, (int, float)) and math.isfinite(v):
                offline[m].append(float(v))
    for m in LIVE_METRICS:
        live = fleet.get(m, {})
        live_n = int(live.get("count", 0))
        vals = sorted(offline[m])
        row: Dict[str, Any] = {
            "live_count": live_n,
            "offline_count": len(vals),
            "checked": False,
        }
        if len(vals) >= 20 and live_n > 0:
            if live_n < len(vals) // 2:
                row["window_truncated"] = True
            else:
                row["checked"] = True
                for key, q in _LIVE_QUANTILES:
                    got = live.get(key)
                    if not isinstance(got, (int, float)):
                        continue
                    lo = _percentile(vals, max(0.0, q - rank_eps))
                    hi = _percentile(vals, min(1.0, q + rank_eps))
                    slack = 1e-9 + 0.01 * max(abs(lo), abs(hi))
                    ok = (lo - slack) <= got <= (hi + slack)
                    row[key] = {
                        "live": got, "exact_lo": lo, "exact_hi": hi,
                        "ok": ok,
                    }
                    if not ok:
                        problems.append(
                            f"live {m} {key}={got:.6g} outside exact "
                            f"rank band [{lo:.6g}, {hi:.6g}] "
                            f"(rank_eps={rank_eps})"
                        )
        reconcile[m] = row
    alerts = snap.get("alerts", {})
    return {
        "events_seen": snap.get("events_seen", 0),
        "window_s": snap.get("window_s"),
        "fleet": fleet,
        "classes": snap.get("classes", {}),
        "alerts_active": alerts.get("active", []),
        "alerts_fired_total": alerts.get("fired_total", 0),
        "fleet_health": snap.get("fleet_health", {}).get("fleet", {}),
        "reconcile": reconcile,
        "problems": problems,
    }


def print_live_report(rep: Dict[str, Any]) -> None:
    print("== live SLO ==")
    print(
        f"events_seen={rep['events_seen']} "
        f"window_s={rep['window_s']} "
        f"alerts_active={len(rep['alerts_active'])} "
        f"alerts_fired_total={rep['alerts_fired_total']}"
    )
    for m in LIVE_METRICS:
        s = rep["fleet"].get(m, {})
        if not s.get("count"):
            print(f"  {m:<13} (no samples in window)")
            continue
        print(
            f"  {m:<13} n={s['count']:<6} p50={s.get('p50', 0.0):.4f}s "
            f"p90={s.get('p90', 0.0):.4f}s p99={s.get('p99', 0.0):.4f}s"
        )
    for name, cls in rep["classes"].items():
        burn = ", ".join(
            f"{r}={b['short']:.2f}/{b['long']:.2f}"
            f"{' FIRING' if b.get('firing') else ''}"
            for r, b in cls.get("burn", {}).items()
        )
        print(
            f"  class {name}: target={cls['target']} "
            f"events={cls['events']} bad={cls['bad']} "
            f"budget_spent={cls['budget_spent_frac']:.1%} [{burn}]"
        )
    for alert in rep["alerts_active"]:
        print(
            f"  ALERT {alert.get('alert_id')} {alert.get('slo_class')}/"
            f"{alert.get('rule')} severity={alert.get('severity')} "
            f"burn={alert.get('burn_short'):.1f}/{alert.get('burn_long'):.1f}"
        )
    fh = rep.get("fleet_health") or {}
    if fh:
        print(
            f"  fleet: replicas={fh.get('replicas_active')}/"
            f"{fh.get('replicas_total')} max_fence={fh.get('max_fence')} "
            f"gauges={fh.get('gauges', {})}"
        )
    checked = [m for m, r in rep["reconcile"].items() if r["checked"]]
    if checked:
        print("== live vs offline ==")
        for m in checked:
            row = rep["reconcile"][m]
            for key, _ in _LIVE_QUANTILES:
                c = row.get(key)
                if c is None:
                    continue
                mark = "ok" if c["ok"] else "MISMATCH"
                print(
                    f"  {m} {key}: live={c['live']:.4f} in "
                    f"[{c['exact_lo']:.4f}, {c['exact_hi']:.4f}] {mark}"
                )
    for p in rep["problems"]:
        print(f"  !! {p}")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    parser.add_argument("paths", nargs="*", help="metrics/events JSONL files")
    parser.add_argument(
        "--strict", action="store_true",
        help="exit nonzero if any line fails to parse (CI schema gate) or, "
        "with --slo, if any request's span tree is incomplete",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--bins", type=int, default=10, help="step-time histogram bins")
    parser.add_argument(
        "--trace", default="",
        help="Chrome-trace JSON export (scripts/serve.py --trace) to "
        "reconstruct per-request span trees from",
    )
    parser.add_argument(
        "--slo", action="store_true",
        help="per-request SLO attribution from --trace: waterfalls, "
        "segment decomposition, miss table",
    )
    parser.add_argument(
        "--slo_ttft_s", type=float, default=0.0,
        help="TTFT SLO bound in seconds (0 = no bound)",
    )
    parser.add_argument(
        "--slo_e2e_s", type=float, default=0.0,
        help="end-to-end SLO bound in seconds (0 = no bound)",
    )
    parser.add_argument(
        "--fleet-trace", dest="fleet_trace", action="store_true",
        help="cross-host lineage view from --trace (+ optional events "
        "JSONL): per-request waterfall across placement attempts (sums "
        "to e2e), worker subtrees clock-aligned into the router "
        "timeline, redrive/partition-detection gaps; --strict makes an "
        "unalignable span, an orphaned attempt/subtree, an "
        "out-of-bound worker span, or a >1%% sum error fatal",
    )
    parser.add_argument(
        "--capacity", action="store_true",
        help="capacity attribution from cap_window/decision events: "
        "slot-second waterfall (sums to wall time), binding constraint, "
        "decision-to-trace join; --strict makes a >1% sum error, an "
        "unjoinable decision, or a run with no occupancy samples fatal",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="fleet attribution from fleet_req_*/redrive/replica_state "
        "events: request conservation (every submit reaches a terminal), "
        "per-replica waterfalls, redrive cost, replica recovery time, "
        "partition detection joins (lease expiry vs fence drop), journal "
        "replays; --strict makes a lost request, a dangling redrive, or "
        "an undetected partition fatal",
    )
    parser.add_argument(
        "--integrity", action="store_true",
        help="integrity attribution from integrity_*/fault_fired events: "
        "corruption-to-detection latency, probe/quarantine waterfall, "
        "wrong-tokens-served exposure bound, decision-to-trace join; "
        "--strict makes an undetected corruption, an unanswered probe "
        "divergence, or a broken trace join fatal",
    )
    parser.add_argument(
        "--live", default="",
        help="poll a RUNNING gateway's live SLO engine (base URL, e.g. "
        "http://localhost:8000): rolling-window percentile sketches, "
        "error budgets, burn rates, active alerts, fleet health. With "
        "events JSONL paths, reconciles the live sketch percentiles "
        "against exact offline percentiles within the sketch's rank "
        "error bound; --strict makes a mismatch fatal",
    )
    parser.add_argument(
        "--live_timeout_s", type=float, default=5.0,
        help="HTTP timeout for --live",
    )
    parser.add_argument(
        "--live_rank_eps", type=float, default=0.05,
        help="rank tolerance for the --live vs offline reconciliation",
    )
    args = parser.parse_args()
    if args.slo and not args.trace:
        parser.error("--slo needs --trace")
    if args.fleet_trace and not args.trace:
        parser.error("--fleet-trace needs --trace")
    if args.capacity and not args.paths:
        parser.error("--capacity needs events JSONL paths")
    if args.fleet and not args.paths:
        parser.error("--fleet needs events JSONL paths")
    if args.integrity and not args.paths:
        parser.error("--integrity needs events JSONL paths")
    if not args.paths and not args.trace and not args.live:
        parser.error(
            "nothing to analyze: pass JSONL paths, --trace, and/or --live"
        )

    records: List[Dict[str, Any]] = []
    bad = 0
    for path in args.paths:
        recs, nbad = read_jsonl(path)
        records.extend(recs)
        bad += nbad
    report = build_report(records, args.bins)
    report["bad_lines"] = bad
    slo_report: Optional[Dict[str, Any]] = None
    if args.trace:
        trace = load_trace(args.trace)
        slo_report = build_slo_report(
            trace, slo_ttft_s=args.slo_ttft_s, slo_e2e_s=args.slo_e2e_s
        )
        report["serving"] = slo_report
    fleet_trace_report: Optional[Dict[str, Any]] = None
    if args.fleet_trace:
        events, _ = split_records(records)
        fleet_trace_report = build_fleet_trace_report(trace, events)
        report["fleet_trace"] = fleet_trace_report
    cap_report: Optional[Dict[str, Any]] = None
    if args.capacity:
        events, _ = split_records(records)
        cap_report = build_capacity_report(events)
        report["capacity"] = cap_report
    fleet_report: Optional[Dict[str, Any]] = None
    if args.fleet:
        events, _ = split_records(records)
        fleet_report = build_fleet_report(events)
        report["fleet"] = fleet_report
    integrity_report: Optional[Dict[str, Any]] = None
    if args.integrity:
        events, _ = split_records(records)
        integrity_report = build_integrity_report(events)
        report["integrity"] = integrity_report
    live_report: Optional[Dict[str, Any]] = None
    if args.live:
        snap = fetch_live(args.live, timeout_s=args.live_timeout_s)
        events, _ = split_records(records)
        live_report = build_live_report(
            snap, events, rank_eps=args.live_rank_eps
        )
        report["live"] = live_report
    if args.json:
        print(json.dumps(report, indent=2, allow_nan=False))
    else:
        if args.paths:
            print_report(report)
        if slo_report is not None and (args.slo or slo_report["problems"]):
            print_slo_report(slo_report)
        if fleet_trace_report is not None:
            print_fleet_trace_report(fleet_trace_report)
        if cap_report is not None:
            print_capacity_report(cap_report)
        if fleet_report is not None:
            print_fleet_report(fleet_report)
        if integrity_report is not None:
            print_integrity_report(integrity_report)
        if live_report is not None:
            print_live_report(live_report)
        if bad:
            print(f"!! {bad} unparseable line(s)", file=sys.stderr)
        if slo_report is not None and slo_report["dropped_spans"]:
            print(
                f"!! {slo_report['dropped_spans']} dropped span(s): the "
                f"recorder saturated; raise max_events or sample fewer "
                f"requests",
                file=sys.stderr,
            )
    if args.strict and bad:
        return 1
    if args.strict and slo_report is not None and slo_report["problems"]:
        for p in slo_report["problems"]:
            print(f"STRICT: {p}", file=sys.stderr)
        return 1
    if (args.strict and fleet_trace_report is not None
            and fleet_trace_report["problems"]):
        for p in fleet_trace_report["problems"]:
            print(f"STRICT: {p}", file=sys.stderr)
        return 1
    if args.strict and cap_report is not None and cap_report["problems"]:
        for p in cap_report["problems"]:
            print(f"STRICT: {p}", file=sys.stderr)
        return 1
    if args.strict and fleet_report is not None and fleet_report["problems"]:
        for p in fleet_report["problems"]:
            print(f"STRICT: {p}", file=sys.stderr)
        return 1
    if args.strict and integrity_report is not None and integrity_report["problems"]:
        for p in integrity_report["problems"]:
            print(f"STRICT: {p}", file=sys.stderr)
        return 1
    if args.strict and live_report is not None and live_report["problems"]:
        for p in live_report["problems"]:
            print(f"STRICT: {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
