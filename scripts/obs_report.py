#!/usr/bin/env python
"""Offline run analyzer: metrics/events JSONL in, run report out.

The online half (pretraining_llm_tpu/observability/) streams events and
metrics to JSONL during the run; this script is the post-hoc fold over those
files — usable on a laptop against files scp'd off a pod, and run in CI over
the smoke run so the JSONL schema stays a checked contract.

    python scripts/obs_report.py run/obs/events.jsonl run/metrics.jsonl
    python scripts/obs_report.py --json --strict ...   # CI: machine output,
                                                       # nonzero on bad lines

Pass any mix of files: records carrying ``event`` + ``t_wall`` are treated as
run events (folded into the goodput decomposition and the event timeline);
records carrying ``step_ms`` feed the step-time histogram. ``--strict`` makes
unparseable lines fatal — a corrupt metrics stream (e.g. bare NaN tokens)
must fail CI, not be skipped.

Deliberately jax-free: imports only the stdlib + the observability package
(itself stdlib-only at import), so it runs where the training stack doesn't.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.observability.goodput import CATEGORIES, GoodputAccountant

# Events worth a line each in the timeline; step_window/device_memory are
# high-rate telemetry and only counted.
_NOTABLE = (
    "run_start", "run_end", "eval", "ckpt_save", "ckpt_restore", "rollback",
    "recompile", "wedge", "preempt", "relaunch", "failure", "fault_injected",
)


def _reject_constant(const: str) -> float:
    raise ValueError(f"non-finite JSON constant {const!r} (invalid strict JSON)")


def read_jsonl(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse one JSONL file; returns (records, bad_line_count)."""
    records: List[Dict[str, Any]] = []
    bad = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                # parse_constant: Python's json ACCEPTS bare NaN/Infinity by
                # default, but they are invalid JSON — exactly the corruption
                # --strict exists to catch (a logger writing a NaN loss raw).
                rec = json.loads(line, parse_constant=_reject_constant)
            except ValueError:
                bad += 1
                print(f"{path}:{lineno}: unparseable JSON line", file=sys.stderr)
                continue
            if not isinstance(rec, dict):
                bad += 1
                print(f"{path}:{lineno}: not a JSON object", file=sys.stderr)
                continue
            records.append(rec)
    return records, bad


def split_records(
    records: List[Dict[str, Any]],
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """(events, metrics): stamped run events vs per-step metric records."""
    events = [r for r in records if "event" in r and "t_wall" in r]
    metrics = [r for r in records if "step_ms" in r]
    return events, metrics


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def step_time_stats(metrics: List[Dict[str, Any]], bins: int = 10) -> Dict[str, Any]:
    vals = sorted(
        float(r["step_ms"]) for r in metrics
        if isinstance(r.get("step_ms"), (int, float))
    )
    if not vals:
        return {"count": 0}
    lo, hi = vals[0], vals[-1]
    width = (hi - lo) / bins if hi > lo else 1.0
    counts = [0] * bins
    for v in vals:
        counts[min(bins - 1, int((v - lo) / width))] += 1
    return {
        "count": len(vals),
        "mean_ms": sum(vals) / len(vals),
        "p50_ms": _percentile(vals, 0.50),
        "p90_ms": _percentile(vals, 0.90),
        "max_ms": hi,
        "histogram": [
            {"lo_ms": lo + i * width, "hi_ms": lo + (i + 1) * width, "count": c}
            for i, c in enumerate(counts)
        ],
    }


def timeline(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chronological notable events, timestamped relative to the first."""
    stamped = sorted(events, key=lambda e: e["t_wall"])
    if not stamped:
        return []
    t0 = stamped[0]["t_wall"]
    out = []
    for e in stamped:
        if e["event"] not in _NOTABLE:
            continue
        entry = {"t_rel_s": round(e["t_wall"] - t0, 3), "event": e["event"]}
        for key in (
            "step", "dur_s", "to_step", "why", "rc", "exit_reason",
            "anomaly", "fault",
        ):
            if key in e:
                entry[key] = e[key]
        out.append(entry)
    return out


def build_report(records: List[Dict[str, Any]], bins: int) -> Dict[str, Any]:
    events, metrics = split_records(records)
    counts: Dict[str, int] = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    report: Dict[str, Any] = {
        "n_records": len(records),
        "n_events": len(events),
        "n_metric_records": len(metrics),
        "event_counts": dict(sorted(counts.items())),
        "step_time": step_time_stats(metrics, bins),
        "timeline": timeline(events),
    }
    if events:
        report["goodput"] = GoodputAccountant.fold(events)
    return report


def print_report(report: Dict[str, Any]) -> None:
    good = report.get("goodput")
    if good:
        total = good["total_s"]
        print("== goodput ==")
        print(f"total wall-clock  {total:.3f}s over {good['runs']} run(s)")
        print(f"goodput           {good['goodput']:.3f}")
        for cat in CATEGORIES:
            sec = good["categories"][cat]
            pct = 100.0 * sec / total if total > 0 else 0.0
            bar = "#" * int(round(pct / 2))
            print(f"  {cat:<11} {sec:9.3f}s {pct:5.1f}% {bar}")
        print(f"rollbacks={good['rollbacks']} recompiles={good['recompiles']} "
              f"max_step={good['max_step']} exit={good['exit_reason']}")
    st = report["step_time"]
    print("== step time ==")
    if st["count"] == 0:
        print("no step_ms records")
    else:
        print(f"windows={st['count']} mean={st['mean_ms']:.2f}ms "
              f"p50={st['p50_ms']:.2f}ms p90={st['p90_ms']:.2f}ms "
              f"max={st['max_ms']:.2f}ms")
        peak = max(b["count"] for b in st["histogram"]) or 1
        for b in st["histogram"]:
            bar = "#" * int(round(30 * b["count"] / peak))
            print(f"  [{b['lo_ms']:9.2f}, {b['hi_ms']:9.2f}) {b['count']:5d} {bar}")
    print("== events ==")
    if not report["event_counts"]:
        print("no events")
    for kind, n in report["event_counts"].items():
        print(f"  {kind:<15} {n}")
    if report["timeline"]:
        print("== timeline ==")
        for entry in report["timeline"]:
            extra = " ".join(
                f"{k}={v}" for k, v in entry.items()
                if k not in ("t_rel_s", "event")
            )
            print(f"  +{entry['t_rel_s']:9.3f}s {entry['event']:<13} {extra}")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    parser.add_argument("paths", nargs="+", help="metrics/events JSONL files")
    parser.add_argument(
        "--strict", action="store_true",
        help="exit nonzero if any line fails to parse (CI schema gate)",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--bins", type=int, default=10, help="step-time histogram bins")
    args = parser.parse_args()

    records: List[Dict[str, Any]] = []
    bad = 0
    for path in args.paths:
        recs, nbad = read_jsonl(path)
        records.extend(recs)
        bad += nbad
    report = build_report(records, args.bins)
    report["bad_lines"] = bad
    if args.json:
        print(json.dumps(report, indent=2, allow_nan=False))
    else:
        print_report(report)
        if bad:
            print(f"!! {bad} unparseable line(s)", file=sys.stderr)
    if args.strict and bad:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
