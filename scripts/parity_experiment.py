#!/usr/bin/env python
"""Eval-loss parity: this framework vs an independent PyTorch twin.

BASELINE.md's bar is "eval loss matching the GPU baseline +-0.01". This
environment has no GPU and no network, so the baseline is produced the way
the reference would have produced it: a from-scratch PyTorch training run
(torch CPU, fp32) of the SAME architecture, from the SAME initial weights,
on the SAME real-text byte stream in the SAME batch order, with the same
AdamW/clip/schedule math. The only remaining differences are framework
numerics (XLA:TPU vs torch CPU kernels, reduction orders) — exactly what the
parity bar is meant to measure.

Corpus: real English prose harvested from the machine itself (package READMEs,
documentation, license texts — ~3.5 MB), byte-level tokenized (vocab 256).
No synthetic data anywhere.

Usage:
  python scripts/parity_experiment.py            # full pipeline
  python scripts/parity_experiment.py --steps 1500 --eval-iters 50

Writes data/parity/{corpus.txt,train.bin,val.bin,init.npz,results.json} and
prints a BASELINE.md-ready table row.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()  # PLLM_PLATFORM=cpu runs the jax side off-TPU

PARITY_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data", "parity")

# Small GPT-2-shape model (standard mode: fused QKV, output projection, tied
# embeddings, GELU, learned positions), fp32 both sides so numerics are
# comparable at the +-0.01 bar.
MODEL_KW = dict(
    vocab_size=256,
    context_length=256,
    d_model=256,
    n_heads=8,
    n_layers=4,
    activation="gelu",
    pos_embed="learned",
    tie_embeddings=True,
    qkv_bias=False,
    mlp_bias=True,
    param_dtype="float32",
    compute_dtype="float32",
)
BATCH = 16
LR = 3e-4
WARMUP_FRAC = 0.05
GRAD_CLIP = 1.0
WEIGHT_DECAY = 0.1
B1, B2, EPS = 0.9, 0.95, 1e-8
DATA_SEED = 1234
EVAL_SEED = 4321


# ---------------------------------------------------------------------------
# Corpus: real English prose available on an air-gapped machine
# ---------------------------------------------------------------------------


def build_corpus(path: str, max_bytes: int = 6_000_000) -> int:
    roots = [
        "/opt/venv/lib/python3.12/site-packages",
        "/usr/share/common-licenses",
        "/THIRD_PARTY_NOTICES",
    ]
    files = []
    for root in roots:
        for dirpath, _, names in os.walk(root):
            for name in names:
                if name.endswith((".rst", ".md")) or name in (
                    "LICENSE", "LICENSE.txt", "LICENSES.txt", "README.txt",
                    "GPL-2", "GPL-3", "LGPL-2", "LGPL-2.1", "LGPL-3", "Apache-2.0",
                    "BSD", "MPL-1.1", "MPL-2.0", "Artistic",
                ):
                    p = os.path.join(dirpath, name)
                    try:
                        if os.path.getsize(p) > 2000 and not os.path.islink(p):
                            files.append(p)
                    except OSError:
                        continue
    files.sort()  # deterministic order
    total = 0
    with open(path, "wb") as out:
        for p in files:
            if total >= max_bytes:
                break
            try:
                data = open(p, "rb").read()
            except OSError:
                continue
            # keep printable-ish text only; skip binary-looking files
            if b"\x00" in data:
                continue
            out.write(data)
            out.write(b"\n\n")
            total += len(data) + 2
    return total


def tokenize_corpus(corpus_path: str, train_path: str, val_path: str) -> None:
    raw = np.frombuffer(open(corpus_path, "rb").read(), dtype=np.uint8).astype(np.uint16)
    n_val = len(raw) // 20  # 5% validation split
    raw[: len(raw) - n_val].tofile(train_path)
    raw[len(raw) - n_val :].tofile(val_path)


# ---------------------------------------------------------------------------
# JAX side (the framework under test)
# ---------------------------------------------------------------------------


def run_jax(args, model_cfg, train_path, val_path, init_npz):
    import jax
    import jax.numpy as jnp

    from pretraining_llm_tpu.config import Config, TrainConfig
    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.models import transformer
    from pretraining_llm_tpu.training import train_step as ts

    cfg = Config(
        model=model_cfg,
        train=TrainConfig(
            batch_size=BATCH, lr=LR, train_steps=args.steps,
            lr_schedule="warmup_constant", warmup_frac=WARMUP_FRAC,
            grad_clip=GRAD_CLIP, weight_decay=WEIGHT_DECAY,
            adam_b1=B1, adam_b2=B2, adam_eps=EPS,
            checkpoint_interval=0, eval_interval=0,
        ),
        name="parity",
    )
    # True-f32 matmuls: on TPU, jax's default "fastest" precision runs f32
    # einsums as bf16 MXU passes — a real numeric difference vs the torch
    # CPU baseline that compounds over steps. The parity bar measures
    # framework math, not matmul rounding mode.
    jax.config.update("jax_default_matmul_precision", "highest")
    state = ts.init_train_state(cfg, jax.random.key(0))
    if os.path.exists(init_npz):
        # The committed init.npz is an ARTIFACT: results.json pins its sha
        # (init_sha), so a rerun must LOAD it — not regenerate and overwrite
        # it, which silently rebased the recorded identity every time the
        # experiment ran (and made the banked curves unreproducible when the
        # init routine drifted). Delete the file to start a fresh experiment.
        raw = dict(np.load(init_npz))
        saved_kw = (
            json.loads(bytes(raw.pop("__model_kw__")).decode())
            if "__model_kw__" in raw else None
        )
        if saved_kw != json.loads(json.dumps(MODEL_KW, sort_keys=True)):
            raise ValueError(
                f"{init_npz} was written for a different MODEL_KW — delete "
                "it to regenerate (the recorded curves will no longer be "
                "comparable)."
            )
        flat, treedef = jax.tree_util.tree_flatten_with_path(state["params"])
        leaves = []
        for path, leaf in flat:
            key = "__".join(str(getattr(e, "key", e)) for e in path)
            if key not in raw:
                raise ValueError(
                    f"{init_npz} is missing param {key!r} — delete it to "
                    "regenerate."
                )
            if raw[key].shape != leaf.shape:
                raise ValueError(
                    f"{init_npz} param {key!r} has shape {raw[key].shape}, "
                    f"model wants {leaf.shape} — delete it to regenerate."
                )
            leaves.append(jnp.asarray(raw[key], leaf.dtype))
        state["params"] = jax.tree_util.tree_unflatten(treedef, leaves)
        print(f"[jax] loaded shared init from {init_npz}", flush=True)
    else:
        # First run: persist the exact initial weights for the torch twin.
        flat = jax.tree_util.tree_flatten_with_path(state["params"])[0]
        np.savez(
            init_npz,
            __model_kw__=np.frombuffer(json.dumps(MODEL_KW, sort_keys=True).encode(), np.uint8),
            **{
                "__".join(str(getattr(e, "key", e)) for e in path): np.asarray(leaf, np.float32)
                for path, leaf in flat
            },
        )
    step = ts.build_train_step(cfg, mesh=None)
    it = loader.get_batch_iterator(
        train_path, BATCH, model_cfg.context_length, seed=DATA_SEED
    )

    eval_step = jax.jit(
        lambda p, x, y: transformer.loss_fn(p, x, y, model_cfg, include_aux=False)
    )

    def eval_loss(params):
        ev = loader.get_batch_iterator(
            val_path, BATCH, model_cfg.context_length, seed=EVAL_SEED
        )
        total = 0.0
        for _ in range(args.eval_iters):
            x, y = next(ev)
            total += float(eval_step(params, jnp.asarray(x), jnp.asarray(y)))
        return total / args.eval_iters

    curve = []
    for s in range(args.steps):
        x, y = next(it)
        state, metrics = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if (s + 1) % args.log_every == 0 or s == 0:
            curve.append({"step": s + 1, "loss": float(metrics["loss"])})
            print(f"[jax] step {s+1} loss {curve[-1]['loss']:.4f}", flush=True)
    final_eval = eval_loss(state["params"])
    print(f"[jax] final eval loss {final_eval:.4f}")
    return {"curve": curve, "eval_loss": final_eval, "backend": jax.default_backend(),
            "steps": args.steps}


# ---------------------------------------------------------------------------
# Torch side (the independent baseline)
# ---------------------------------------------------------------------------


def run_torch(args, model_cfg, train_path, val_path, init_npz):
    import torch

    from pretraining_llm_tpu.data import loader

    torch.manual_seed(0)
    torch.set_num_threads(os.cpu_count() or 8)
    d, h, dh, f, L = (
        model_cfg.d_model, model_cfg.n_heads, model_cfg.head_dim,
        model_cfg.d_ff, model_cfg.n_layers,
    )
    eps_ln = model_cfg.norm_eps
    if not os.path.exists(init_npz):
        raise FileNotFoundError(
            f"{init_npz} not found: the jax side writes the shared initial "
            "weights — run without --only torch first (or with --only jax)."
        )
    raw = dict(np.load(init_npz))
    saved_kw = json.loads(bytes(raw.pop("__model_kw__")).decode()) if "__model_kw__" in raw else None
    if saved_kw is not None and saved_kw != json.loads(json.dumps(MODEL_KW, sort_keys=True)):
        raise ValueError(
            "init.npz was written for a different MODEL_KW — rerun the jax "
            "side so both twins start from the same weights."
        )
    P = {k: torch.nn.Parameter(torch.from_numpy(v.copy())) for k, v in raw.items()}

    def forward(tokens):
        x = P["tok_embed__embedding"][tokens] + P["pos_embed__embedding"][None, : tokens.shape[1]]
        t = tokens.shape[1]
        mask = torch.tril(torch.ones(t, t, dtype=torch.bool))
        for li in range(L):
            ln1 = torch.nn.functional.layer_norm(
                x, (d,), P["blocks__ln1__scale"][li], P["blocks__ln1__bias"][li], eps=eps_ln
            )
            qkv = torch.einsum("btd,dchn->bcthn", ln1, P["blocks__attn__wqkv"][li])
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            att = torch.einsum("bqhd,bkhd->bhqk", q, k) / (dh**0.5)
            att = att.masked_fill(~mask[None, None], float("-inf"))
            out = torch.einsum("bhqk,bkhd->bqhd", torch.softmax(att, -1), v)
            x = x + torch.einsum("bthn,hnd->btd", out, P["blocks__attn__wo"][li]) + P["blocks__attn__bo"][li]
            ln2 = torch.nn.functional.layer_norm(
                x, (d,), P["blocks__ln2__scale"][li], P["blocks__ln2__bias"][li], eps=eps_ln
            )
            hidden = torch.nn.functional.gelu(
                ln2 @ P["blocks__mlp__w1"][li] + P["blocks__mlp__b1"][li], approximate="tanh"
            )
            x = x + hidden @ P["blocks__mlp__w2"][li] + P["blocks__mlp__b2"][li]
        x = torch.nn.functional.layer_norm(
            x, (d,), P["final_norm__scale"], P["final_norm__bias"], eps=eps_ln
        )
        return x @ P["tok_embed__embedding"].T  # tied head

    def ce(tokens, targets):
        logits = forward(tokens)
        return torch.nn.functional.cross_entropy(
            logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
        )

    # Decay mask mirrors optimizer.decay_mask (leaf-name based).
    decay_names = ("wqkv", "wo", "w1", "w2", "kernel", "embedding")
    decay = [p for k, p in P.items() if k.split("__")[-1] in decay_names]
    no_decay = [p for k, p in P.items() if k.split("__")[-1] not in decay_names]
    opt = torch.optim.AdamW(
        [
            {"params": decay, "weight_decay": WEIGHT_DECAY},
            {"params": no_decay, "weight_decay": 0.0},
        ],
        lr=LR, betas=(B1, B2), eps=EPS,
    )

    def lr_at(s):
        warm = max(WARMUP_FRAC * args.steps, 1.0)
        return min(LR * (s + 1.0) / warm, LR)

    it = loader.get_batch_iterator(
        train_path, BATCH, model_cfg.context_length, seed=DATA_SEED
    )
    curve = []
    for s in range(args.steps):
        x, y = next(it)
        for gp in opt.param_groups:
            gp["lr"] = lr_at(s)
        opt.zero_grad(set_to_none=True)
        loss = ce(torch.from_numpy(x).long(), torch.from_numpy(y).long())
        loss.backward()
        # Same clip formula as training.optimizer.clip_by_global_norm.
        with torch.no_grad():
            norm = torch.sqrt(sum((p.grad.float() ** 2).sum() for p in P.values()))
            scale = min(1.0, GRAD_CLIP / (float(norm) + 1e-9))
            if scale < 1.0:
                for p in P.values():
                    p.grad.mul_(scale)
        opt.step()
        if (s + 1) % args.log_every == 0 or s == 0:
            curve.append({"step": s + 1, "loss": loss.item()})
            print(f"[torch] step {s+1} loss {loss.item():.4f}", flush=True)

    ev = loader.get_batch_iterator(
        val_path, BATCH, model_cfg.context_length, seed=EVAL_SEED
    )
    with torch.no_grad():
        total = 0.0
        for _ in range(args.eval_iters):
            x, y = next(ev)
            total += ce(torch.from_numpy(x).long(), torch.from_numpy(y).long()).item()
    final_eval = total / args.eval_iters
    print(f"[torch] final eval loss {final_eval:.4f}")
    return {"curve": curve, "eval_loss": final_eval, "backend": "torch-cpu",
            "steps": args.steps}


# ---------------------------------------------------------------------------


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1500)
    ap.add_argument("--eval-iters", type=int, default=50)
    ap.add_argument("--log-every", type=int, default=100)
    ap.add_argument("--rebuild-corpus", action="store_true")
    ap.add_argument("--only", choices=["", "jax", "torch"], default="")
    args = ap.parse_args()

    from pretraining_llm_tpu.config import ModelConfig

    model_cfg = ModelConfig(**MODEL_KW)
    os.makedirs(PARITY_DIR, exist_ok=True)
    corpus = os.path.join(PARITY_DIR, "corpus.txt")
    train_bin = os.path.join(PARITY_DIR, "train.bin")
    val_bin = os.path.join(PARITY_DIR, "val.bin")
    init_npz = os.path.join(PARITY_DIR, "init.npz")
    results_path = os.path.join(PARITY_DIR, "results.json")

    # Rebuild only when missing (or forced): the harvest walks a LIVE
    # filesystem, so an implicit rebuild between --only jax and --only torch
    # could silently train the twins on different data.
    if args.rebuild_corpus or not os.path.exists(train_bin):
        n = build_corpus(corpus)
        tokenize_corpus(corpus, train_bin, val_bin)
        print(f"corpus: {n/1e6:.2f} MB real text -> {train_bin}")

    def _steps_of(rec):
        """(count, exact). Pre-"steps" records fall back to the last LOGGED
        step — a LOWER bound (the true count may exceed it by up to
        log_every-1), so callers must only flag mismatches they can prove."""
        if rec.get("steps") is not None:
            return rec["steps"], True
        curve = rec.get("curve") or []
        return (curve[-1]["step"], False) if curve else (None, False)

    def _proven_mismatch(a, a_exact, b, b_exact):
        if a is None or b is None:
            return False
        if a_exact and b_exact:
            return a != b
        # An exact count strictly below the other side's lower bound is the
        # only provable mismatch; two bounds prove nothing.
        if a_exact and not b_exact:
            return a < b
        if b_exact and not a_exact:
            return b < a
        return False

    results = {}
    if os.path.exists(results_path):
        results = json.load(open(results_path))

    # The delta only means something when both twins trained the same number
    # of steps — and a partial --only rerun at a different --steps must be
    # refused BEFORE it trains and overwrites the banked matching record
    # (this exact mistake produced a spurious "delta 1.1571 FAIL" and
    # destroyed a 1500-step record: a 300-step `--only jax` rerun compared
    # against — and clobbered — the recorded 1500-step twin).
    # Corpus-identity guard: the harvest walks a LIVE filesystem, so a
    # record trained in another container could (if the image ever
    # changes) sit on DIFFERENT data than the local train.bin — a partial
    # --only rerun would then compare curves across corpora and bank a
    # spurious delta. Records carry the corpus sha; a mismatch against the
    # recorded other side refuses before training. (The corpus bins are
    # also committed now, so a fresh container gets the exact bytes.)
    import hashlib

    def _file_sha(path: str) -> str:
        return hashlib.sha256(open(path, "rb").read()).hexdigest()

    def _corpus_sha() -> str:
        # The data streams the delta depends on: the train stream and the
        # val set eval_loss is measured on. The shared initial weights are
        # a SEPARATE identity (init_sha): the jax side writes init.npz on
        # a first run (and loads it thereafter), so folding it in here
        # would make the value depend on run order.
        h = hashlib.sha256(open(train_bin, "rb").read())
        h.update(open(val_bin, "rb").read())
        return h.hexdigest()

    corpus_sha = _corpus_sha()

    if args.only in ("jax", "torch"):
        other = results.get({"jax": "torch", "torch": "jax"}[args.only])
        other_sha = other.get("corpus_sha") if other else None
        if other_sha and other_sha != corpus_sha:
            print(json.dumps({
                "error": f"corpus mismatch: local train.bin+val.bin sha "
                         f"{corpus_sha[:16]} != recorded "
                         f"{'torch' if args.only == 'jax' else 'jax'} twin's "
                         f"{other_sha[:16]}; the twins would train on "
                         "different data — restore the committed "
                         "data/parity bins or retrain BOTH sides",
            }))
            return 2
        # init identity: --only torch READS the local init.npz — it must
        # be the exact weights the recorded jax twin started from.
        other_init = other.get("init_sha") if other else None
        if (
            args.only == "torch"
            and other_init
            and os.path.exists(init_npz)
            and _file_sha(init_npz) != other_init
        ):
            print(json.dumps({
                "error": f"init mismatch: local init.npz sha "
                         f"{_file_sha(init_npz)[:16]} != the recorded jax "
                         f"twin's {other_init[:16]}; the torch side would "
                         "train from different initial weights — restore "
                         "the committed data/parity/init.npz or retrain "
                         "BOTH sides",
            }))
            return 2
        so, so_exact = _steps_of(other) if other else (None, False)
        if _proven_mismatch(args.steps, True, so, so_exact):
            bound = "" if so_exact else "at least "
            print(json.dumps({
                "error": f"step-count mismatch: --only {args.only} with "
                         f"--steps {args.steps}, but the recorded "
                         f"{'torch' if args.only == 'jax' else 'jax'} twin "
                         f"ran {bound}{so} steps; rerun with a matching "
                         "--steps (or retrain both sides)",
            }))
            return 2

    if args.only in ("", "jax"):
        new_jax = run_jax(args, model_cfg, train_bin, val_bin, init_npz)
        new_jax["corpus_sha"] = corpus_sha
        # Post-run: the jax side LOADED an existing init.npz (or wrote it
        # on a first run) — stamp the file this run actually trained from,
        # and refuse if it doesn't match what the recorded torch twin
        # trained from (belt-and-braces: a hand-deleted/regenerated file
        # would otherwise silently compare curves across different inits).
        new_jax["init_sha"] = _file_sha(init_npz)
        rec_torch = results.get("torch")
        if (
            args.only == "jax"
            and rec_torch
            and rec_torch.get("init_sha")
            and rec_torch["init_sha"] != new_jax["init_sha"]
        ):
            print(json.dumps({
                "error": f"init drift: this jax run trained from init.npz "
                         f"sha {new_jax['init_sha'][:16]} but the recorded "
                         f"torch twin trained from "
                         f"{rec_torch['init_sha'][:16]} — the curves are "
                         "not comparable; restore the committed "
                         "data/parity/init.npz or retrain BOTH sides",
            }))
            return 2
        # A rerun on a DIFFERENT backend must not destroy the banked
        # record: the TPU pinned-precision capture is round evidence
        # (BASELINE.md parity table), and a casual CPU rerun would
        # silently overwrite it. Archive the displaced record under a
        # backend-suffixed key (the pattern jax_tpu_fastmatmul/jax_cpu
        # already follow).
        old_jax = results.get("jax")
        if old_jax and old_jax.get("backend") != new_jax.get("backend"):
            # Collision-safe: an existing archive (e.g. the banked
            # jax_cpu baseline) must never itself be overwritten.
            key = f"jax_{old_jax.get('backend', 'prev')}"
            n = 2
            while key in results:
                key = f"jax_{old_jax.get('backend', 'prev')}_{n}"
                n += 1
            results[key] = old_jax
        results["jax"] = new_jax
    if args.only in ("", "torch"):
        results["torch"] = run_torch(args, model_cfg, train_bin, val_bin, init_npz)
        results["torch"]["corpus_sha"] = corpus_sha
        # Post-run: in a full run, run_jax loaded (or first-run wrote)
        # init.npz and torch trained from those bytes — stamp the file
        # torch actually read.
        results["torch"]["init_sha"] = _file_sha(init_npz)
    with open(results_path, "w") as fh:
        json.dump(results, fh, indent=2)
        fh.write("\n")

    if "jax" in results and "torch" in results:
        sj, sj_exact = _steps_of(results["jax"])
        st, st_exact = _steps_of(results["torch"])
        if _proven_mismatch(sj, sj_exact, st, st_exact):
            # Belt-and-braces: records can still disagree (hand-edited file).
            print(json.dumps({
                "error": f"step-count mismatch: jax ran {sj} steps, torch ran "
                         f"{st}; rerun the shorter side with --steps "
                         f"{max(sj, st)} (or both with matching --steps)",
            }))
            return 2
        ja, to = results["jax"]["eval_loss"], results["torch"]["eval_loss"]
        delta = abs(ja - to)
        passed = delta <= 0.01
        print("\n=== PARITY ===")
        print(f"jax  ({results['jax']['backend']}): eval loss {ja:.4f}")
        print(f"torch (cpu fp32 baseline):          eval loss {to:.4f}")
        print(f"delta {delta:.4f}  ({'PASS' if passed else 'FAIL'} at +-0.01)")
        # Structured last line + nonzero exit on FAIL (ADVICE r3 medium):
        # tpu_capture banks rc and the raw tail; bank_results classifies
        # rc==0 records without an "error" key as success, so a FAIL that
        # exits 0 is silently laundered into an "ok" row.
        print(json.dumps({
            "delta": round(delta, 6),
            "pass": passed,
            "jax_eval_loss": ja,
            "torch_eval_loss": to,
            "jax_backend": results["jax"]["backend"],
            "steps": sj,
        }))
        if not passed:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
