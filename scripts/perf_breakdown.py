#!/usr/bin/env python
"""Where does the step time go? Component-level timings on the real chip.

Times (a) forward loss only, (b) forward+backward, (c) the full train step
(adds optimizer), plus isolated attention and CE-head microbenches, using the
same scan-of-N-steps + slope protocol as bench.py (the axon tunnel makes
per-dispatch timing meaningless). Prints one JSON line per component.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.training import train_step as ts


def timed(body, init_carry, n2=12, n1=3):
    """ms per iteration of `body(carry) -> carry` via the two-length slope
    protocol (cancels dispatch/transfer overhead on the remote tunnel)."""

    def runner(n):
        def run(c):
            out, _ = jax.lax.scan(lambda c, _: (body(c), None), c, None, length=n)
            return out

        return jax.jit(run)

    def sync(tree):
        return jax.tree.leaves(jax.device_get(jax.tree.map(lambda x: x.ravel()[:1], tree)))[0]

    r1, r2 = runner(n1), runner(n2)
    sync(r1(init_carry))
    sync(r2(init_carry))
    t0 = time.perf_counter()
    sync(r1(init_carry))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    sync(r2(init_carry))
    t2 = time.perf_counter() - t0
    return (t2 - t1) / (n2 - n1) * 1e3  # ms per iteration


def ragged_kernel_breakdown() -> None:
    """Decode-side component lane: the four ragged paged-attention
    variants (XLA gather, classic ragged, FA2 KV-split, AMLA rescale)
    through the same two-length-slope protocol. One JSON line each; the
    output feeds queries as next-round carry so iterations serialize.
    Off-TPU the kernel runs in interpret mode — labeled, not comparable
    to chip numbers.
    """
    import numpy as np

    from pretraining_llm_tpu.ops.pallas_ragged import (
        ragged_gather_attention,
        ragged_paged_attention,
    )

    interpret = jax.devices()[0].platform != "tpu"
    h, g, d, bs, b, t, pages = 4, 2, 32, 8, 4, 8, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(pages * 3, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(pages * 3, bs, g, d)), jnp.float32)
    tbl = jnp.asarray(rng.integers(1, pages * 3, size=(b, pages)), jnp.int32)
    seq = jnp.asarray(rng.integers(pages * bs // 2, pages * bs - t, size=(b,)), jnp.int32)
    ql = jnp.asarray([1 if i % 2 == 0 else t for i in range(b)], jnp.int32)

    variants = {
        "gather": lambda c: ragged_gather_attention(c, kp, vp, tbl, seq, ql),
        "ragged": lambda c: ragged_paged_attention(c, kp, vp, tbl, seq, ql, kv_splits=1),
        "ragged_split": lambda c: ragged_paged_attention(c, kp, vp, tbl, seq, ql, kv_splits=4),
        "ragged_amla": lambda c: ragged_paged_attention(c, kp, vp, tbl, seq, ql, kv_splits=1, amla=True),
    }
    for name, fn in variants.items():
        ms = timed(lambda c, fn=fn: fn(c).astype(c.dtype), q, n2=8, n1=2)
        print(json.dumps({
            "component": f"ragged_kernel_{name}", "ms": round(ms, 3),
            "cpu_interpret": interpret,
            "shape": {"B": b, "T": t, "pages": pages, "block_size": bs},
        }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-124m")
    ap.add_argument("--batch", type=int, default=24)
    ap.add_argument("--remat", default="full")
    ap.add_argument(
        "--ragged-kernel", action="store_true",
        help="time the ragged paged-attention variants instead of the "
        "train-step components (runs anywhere; interpret-mode off-TPU)",
    )
    args = ap.parse_args()

    if args.ragged_kernel:
        ragged_kernel_breakdown()
        return

    cfg = get_preset(args.preset)
    model = dataclasses.replace(
        cfg.model,
        attention_impl="flash" if cfg.model.attention_impl == "ring" else cfg.model.attention_impl,
        sequence_parallel=False,
        remat=args.remat,
    )
    cfg = cfg.replace(model=model, train=dataclasses.replace(cfg.train, batch_size=args.batch))
    b, t = args.batch, model.context_length
    x = jnp.zeros((b, t), jnp.int32)
    y = jnp.zeros((b, t), jnp.int32)
    state = ts.init_train_state(cfg, jax.random.key(0))

    # (a) forward loss only: params ride the carry (closing over them would
    # bake 124M constants into the program — the tunnel rejects the upload);
    # the scalar slot chains iterations so they serialize.
    def fwd_body(c):
        params, prev = c
        return (params, transformer.loss_fn(params, x, y, model) + 0.0 * prev)

    ms_fwd = timed(fwd_body, (state["params"], jnp.zeros(())))
    print(json.dumps({"component": "forward_loss", "ms": round(ms_fwd, 2)}))

    # (b) forward+backward: carry a params-shaped tree (grads feed back in)
    gradfn = jax.grad(lambda p: transformer.loss_fn(p, x, y, model))
    ms_bwd = timed(gradfn, state["params"])
    print(json.dumps({"component": "forward_backward", "ms": round(ms_bwd, 2)}))

    # (c) full train step
    step = ts.build_train_step(cfg, None)
    ms_step = timed(lambda s: step(s, (x, y))[0], state)
    print(json.dumps({"component": "full_step", "ms": round(ms_step, 2),
                      "optimizer_ms": round(ms_step - ms_bwd, 2)}))

    # attention microbench: one layer's flash fwd+bwd at model shapes
    from pretraining_llm_tpu.ops.flash_attention import flash_attention

    h, dh, g = model.n_heads, model.head_dim, model.kv_heads
    q = jnp.zeros((b, t, h, dh), jnp.bfloat16)
    kv = jnp.zeros((b, t, g, dh), jnp.bfloat16)
    attn_g = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v).astype(jnp.float32)), (0, 1, 2)
    )

    def attn_body(c):
        dq, dk, dv = attn_g(c[0], c[1], c[2])
        return (dq.astype(c[0].dtype), dk.astype(c[1].dtype), dv.astype(c[2].dtype))

    ms_attn = timed(attn_body, (q, kv, kv))
    print(json.dumps({"component": "flash_attn_fwd_bwd_per_layer", "ms": round(ms_attn, 2),
                      "all_layers_ms": round(ms_attn * model.n_layers, 2)}))

    # CE head microbench: hidden -> chunked CE fwd+bwd
    hid = jnp.zeros((b, t, model.d_model), jnp.bfloat16)
    w = jnp.zeros((model.d_model, model.vocab_size), jnp.float32)
    ce_g = jax.grad(
        lambda hdn, w: transformer._chunked_ce(hdn, w, None, y, model), (0, 1)
    )

    def ce_body(c):
        dh, dw = ce_g(c[0], c[1])
        return (dh.astype(c[0].dtype), dw)

    ms_ce = timed(ce_body, (hid, w))
    print(json.dumps({"component": "ce_head_fwd_bwd", "ms": round(ms_ce, 2)}))


if __name__ == "__main__":
    main()
