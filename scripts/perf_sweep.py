#!/usr/bin/env python
"""Sweep bench.py configurations on the real chip; record and rank results.

One command to re-tune after kernel/schedule changes (or a new chip):
runs the grid sequentially through bench.py's resilient wrapper (fresh
subprocess per attempt, transient-backend retries), appends every result to
a JSONL log, and prints the ranked table + the single best flag set.

Usage:
  python scripts/perf_sweep.py                  # default grid, gpt2-124m
  python scripts/perf_sweep.py --quick          # 1 attempt, short budget
  python scripts/perf_sweep.py --out /tmp/sweep.jsonl
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The grid: remat policies x CE head x batch. Attention stays flash (naive
# is only a reference point; measured 25% vs 41% MFU).
GRID = {
    "remat": ["none", "save_attn", "save_attn_res", "save_qkv_attn",
              "save_big", "full"],
    "ce": ["chunked", "fused", "dense"],
    "batch": [8, 12, 16, 24, 32],
}

# Excluded combos, each with the reason the skip log prints. Two classes:
# wedge risk (a known or adjacent chip-wedge combo: probing one can cost
# the backend for HOURS — the round-2 0.0 mechanism) and capacity (points
# far past the AOT-estimated memory ceiling; OOM is a clean bounded
# failure, but the budget is better spent on points that can land).
EXCLUDE = [
    # fused CE is a WEDGE CLASS on this backend, not a single bad combo:
    # save_attn+fused hung the chip twice (2026-07-31), and save_big+fused
    # — which had TWO clean captures in round 3 — hung and wedged the
    # backend on 2026-08-01. The wedge is intermittent within the class,
    # so no fused point is safe to probe on-chip; fused CE also measured
    # a throughput LOSS at every shape it completed (BASELINE.md), so the
    # payoff is known-negative.
    ({"ce": "fused"},
     "fused-CE wedge class (hung save_attn twice 2026-07-31 and save_big "
     "2026-08-01 despite two prior clean captures); measured slower anyway"),
    ({"remat": "none", "batch": 24},
     "far past the remat=none memory ceiling (AOT r4): near-certain OOM"),
    ({"remat": "none", "batch": 32},
     "far past the remat=none memory ceiling (AOT r4): near-certain OOM"),
]


def _excluded(flags: dict) -> str:
    """The exclusion reason for this combo, or '' if it should be probed."""
    for ex, why in EXCLUDE:
        if all(flags.get(k) == v for k, v in ex.items()):
            return why
    return ""


def run_one(
    flags: dict, budget: float, preset: str, quick: bool = False,
    skip_canary: bool = False,
) -> dict:
    cmd = [
        sys.executable, os.path.join(REPO, "bench.py"),
        "--preset", preset,
        "--remat", flags["remat"],
        "--ce", flags["ce"],
        "--batch", str(flags["batch"]),
        "--timeout-budget", str(budget),
        "--attempt-timeout", str(min(400.0, budget)),
    ]
    if quick:
        cmd.append("--quick")
    if skip_canary:
        # The environment was proven alive by the first config's canary;
        # later configs skip it (a mid-sweep tunnel death still surfaces as
        # that config's structured bench error).
        cmd.append("--skip-canary")
    t0 = time.time()
    rec = {"flags": flags}
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=budget + 120
        )
    except subprocess.TimeoutExpired:
        # One wedged config must not abort the rest of the grid.
        rec.update({"value": 0.0, "error": f"harness timeout after {budget + 120:.0f}s"})
        rec["wall_s"] = round(time.time() - t0, 1)
        return rec
    rec["wall_s"] = round(time.time() - t0, 1)
    line = (proc.stdout or "").strip().splitlines()
    try:
        rec.update(json.loads(line[-1]))
    except (IndexError, json.JSONDecodeError):
        rec.update({"value": 0.0, "error": (proc.stderr or "no output")[-300:]})
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-124m")
    ap.add_argument("--out", default=os.path.join(REPO, "sweep_results.jsonl"))
    ap.add_argument("--budget", type=float, default=700.0, help="seconds per config")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    budget = 300.0 if args.quick else args.budget

    combos = [
        dict(zip(GRID, vals)) for vals in itertools.product(*GRID.values())
    ]
    skipped = [(c, _excluded(c)) for c in combos if _excluded(c)]
    combos = [c for c in combos if not _excluded(c)]
    for c, why in skipped:
        print(f"[skip] {c}: {why}", flush=True)
    results = []
    with open(args.out, "a") as f:
        env_alive = False
        for i, flags in enumerate(combos):
            print(f"[{i + 1}/{len(combos)}] {flags}", flush=True)
            rec = run_one(
                flags, budget, args.preset, quick=args.quick, skip_canary=env_alive
            )
            if rec.get("value", 0) > 0 or not rec.get("environment_error"):
                env_alive = True
            f.write(json.dumps(rec) + "\n")
            f.flush()
            results.append(rec)
            print(f"    -> {rec.get('value', 0)} {rec.get('error', '')[:80]}", flush=True)

    ok = [r for r in results if r.get("value", 0) > 0]
    ok.sort(key=lambda r: -r["value"])
    print("\n=== ranked ===")
    for r in ok[:10]:
        print(f"{r['value']:.4f}  {r['flags']}  step_ms={r.get('step_ms')}")
    if ok:
        best = ok[0]
        print(
            f"\nbest: python bench.py --remat {best['flags']['remat']} "
            f"--ce {best['flags']['ce']} --batch {best['flags']['batch']}"
            f"  -> {best['value']:.4f} MFU"
        )


if __name__ == "__main__":
    main()
