#!/usr/bin/env python
"""Capture a device trace of the train step and print the HLO-op time table.

Runs a few steps under jax.profiler.trace, then parses the captured
xplane.pb with the in-image xprof converter (no TensorBoard UI needed,
the machine is air-gapped) and prints the top ops by self time — the
ground truth for where the step time actually goes.

Usage:
  python scripts/profile_capture.py --preset gpt2-124m --batch 24 --remat save_attn
  python scripts/profile_capture.py --tool framework_op_stats --top 40
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2-124m")
    ap.add_argument(
        "--batch", type=int, default=0,
        help="0 = mode default (train: 24; decode: 8 — matching bench.py's "
        "decode default so the trace explains the benchmark number)",
    )
    ap.add_argument("--remat", default="")
    ap.add_argument("--attention", default="")
    ap.add_argument(
        "--mode", default="train", choices=["train", "decode"],
        help="decode: trace KV-cached generation (prefill + token scan) "
        "instead of the train step — the ground truth for serving opt",
    )
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument(
        "--out", default="",
        help="trace dir; default derives from --mode (/tmp/pllm_trace vs "
        "/tmp/pllm_trace_decode) so a failed decode trace can never be "
        "silently satisfied by a stale train xplane (ADVICE r3)",
    )
    ap.add_argument("--tool", default="hlo_stats")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--parse-only", action="store_true")
    args = ap.parse_args()

    if not args.batch:
        args.batch = 8 if args.mode == "decode" else 24
    if not args.out:
        args.out = "/tmp/pllm_trace_decode" if args.mode == "decode" else "/tmp/pllm_trace"

    def _xplanes():
        return set(
            glob.glob(os.path.join(args.out, "**", "*.xplane.pb"), recursive=True)
        )

    pre_existing = _xplanes()
    if not args.parse_only:
        import jax
        import jax.numpy as jnp

        from pretraining_llm_tpu.config import get_preset
        from pretraining_llm_tpu.data import loader
        from pretraining_llm_tpu.training import train_step as ts

        cfg = get_preset(args.preset)
        model = cfg.model
        if args.attention:
            model = dataclasses.replace(model, attention_impl=args.attention)
        elif model.attention_impl == "ring":
            model = dataclasses.replace(model, attention_impl="flash", sequence_parallel=False)
        if args.remat:
            model = dataclasses.replace(model, remat=args.remat)
        cfg = cfg.replace(
            model=model, train=dataclasses.replace(cfg.train, batch_size=args.batch)
        )
        if args.mode == "decode":
            # Same trap bench.py guards against (its --attention check):
            # these flags shape the TRAIN step only; silently accepting
            # them would produce identical traces labeled differently.
            if args.remat or args.attention:
                raise ValueError(
                    "--remat/--attention have no effect on the cached "
                    "decode path; drop them for --mode decode"
                )
            from pretraining_llm_tpu.generation.generate import (
                decode_bench_workload, generate,
            )

            # The canonical decode-bench workload from the RAW preset model
            # (bench.py passes the raw model too — the train-oriented
            # ring->flash rewrite above must not leak in): the trace
            # explains exactly the shape `bench.py --mode decode` measures.
            mcfg, params, prompt, new_tokens = decode_bench_workload(
                get_preset(args.preset).model, args.batch
            )

            def run(seed):
                return jax.device_get(
                    generate(params, mcfg, prompt, new_tokens,
                             jax.random.key(seed), temperature=1.0)
                )

            run(0)  # compile + warm outside the trace window
            with jax.profiler.trace(args.out):
                for s in range(1, args.steps + 1):
                    run(s)
        else:
            state = ts.init_train_state(cfg, jax.random.key(0))
            step = ts.build_train_step(cfg, None)
            it = loader.synthetic_iterator(model.vocab_size, model.context_length, args.batch, seed=0)
            x, y = next(it)
            batch = (jnp.asarray(x), jnp.asarray(y))
            # Warm (compile) outside the trace window.
            state, m = step(state, batch)
            float(jax.device_get(m["loss"]))
            with jax.profiler.trace(args.out):
                for _ in range(args.steps):
                    state, m = step(state, batch)
                float(jax.device_get(m["loss"]))

    planes = sorted(_xplanes(), key=os.path.getmtime)
    if not planes:
        print(json.dumps({"error": f"no xplane.pb under {args.out}"}))
        sys.exit(1)
    if not args.parse_only and not (set(planes) - pre_existing):
        # The profiler ran but produced no NEW trace: parsing the
        # mtime-newest pre-existing file would print a stale trace (possibly
        # from the other mode) labeled as this run's. Fail loudly instead.
        print(json.dumps({
            "error": f"profiler produced no new xplane under {args.out}; "
            f"{len(planes)} stale file(s) present — refusing to parse them",
        }))
        sys.exit(1)
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([planes[-1]], args.tool, {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    rows = _extract_rows(data, args.tool)

    # Persist the FULL table and end stdout with one JSON summary line:
    # campaign stages keep only the last stdout line (tpu_capture.run_cmd),
    # and round 4's first-ever banked profile record was one truncated
    # HTML fragment — the whole table must live on disk, not in a pipe.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    table_dir = os.path.join(repo, "data", "captures")
    os.makedirs(table_dir, exist_ok=True)
    import time

    # Timestamped: successive captures must not overwrite the table a
    # previously-banked campaign record's table_path points at.
    stamp = time.strftime("%Y%m%d_%H%M%S")
    table_path = os.path.join(
        table_dir, f"profile_{args.mode}_{args.tool}_{stamp}.tsv"
    )
    with open(table_path, "w") as f:
        f.write(data if rows is None else "\n".join(rows))
    if rows is None:
        print(json.dumps({"table_path": table_path, "parsed": False}))
        return
    for r in rows[: args.top]:
        print(r)
    import re

    def clean(row: str) -> str:
        return re.sub(r"<[^>]+>", "", row)[:240]

    print(json.dumps({
        "table_path": table_path,
        "n_rows": len(rows),
        "header": clean(rows[0]) if rows else "",
        "top": [clean(r) for r in rows[1: min(9, len(rows))]],
    }))


def _extract_rows(data: str, tool: str):
    """hlo_stats/framework_op_stats come back as gviz JSON-ish or CSV."""
    try:
        obj = json.loads(data)
    except (json.JSONDecodeError, ValueError):
        lines = data.splitlines()
        return lines if lines else None
    # gviz DataTable: {"cols": [...], "rows": [{"c": [{"v": ...}, ...]}]}
    if isinstance(obj, dict) and "rows" in obj and "cols" in obj:
        labels = [c.get("label") or c.get("id") for c in obj["cols"]]
        out = ["\t".join(str(x) for x in labels)]
        for row in obj["rows"]:
            out.append("\t".join(str(c.get("v") if c else "") for c in row["c"]))
        return out
    return None


if __name__ == "__main__":
    main()
