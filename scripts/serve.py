#!/usr/bin/env python
"""Continuous-batching serving CLI over the paged KV cache.

Unlike `generate_text.py --input_file` (ONE compiled ragged program, all
rows enter and leave together), this drives
`generation.serving.ServingEngine`: requests flow through a fixed set of
batch rows, short ones finish early and free their pool blocks for
waiting ones — the online-serving execution model, exercised offline on
a prompt file. The reference has no serving stack at all (batch-1
fixed-count generate, /root/reference/src/models/transformer.py:96-114).

Example:
  python scripts/serve.py --model_path checkpoints \
      --input_file prompts.txt --max_new_tokens 100 \
      --max_batch 8 --steps_per_sched 8 --output results.jsonl

With ``--http`` the same engine goes ONLINE: a continuous engine loop
(frontend.EngineLoop) plus a stdlib HTTP/SSE gateway serving
POST /v1/generate, GET /healthz and GET /metrics until interrupted:

  python scripts/serve.py --model_path checkpoints --http --port 8000
  curl -s localhost:8000/v1/generate -d '{"prompt": "hi", "max_new_tokens": 16}'
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model_path", required=True,
                        help="checkpoint dir (or a step-N dir)")
    parser.add_argument("--input_file", default="",
                        help="one prompt per line (required unless --http)")
    parser.add_argument("--max_new_tokens", type=int, default=100)
    parser.add_argument("--max_batch", type=int, default=8,
                        help="concurrent decode rows (the compiled width)")
    parser.add_argument("--n_blocks", type=int, default=256,
                        help="KV pool size in blocks (block 0 is reserved)")
    parser.add_argument("--block_size", type=int, default=64,
                        help="tokens per pool block (multiple of 8)")
    parser.add_argument("--steps_per_sched", type=int, default=8,
                        help="decode steps per device dispatch")
    parser.add_argument("--temperature", type=float, default=1.0,
                        help="0 = greedy")
    parser.add_argument("--top_k", type=int, default=None)
    parser.add_argument("--top_p", type=float, default=None)
    parser.add_argument("--min_p", type=float, default=None)
    parser.add_argument("--stop_token", type=int, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ema", action="store_true",
                        help="serve from the EMA shadow params")
    parser.add_argument("--draft_model_path", default="",
                        help="draft checkpoint for SPECULATIVE serving "
                        "(k proposals per round verified in one target "
                        "forward; temperature-only sampling)")
    parser.add_argument("--spec_k", type=int, default=4,
                        help="draft proposals per speculative round")
    parser.add_argument("--no-pipeline", action="store_true",
                        help="disable the pipelined scheduler (fully "
                        "synchronous dispatch/reap baseline)")
    parser.add_argument("--pipeline_depth", type=int, default=0,
                        help="in-flight decode-window queue depth (0 = "
                        "config/engine default; 1 = classic double "
                        "buffering). Host scheduling only — greedy "
                        "outputs are identical at every depth")
    parser.add_argument("--admit_batch", type=int, default=0,
                        help="accumulate waiting prefills until this many "
                        "can be admitted in ONE batched admission (0/1 = "
                        "admit eagerly at every window boundary)")
    parser.add_argument("--prefix_cache", action="store_true",
                        help="cross-request prefix cache: finished requests "
                        "publish their KV blocks; new admissions reuse the "
                        "longest cached block-aligned prefix and prefill "
                        "only the suffix (greedy outputs unchanged)")
    parser.add_argument("--prefix_cache_min_blocks", type=int, default=0,
                        help="shortest cached prefix (in blocks) worth "
                        "mapping (0 = config default)")
    parser.add_argument("--prefill_chunk_tokens", type=int, default=0,
                        help="chunked prefill: stream prompts into the pool "
                        "in chunks of at most this many tokens, interleaved "
                        "with decode windows, instead of one monolithic "
                        "prefill per admission (0 = config default, which "
                        "is off; greedy outputs are identical either way)")
    parser.add_argument("--tokenizer", default=None,
                        help="override the checkpoint's tokenizer name")
    parser.add_argument("--output", default="",
                        help="results JSONL path (default: stdout)")
    parser.add_argument("--http", action="store_true",
                        help="serve an HTTP/SSE gateway instead of draining "
                        "an offline prompt file")
    parser.add_argument("--host", default=None,
                        help="gateway bind host (default: config)")
    parser.add_argument("--port", type=int, default=None,
                        help="gateway bind port, 0 = ephemeral (default: "
                        "config)")
    parser.add_argument("--max_queue_depth", type=int, default=None,
                        help="backpressure: max in-system requests before "
                        "429 (default: config)")
    parser.add_argument("--max_outstanding_tokens", type=int, default=None,
                        help="backpressure: outstanding prompt+max_new token "
                        "budget, 0 = unlimited (default: config)")
    parser.add_argument("--default_deadline_s", type=float, default=None,
                        help="deadline applied to requests that send none, "
                        "0 = none (default: config)")
    parser.add_argument("--events", default="",
                        help="(--http) request-lifecycle events JSONL path")
    parser.add_argument("--trace", default=None,
                        help="(--http) Chrome-trace JSON export path, "
                        "written at shutdown; implies --trace_sample 1.0 "
                        "unless set explicitly (default: config)")
    parser.add_argument("--trace_sample", type=float, default=None,
                        help="(--http) per-request tracing head-sample "
                        "fraction in [0, 1]; 0 = off (default: config)")
    parser.add_argument("--healthz_stale_after_s", type=float, default=None,
                        help="(--http) /healthz returns 503 once the engine "
                        "loop has not completed a scheduler turn for this "
                        "many seconds; 0 = disabled (default: config)")
    parser.add_argument("--replicas", type=int, default=None,
                        help="(--http) in-process engine replicas behind the "
                        "fleet router: prefix-affinity routing, health "
                        "ejection + relaunch, drain/redrive of in-flight "
                        "requests. 1 = plain single engine loop (default: "
                        "config)")
    parser.add_argument("--replica_mode", default=None,
                        choices=["inproc", "process"],
                        help="(--http) where replica engines live: "
                        "'inproc' = EngineLoop threads in this process; "
                        "'process' = one worker subprocess per replica "
                        "behind a socket (real kill -9 fault domain, "
                        "rolling weight upgrades). Router/gateway "
                        "behavior is identical (default: config)")
    parser.add_argument("--replica_roles", default=None,
                        help="(--http, replicas>1) comma-separated "
                        "disaggregation roles, one per replica (or one "
                        "value for all): prefill|decode|both, e.g. "
                        "'prefill,decode'. Prefill workers take no "
                        "client decode traffic; the router runs prompt "
                        "prefills on them and migrates the KV pages to "
                        "the decode target over the wire "
                        "(default: config)")
    parser.add_argument("--attach", default=None,
                        help="(--http, replica_mode=process) attach to "
                        "pre-spawned workers (worker.py --listen) instead "
                        "of spawning: comma-separated host:port list, one "
                        "address per replica. Attached workers are "
                        "detached, never killed, at teardown "
                        "(default: config frontend.worker_attach)")
    parser.add_argument("--attach_token", default=None,
                        help="(--http) shared secret for the attach "
                        "handshake; must match the worker's --token "
                        "(default: config)")
    parser.add_argument("--lease_s", type=float, default=None,
                        help="(--http) heartbeat lease: a worker that "
                        "hears nothing from the router for this long "
                        "stops admitting and parks; the router redrives "
                        "its in-flight work. 0 = disabled "
                        "(default: config)")
    parser.add_argument("--journal_path", default=None,
                        help="(--http) write-ahead fleet journal JSONL: "
                        "membership, fence generations, committed "
                        "frontiers — enough to restart the router "
                        "without losing or duplicating a request "
                        "(default: config)")
    parser.add_argument("--recover", action="store_true",
                        help="(--http) recover router state from "
                        "--journal_path before taking traffic: re-attach "
                        "survivors, fence the old generation, redrive "
                        "journaled in-flight requests from their last "
                        "committed frontier")
    parser.add_argument("--serving_faults", default=None,
                        help="(--http) serving fault plan, e.g. "
                        "'replica_crash@req3:r0,slow_window@req5' — a "
                        "deterministic failover drill (default: config)")
    parser.add_argument("--wedged_after_s", type=float, default=None,
                        help="(--http) watchdog: eject a replica whose loop "
                        "has active requests but no completed scheduler turn "
                        "for this long; 0 = disabled (default: config)")
    parser.add_argument("--quantize", default="",
                        choices=["", "none", "int8", "int8-kv"],
                        help="serving quantization: 'int8' = per-channel "
                        "int8 weights (attention/FFN projections, bf16 "
                        "accumulation); 'int8-kv' = int8 weights AND int8 "
                        "KV pool pages with bf16 per-token scales (~1.9x "
                        "block capacity at head_dim 64). Greedy outputs "
                        "are deterministic within the quantized graph but "
                        "differ from the bf16 graph (default: config)")
    parser.add_argument("--kv_checksum", action="store_true",
                        help="verify prefix-cache KV pages against digests "
                        "recorded at publish; a corrupted shared page is "
                        "dropped and the request re-prefills privately")
    parser.add_argument("--probe_interval_s", type=float, default=None,
                        help="(--http, replicas>1) golden-probe period: "
                        "inject pinned greedy probes per replica and "
                        "quarantine on output divergence; 0 = off "
                        "(default: config)")
    parser.add_argument("--probe_count", type=int, default=None,
                        help="(--http) distinct golden probes to pin "
                        "(default: config)")
    parser.add_argument("--probe_max_new", type=int, default=None,
                        help="(--http) tokens each probe decodes "
                        "(default: config)")
    parser.add_argument("--weight_fingerprint_interval_s", type=float,
                        default=None,
                        help="(--http) per-replica weight fingerprint "
                        "recompute period; the sentinel quarantines on "
                        "drift from the value pinned at launch; 0 = off "
                        "(default: config)")
    parser.add_argument("--no-slo", action="store_true",
                        help="(--http) disable the live SLO engine "
                        "(GET /slo returns 404, no burn-rate alerts)")
    parser.add_argument("--slo_ttft_s", type=float, default=2.0,
                        help="(--http) TTFT latency objective threshold "
                        "for the 'interactive' SLO class")
    parser.add_argument("--slo_e2e_s", type=float, default=30.0,
                        help="(--http) end-to-end latency objective "
                        "threshold for the 'interactive' SLO class")
    parser.add_argument("--slo_target", type=float, default=0.99,
                        help="(--http) success-fraction target shared by "
                        "the SLO objectives (error budget = 1 - target)")
    parser.add_argument("--slo_window_s", type=float, default=60.0,
                        help="(--http) rolling window for the live "
                        "latency percentile sketches")
    args = parser.parse_args()
    if not args.http and not args.input_file:
        parser.error("--input_file is required unless --http is set")

    from pretraining_llm_tpu.data.tokenizer import get_tokenizer
    from pretraining_llm_tpu.generation.generate import (
        cast_params_for_inference, load_model_for_inference,
    )
    from pretraining_llm_tpu.generation.serving import ServingEngine

    texts = []
    if args.input_file:
        with open(args.input_file) as f:
            texts = [ln.rstrip("\r\n") for ln in f if ln.strip()]
        if not texts:
            raise SystemExit(f"no prompts in {args.input_file}")

    params, cfg = load_model_for_inference(args.model_path, use_ema=args.ema)
    params = cast_params_for_inference(params, cfg.model)
    enc = get_tokenizer(args.tokenizer or cfg.data.tokenizer_name)

    spec = {}
    if args.draft_model_path:
        d_params, d_cfg = load_model_for_inference(args.draft_model_path)
        spec = dict(
            draft_params=cast_params_for_inference(d_params, d_cfg.model),
            draft_cfg=d_cfg.model, spec_k=args.spec_k,
        )

    quantize = args.quantize or cfg.serving.quantize

    # A factory, not an engine: the fleet path builds one engine per
    # replica, and a crashed replica relaunches with a FRESH engine.
    # With quantization on, quantize ONCE here (not per replica): every
    # replica then serves the same int8 codes + scales, so fleet-wide
    # fingerprint comparison and probe unanimity stay meaningful.
    if quantize != "none":
        from pretraining_llm_tpu.models import quantize as quantize_mod

        params = quantize_mod.quantize_params_for_serving(params, cfg.model)

    def make_engine():
        return ServingEngine(
            params, cfg.model,
            max_batch=args.max_batch, n_blocks=args.n_blocks,
            block_size=args.block_size, temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p, min_p=args.min_p,
            stop_token=args.stop_token, seed=args.seed,
            steps_per_sched=args.steps_per_sched,
            pipeline_depth=args.pipeline_depth or cfg.serving.pipeline_depth,
            admit_batch=args.admit_batch or cfg.serving.admit_batch,
            prefix_cache=args.prefix_cache or cfg.serving.prefix_cache,
            prefix_cache_min_blocks=(
                args.prefix_cache_min_blocks
                or cfg.serving.prefix_cache_min_blocks
            ),
            prefill_chunk_tokens=(
                args.prefill_chunk_tokens or cfg.serving.prefill_chunk_tokens
            ),
            kv_checksum=args.kv_checksum or cfg.serving.kv_checksum,
            quantize=quantize,
            **spec,
        )

    if args.http:
        _serve_http(args, cfg, make_engine, enc)
        return

    eng = make_engine()

    rids = {}
    rejected = []
    for i, text in enumerate(texts):
        try:
            rids[eng.submit(enc.encode_ordinary(text), args.max_new_tokens)] = i
        except ValueError as e:
            # One oversized prompt must not abort the other requests.
            rejected.append(i)
            print(f"[serve] rejected prompt {i}: {e}", file=sys.stderr)
    if not rids:
        raise SystemExit("every prompt was rejected")

    t0 = time.perf_counter()
    out = eng.run(pipeline=not args.no_pipeline)
    dt = time.perf_counter() - t0

    sink = open(args.output, "w") if args.output else sys.stdout
    try:
        for rid in sorted(rids, key=rids.get):
            toks = out[rid]
            record = {
                "index": rids[rid],
                "prompt": texts[rids[rid]],
                "output": enc.decode(toks),
                "n_tokens": len(toks),
            }
            # Per-request lifecycle latencies: how long the request sat in
            # the waiting queue, time to its first committed token, and
            # submit-to-finish — the offline view of the serving SLOs.
            record.update(eng.timing_summary(rid))
            sink.write(json.dumps(record) + "\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    n_tok = sum(len(out[r]) for r in rids)
    print(
        f"[serve] {len(texts)} requests, {n_tok} tokens in {dt:.2f}s "
        f"({n_tok / dt:.1f} tok/s) — stats {eng.stats}",
        file=sys.stderr,
    )


def _serve_http(args, cfg, make_engine, enc) -> None:
    """Run the online gateway until interrupted (Ctrl-C).

    ``--replicas 1`` (the default) keeps the original single
    EngineLoop wiring; ``--replicas N`` puts the fleet Router in front
    of N in-process replicas (each with its own engine, loop, admission
    and labeled registry) — same gateway, same endpoints, plus
    failover/drain/redrive semantics.
    """
    from pretraining_llm_tpu.frontend.admission import AdmissionController
    from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
    from pretraining_llm_tpu.frontend.gateway import ServingGateway
    from pretraining_llm_tpu.frontend.replica import Replica
    from pretraining_llm_tpu.frontend.router import Router
    from pretraining_llm_tpu.observability.capacity import DecisionLog
    from pretraining_llm_tpu.observability.events import EventBus
    from pretraining_llm_tpu.observability.metrics import MetricsRegistry
    from pretraining_llm_tpu.observability.slo import (
        SLOEngine, default_slo_classes,
    )
    from pretraining_llm_tpu.observability.spans import get_recorder
    from pretraining_llm_tpu.observability.tracing import Tracer
    from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

    fc = cfg.frontend

    def pick(cli_val, cfg_val):
        return cfg_val if cli_val is None else cli_val

    # The SLO engine is a pure bus subscriber, so enabling it forces a
    # bus into existence even without --events (in-memory, no JSONL).
    bus = None
    if args.events or not args.no_slo:
        bus = EventBus(jsonl_path=args.events)
    slo = None
    if not args.no_slo:
        slo = SLOEngine(
            classes=default_slo_classes(
                ttft_s=args.slo_ttft_s, e2e_s=args.slo_e2e_s,
                target=args.slo_target,
            ),
            bus=bus,
            decisions=DecisionLog(bus=bus),
            window_s=args.slo_window_s,
        )
    trace_path = pick(args.trace, fc.trace_path)
    trace_sample = pick(args.trace_sample, fc.trace_sample)
    if args.trace is not None and args.trace_sample is None:
        trace_sample = 1.0  # asking for an export implies sampling
    tracer = None
    if trace_sample > 0:
        tracer = Tracer(get_recorder(), sample=trace_sample, seed=args.seed)
    # quant_dtype rides every serving series as a const-label so dashboards
    # can split bf16 vs quantized fleets without a scrape-config change.
    quantize = args.quantize or cfg.serving.quantize
    registry = MetricsRegistry(
        prefix="pllm_serving_", const_labels={"quant_dtype": quantize}
    )
    n_replicas = pick(args.replicas, fc.replicas)
    replica_mode = pick(args.replica_mode, fc.replica_mode)
    fault_spec = pick(args.serving_faults, fc.serving_faults)
    attach = pick(args.attach, fc.worker_attach)
    attach_token = pick(args.attach_token, fc.attach_token)
    lease_s = pick(args.lease_s, fc.lease_s)
    journal_path = pick(args.journal_path, fc.journal_path)
    roles_raw = pick(args.replica_roles, getattr(fc, "replica_roles", ""))
    roles = (
        [r.strip() for r in str(roles_raw).split(",") if r.strip()]
        if roles_raw else []
    )
    if roles:
        if len(roles) == 1:
            roles = roles * n_replicas
        if len(roles) != n_replicas:
            raise SystemExit(
                f"--replica_roles lists {len(roles)} roles for "
                f"{n_replicas} replicas"
            )
        bad = [r for r in roles if r not in ("prefill", "decode", "both")]
        if bad:
            raise SystemExit(
                f"--replica_roles: unknown role(s) {bad}; expected "
                "prefill|decode|both"
            )
    attach_addrs = [a.strip() for a in attach.split(",")] if attach else []
    if attach_addrs:
        if replica_mode != "process":
            raise SystemExit("--attach needs --replica_mode process")
        if len(attach_addrs) != n_replicas:
            raise SystemExit(
                f"--attach lists {len(attach_addrs)} addresses for "
                f"{n_replicas} replicas"
            )
    if args.recover and not journal_path:
        raise SystemExit("--recover needs --journal_path")
    max_queue_depth = pick(args.max_queue_depth, fc.max_queue_depth)
    max_outstanding = pick(
        args.max_outstanding_tokens, fc.max_outstanding_tokens
    )

    def make_admission(reg, scope=""):
        return AdmissionController(
            max_queue_depth=max_queue_depth,
            max_outstanding_tokens=max_outstanding,
            retry_after_s=fc.retry_after_s,
            shed_infeasible=fc.shed_infeasible,
            registry=reg,
            scope=scope,
        )

    loop_kwargs = dict(
        idle_wait_s=fc.idle_wait_s, capacity_ring=fc.capacity_ring,
        weight_fingerprint_interval_s=pick(
            args.weight_fingerprint_interval_s,
            fc.weight_fingerprint_interval_s,
        ),
    )

    def make_router(replicas, extra_bus_faults_done=False):
        return Router(
            replicas,
            admission=make_admission(registry, scope="fleet"),
            bus=bus, registry=registry, tracer=tracer, slo=slo,
            affinity_tokens=fc.affinity_tokens,
            spill_margin=fc.spill_margin,
            wedged_after_s=pick(args.wedged_after_s, fc.wedged_after_s),
            eject_backoff_s=fc.eject_backoff_s,
            eject_backoff_max_s=fc.eject_backoff_max_s,
            backoff_seed=args.seed,
            redrive_max=fc.redrive_max_attempts,
            brownout_min_healthy_frac=fc.brownout_min_healthy_frac,
            brownout_min_priority=fc.brownout_min_priority,
            brownout_max_deadline_s=fc.brownout_max_deadline_s,
            probe_interval_s=pick(args.probe_interval_s, fc.probe_interval_s),
            probe_count=pick(args.probe_count, fc.probe_count),
            probe_max_new=pick(args.probe_max_new, fc.probe_max_new),
            journal_path=journal_path,
            journal_rotate_bytes=int(fc.journal_rotate_mb * 1024 * 1024),
            recover=args.recover,
        ).start()

    if replica_mode == "process":
        # One worker subprocess per replica. Workers load the checkpoint
        # themselves from the spec (same load/cast/quantize pipeline as
        # above); the fault plan splits into engine kinds (ride in the
        # worker spec, fire inside its scheduler) and process kinds
        # (worker_kill/worker_stall/conn_drop — executed by the parent,
        # the only party that can kill a process).
        from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
        from pretraining_llm_tpu.resilience.faults import split_serving_plan

        if args.draft_model_path:
            raise SystemExit(
                "--replica_mode process does not support speculative "
                "serving (--draft_model_path): draft params cannot ride "
                "a JSON worker spec"
            )
        engine_plan, process_plan = (
            split_serving_plan(fault_spec) if fault_spec else ("", "")
        )
        proc_faults = (
            ServingFaultInjector(process_plan, bus=bus)
            if process_plan else None
        )
        worker_spec = dict(
            model_path=args.model_path,
            ema=bool(args.ema),
            quantize=quantize,
            engine=dict(
                max_batch=args.max_batch, n_blocks=args.n_blocks,
                block_size=args.block_size, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p, min_p=args.min_p,
                stop_token=args.stop_token, seed=args.seed,
                steps_per_sched=args.steps_per_sched,
                pipeline_depth=(
                    args.pipeline_depth or cfg.serving.pipeline_depth
                ),
                admit_batch=args.admit_batch or cfg.serving.admit_batch,
                prefix_cache=args.prefix_cache or cfg.serving.prefix_cache,
                prefix_cache_min_blocks=(
                    args.prefix_cache_min_blocks
                    or cfg.serving.prefix_cache_min_blocks
                ),
                prefill_chunk_tokens=(
                    args.prefill_chunk_tokens
                    or cfg.serving.prefill_chunk_tokens
                ),
                kv_checksum=args.kv_checksum or cfg.serving.kv_checksum,
            ),
            admission=dict(
                max_queue_depth=max_queue_depth,
                max_outstanding_tokens=max_outstanding,
                retry_after_s=fc.retry_after_s,
                shed_infeasible=fc.shed_infeasible,
            ),
            loop=loop_kwargs,
            serving_faults=engine_plan,
        )
        def _rep_spec(i):
            # Attach mode: each replica gets its own pre-spawned worker
            # address (plus the shared token); spawn mode shares the spec
            # unless per-replica roles differentiate it.
            if not attach_addrs and not roles:
                return worker_spec
            s = dict(worker_spec)
            if roles:
                s["role"] = roles[i]
            if attach_addrs:
                s["attach"] = attach_addrs[i]
                if attach_token:
                    s["token"] = attach_token
            return s

        # All RemoteReplicas share the tracer's recorder (or the process
        # default): worker-exported spans land in the SAME buffer as the
        # router's own, so one shutdown export yields the merged
        # cross-host trace.
        replicas = [
            RemoteReplica(
                i, _rep_spec(i), bus=bus,
                registry_labels={"quant_dtype": quantize},
                fault_injector=proc_faults,
                backoff_seed=args.seed,
                lease_s=lease_s,
                recorder=tracer.recorder if tracer is not None else None,
            )
            for i in range(n_replicas)
        ]
        loop = make_router(replicas)
    elif n_replicas > 1:
        faults = (
            ServingFaultInjector(fault_spec, bus=bus) if fault_spec else None
        )
        replicas = [
            Replica(
                i, make_engine, bus=bus, tracer=tracer,
                registry_labels={"quant_dtype": quantize},
                admission_factory=make_admission, fault_injector=faults,
                loop_kwargs=loop_kwargs,
                role=roles[i] if roles else "both",
            )
            for i in range(n_replicas)
        ]
        loop = make_router(replicas)
    else:
        faults = (
            ServingFaultInjector(fault_spec, bus=bus) if fault_spec else None
        )
        eng = make_engine()
        if faults is not None:
            eng.pipeline_tick = faults.wrap_tick(0, eng.pipeline_tick)
        loop = EngineLoop(
            eng, admission=make_admission(registry), bus=bus,
            idle_wait_s=fc.idle_wait_s, tracer=tracer, registry=registry,
            capacity_ring=fc.capacity_ring,
        ).start()
    gateway = ServingGateway(
        loop,
        host=pick(args.host, fc.host),
        port=pick(args.port, fc.port),
        encode=enc.encode_ordinary,
        decode=enc.decode,
        default_deadline_s=pick(args.default_deadline_s, fc.default_deadline_s),
        healthz_stale_after_s=pick(
            args.healthz_stale_after_s, fc.healthz_stale_after_s
        ),
        retry_jitter_frac=fc.retry_jitter_frac,
        retry_jitter_seed=fc.retry_jitter_seed,
        slo=slo,
    )
    fleet = f" ({n_replicas} replicas)" if n_replicas > 1 else ""
    print(
        f"[serve] gateway{fleet} listening on "
        f"http://{gateway._server.server_address[0]}"
        f":{gateway.port} — POST /v1/generate, GET /healthz, GET /readyz, "
        f"GET /metrics, GET /slo, GET /metricsz, GET /debug/requests, "
        f"GET /debug/engine",
        file=sys.stderr,
    )
    # SIGTERM (a plain `kill`, the orchestrator's stop signal) must take
    # the same graceful path as ^C: without this the process dies before
    # the finally block and the whole trace export is lost. SIGTERM
    # additionally requests a fleet drain — stop admitting, let in-flight
    # requests finish (or redrive), THEN tear down — because the
    # orchestrator's kill is routine (rolling restart), not an emergency.
    graceful = {"drain": False}

    def _sigterm(signum, frame):
        graceful["drain"] = True
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        gateway.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if graceful["drain"]:
            begin = getattr(loop, "begin_drain", None)
            if begin is not None:
                begin()
            deadline = time.monotonic() + 30.0
            while (
                getattr(loop, "active_requests", 0) > 0
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            print("[serve] SIGTERM drain complete "
                  f"({getattr(loop, 'active_requests', 0)} still in flight)",
                  file=sys.stderr)
        gateway.stop()
        clean = loop.stop()
        if clean is False:
            print("[serve] WARNING: engine loop abandoned wedged at "
                  "shutdown; outstanding requests got error terminals",
                  file=sys.stderr)
        if bus is not None:
            bus.close()
        if tracer is not None and trace_path:
            path = tracer.recorder.export(trace_path)
            dropped = tracer.recorder.dropped
            extra = f" ({dropped} spans DROPPED)" if dropped else ""
            print(f"[serve] trace written to {path}{extra}", file=sys.stderr)
        counters = getattr(loop, "counters", {})
        print(f"[serve] shut down — {counters}", file=sys.stderr)


if __name__ == "__main__":
    main()
