#!/usr/bin/env python
"""Bounded exponential-backoff relauncher for training runs.

The out-of-process half of the resilience subsystem (see
pretraining_llm_tpu/resilience/): the in-process machinery turns faults into
distinct return codes + checkpoints, and this supervisor turns those codes
into restart policy. Pure stdlib — it must stay importable and instant even
when the JAX toolchain is wedged.

Usage:
    python scripts/supervisor.py [options] -- python scripts/train.py ...

Everything after ``--`` is the child command, relaunched as-is (training
resumes from the latest checkpoint by itself — resume-from-latest is the
trainer's default).

Return-code policy (the contract in resilience/__init__.py):
  0    clean completion              -> exit 0.
  43   EXIT_PREEMPTED (SIGTERM stop) -> relaunch immediately; preemptions
       are routine and the checkpoint is already written. Capped by
       --max-preemptions only as a runaway guard.
  44   EXIT_ANOMALY (rollback budget exhausted / no checkpoint) -> exit 44.
       An anomaly that survived N in-process rollbacks is systemic;
       relaunching would burn the cluster on the same failure forever.
  45   EXIT_WEDGED (watchdog: hung step) -> relaunch with backoff; counts
       toward --max-restarts.
  else crash                         -> relaunch with backoff; counts
       toward --max-restarts.

A child that ran longer than --healthy-secs before CRASHING resets the
failure count (standard supervisor pattern: a run that made hours of
progress should not inherit the backoff of a crash loop). EXIT_WEDGED
never resets it: a wedged child's lifetime includes the full watchdog
timeout of dead hang, so wall-clock says nothing about progress — and a
watchdog timeout >= --healthy-secs would otherwise relaunch a
permanently wedged run forever. The supervisor exits with the child's
last return code when a budget is exhausted, so outer schedulers see the
true failure class.

SIGTERM to the supervisor is forwarded to the child; once the child
exits, the supervisor surfaces its return code WITHOUT relaunching — a
terminated supervisor has no business restarting work. (Process-group
delivery still works too: the child's own SIGTERM handler checkpoints
and exits EXIT_PREEMPTED either way.)
"""

from __future__ import annotations

import argparse
import json
import signal
import subprocess
import sys
import time

# Keep in sync with pretraining_llm_tpu/resilience/__init__.py — duplicated
# here so the supervisor never imports the training package (or JAX).
EXIT_PREEMPTED = 43
EXIT_ANOMALY = 44
EXIT_WEDGED = 45


def _log(record: dict) -> None:
    record = {"supervisor": True, "t": round(time.time(), 1), **record}
    print(json.dumps(record), flush=True)


class _EventWriter:
    """Append supervisor events to the same JSONL stream the trainer's
    EventBus writes (--events pointed at the trainer's obs events file), in
    the same record shape (event/seq/t_wall/t_mono), so obs_report.py folds
    relaunches into one run-wide timeline. Duplicated rather than imported:
    the supervisor must stay pure-stdlib (importable when JAX is wedged).
    Every write is best-effort — a full disk must not kill the relauncher."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._seq = 0

    def emit(self, kind: str, **fields) -> None:
        if not self.path:
            return
        self._seq += 1
        record = {
            "event": kind,
            "seq": self._seq,
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "supervisor": True,
            **fields,
        }
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(record, allow_nan=False) + "\n")
        except (OSError, ValueError):
            pass


def parse_args(argv):
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "--max-restarts", type=int, default=10,
        help="failure-restart budget (wedges + crashes); exceeded -> give up",
    )
    parser.add_argument(
        "--max-preemptions", type=int, default=1000,
        help="runaway guard on immediate preemption relaunches",
    )
    parser.add_argument(
        "--backoff-base", type=float, default=5.0,
        help="first failure backoff in seconds (doubles per consecutive failure)",
    )
    parser.add_argument(
        "--backoff-max", type=float, default=300.0,
        help="backoff ceiling in seconds",
    )
    parser.add_argument(
        "--events", default="", metavar="PATH",
        help="append relaunch/exit events (trainer EventBus JSONL schema) "
        "here; point it at the run's obs events file for one merged timeline",
    )
    parser.add_argument(
        "--healthy-secs", type=float, default=300.0,
        help="a child surviving this long before a CRASH resets the failure "
        "count (wedges never reset it: their lifetime includes the whole "
        "watchdog timeout spent hung)",
    )
    if "--" not in argv:
        parser.error("missing '-- <command ...>' (the child command to supervise)")
    split = argv.index("--")
    args = parser.parse_args(argv[:split])
    cmd = argv[split + 1:]
    if not cmd:
        parser.error("empty child command after '--'")
    return args, cmd


def supervise(args, cmd) -> int:
    failures = 0
    preemptions = 0
    launches = 0
    events = _EventWriter(getattr(args, "events", ""))
    # SIGTERM handling: a TERM delivered to the supervisor ALONE (not the
    # whole process group) must not kill it outright — that would orphan
    # the training child and lose the EXIT_PREEMPTED relaunch contract.
    # The handler forwards the signal to the child; the loop then waits
    # for the child's exit and surfaces its return code without
    # relaunching.
    state = {"child": None, "term": False}

    def _on_term(signum, frame):  # noqa: ARG001 — signal API shape
        state["term"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signal.SIGTERM)
            except OSError:
                pass  # child exited between poll and send

    try:
        prev_term = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # non-main thread (tests): run without forwarding
        prev_term = None
    try:
        while True:
            launches += 1
            _log({"event": "launch", "attempt": launches, "cmd": cmd})
            started = time.monotonic()
            try:
                child = subprocess.Popen(cmd)
                state["child"] = child
                if state["term"]:  # TERM raced the launch: forward now
                    child.send_signal(signal.SIGTERM)
                rc = child.wait()
            except KeyboardInterrupt:
                _log({"event": "interrupted"})
                child = state["child"]
                if child is not None and child.poll() is None:
                    child.terminate()
                    try:
                        child.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        child.kill()
                return 130
            finally:
                state["child"] = None
            elapsed = time.monotonic() - started
            _log({"event": "exit", "rc": rc, "elapsed_s": round(elapsed, 1)})

            if state["term"]:
                _log({"event": "terminated", "rc": rc})
                return rc
            if rc == 0:
                return 0
            if rc == EXIT_ANOMALY:
                _log({"event": "fatal", "why": "anomaly budget exhausted; needs a human"})
                events.emit("failure", rc=rc, why="anomaly_budget")
                return rc
            if rc == EXIT_PREEMPTED:
                preemptions += 1
                if preemptions > args.max_preemptions:
                    _log({"event": "fatal", "why": "preemption budget exhausted"})
                    events.emit("failure", rc=rc, why="preemption_budget")
                    return rc
                _log({"event": "relaunch", "why": "preempted", "backoff_s": 0})
                events.emit("relaunch", rc=rc, why="preempted", attempt=launches)
                continue

            # Wedge or crash: exponential backoff, bounded budget. The
            # health reset applies to crashes only — a wedged child's
            # elapsed time includes watchdog_timeout_s of dead hang, so
            # its lifetime measures nothing; letting wedges reset the
            # counter would relaunch a permanently wedged run forever
            # whenever the watchdog timeout exceeds --healthy-secs.
            if rc != EXIT_WEDGED and elapsed >= args.healthy_secs and failures:
                _log({"event": "failure_count_reset", "elapsed_s": round(elapsed, 1)})
                failures = 0
            failures += 1
            if failures > args.max_restarts:
                _log({"event": "fatal", "why": "restart budget exhausted", "failures": failures - 1})
                events.emit("failure", rc=rc, why="restart_budget")
                return rc
            backoff = min(args.backoff_base * 2 ** (failures - 1), args.backoff_max)
            why = "wedged" if rc == EXIT_WEDGED else f"crash rc={rc}"
            _log({"event": "relaunch", "why": why, "failures": failures, "backoff_s": backoff})
            events.emit(
                "relaunch", rc=rc, why=why, attempt=launches, backoff_s=backoff
            )
            time.sleep(backoff)
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)


def main() -> None:
    args, cmd = parse_args(sys.argv[1:])
    sys.exit(supervise(args, cmd))


if __name__ == "__main__":
    main()
