#!/usr/bin/env python
"""One-shot TPU measurement campaign: run EVERYTHING the round needs the
moment hardware is reachable.

The axon TPU tunnel has been intermittent across rounds; when it comes back
there may be a short window. This script runs the full capture sequence in
priority order, each stage a bounded subprocess, appending structured
results to a JSONL log as they land — a partial window still banks the
most important numbers first.

Hardware-window discipline (VERDICT r3 next #7), enforced in code: the
round's driver-facing deliverables — (a) the driver-reproducible bench.py
default [mfu], (b) parity-tpu, (c) e2e — are CRITICAL_STAGES and run first;
any stage that probes a kernel-config class never proven on this backend
(RISKY_STAGES: profiler instrumentation, int8-KV decode, scan-unroll
overrides, the open-ended sweep grid) is DEFERRED until all three critical
records are banked in the campaign log. Two full rounds lost their headline
number to probe-induced wedges (save_attn+fused CE, flash block-512
overrides) during the only hardware window; the ordering is now policy,
not convention. Override for manual debugging only: --force-risky.

Stages (priority order):
  1. canary        — environment probe (bench.py --_canary); abort if dead
  2. mfu           — the driver metric: bench.py default race (gpt2-124m)
  3. parity-tpu    — scripts/parity_experiment.py with pinned matmul
                     precision (the BASELINE.md promised TPU rerun)
  4. e2e           — train -> SIGTERM -> resume -> evaluate, on chip
  5. sweep-top     — the most promising perf-sweep configs (proven classes)
  6. batch-sweep / mfu-350m / mfu-1b / mfu-1b-ladder / mfu-wave3 /
     mfu-wave4 / sweep2 — batch knees, the larger BASELINE models'
                     remat x batch x CE ladders (proven classes)
  7. decode        — KV-cached decode (+ stacked comparison arm, ragged)
  8. ctx8k / ctx16k / trainer — 8k + 16k/32k context, trainer overlap
  9. [risky, gated] profile / profile-decode / decode-int8 /
                    decode-unroll / unroll-sweep / serving (+sps sweep) /
                    sweep-full

Usage:
  python scripts/tpu_capture.py                 # full campaign
  python scripts/tpu_capture.py --stages mfu,decode
  python scripts/tpu_capture.py --out /tmp/capture.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

# The three records the round cannot end without (VERDICT r3 #7): the
# driver's reproducible number, TPU-side loss parity, and the on-chip
# end-to-end exercise.
CRITICAL_STAGES = ("mfu", "parity-tpu", "e2e")

# Kernel-config classes never proven on this backend. Every chip wedge so
# far came from exactly such a probe (save_attn+fused CE, block-512
# overrides) — and a wedge costs the rest of the hardware window, so these
# may only run once every critical record is banked.
RISKY_STAGES = frozenset(
    {"profile", "profile-decode", "decode-int8", "decode-unroll",
     "unroll-sweep", "sweep-full", "serving"}
)


def _log_records(out_path: str):
    """Yield parsed records from a campaign log, skipping undecodable
    lines — the ONE definition of log iteration (the log is append-only
    JSONL shared across campaigns)."""
    try:
        with open(out_path) as f:
            for ln in f:
                try:
                    yield json.loads(ln)
                except json.JSONDecodeError:
                    continue
    except OSError:
        return


def _stage_proven_this_campaign(out_path: str, prefix: str) -> bool:
    """True when THIS campaign (records after the last campaign-start
    marker) banked a clean run of a stage matching ``prefix``: rc == 0,
    no error, and NOT flagged backend_wedged (an rc==0 bench race whose
    later candidate wedged the chip proves nothing about probing the
    class again). Scoping + the wedge check exist for the same reason as
    _critical_banked's latest-record semantics: stale or poisoned
    records must never unlock a risky probe."""
    proven = False
    for r in _log_records(out_path):
        if r.get("stage") == "campaign-start":
            proven = False  # scope to the current campaign
            continue
        if (
            str(r.get("stage", "")).startswith(prefix)
            and r.get("rc") == 0
            and not r.get("error")
            and not r.get("backend_wedged")
        ):
            proven = True
    return proven


def _critical_banked(out_path: str) -> set:
    """Critical stages whose LATEST record in the campaign log is a
    completed measurement.

    mfu/e2e count when they succeeded (rc==0, no error). parity-tpu counts
    when the measurement COMPLETED — its structured last line carries a
    "delta" key whether it passed or failed (an honest numeric FAIL is a
    banked result, not a lost window; only a crash/hang leaves it unbanked).

    Latest-record-per-stage semantics: the default log is append-only
    across campaigns, and a stale success from a previous round must not
    unlock risky probes on a backend whose mfu/e2e just FAILED this
    campaign — the most recent attempt decides.
    """
    latest: dict = {}
    for r in _log_records(out_path):
        stage = r.get("stage", "")
        if stage in CRITICAL_STAGES:
            latest[stage] = r
    done: set = set()
    for stage, r in latest.items():
        if "error" in r:
            continue
        if stage == "parity-tpu":
            # Regardless of rc: only a structured delta is a measurement.
            # An rc==0 run that never compared curves (e.g. the torch twin
            # record was missing, so the script trained one side and
            # exited 0) must not unlock risky probes — that is exactly the
            # spurious-record shape that burned round 3.
            if "delta" in r:
                done.add(stage)
        elif r.get("rc") == 0:
            done.add(stage)
    return done

sys.path.insert(0, REPO)
import bench as _bench  # noqa: E402 — one definition of "healthy canary"


def _canary_probe(timeout: float = 150.0):
    """Cheap environment probe (~7s when healthy). Returns the canary's
    parsed JSON record on success, None on failure/hang. Delegates to
    bench.py's _run_canary so the canary contract lives in ONE place."""
    ok, detail = _bench._run_canary(timeout)
    return detail if ok and isinstance(detail, dict) else None


def wait_for_backend(out_f, wait_pool: dict):
    """Poll canaries until the backend answers or the shared recovery pool
    is exhausted. Returns the successful canary record, or None.

    Round-3 on-chip lesson: a stage whose inner run hangs and is killed can
    leave the backend unacquirable for a long stretch — chaining the next
    stage with --skip-canary then burns its whole budget hanging at device
    acquisition. Cheap canary polls instead; the campaign resumes (with the
    SAME stage, preserving priority order) the moment the tunnel answers.

    ``wait_pool["remaining"]`` is the campaign-wide waiting budget: outages
    across the whole run may consume at most --recovery-wait seconds in
    total, after which the campaign aborts — a stage is never skipped while
    the backend is down.
    """
    t0 = time.time()

    def _pool_bounded_timeout() -> float:
        # Every probe — including the initial two — is bounded by the pool,
        # so --recovery-wait is a real cap even when canaries hang for their
        # full timeout (a 150s default probe must not overrun a nearly-dry
        # pool). The 5s floor keeps a healthy-but-slow probe classifiable.
        return min(150.0, max(5.0, wait_pool["remaining"] - (time.time() - t0)))

    rec = _canary_probe(timeout=_pool_bounded_timeout())
    if rec is not None:
        return rec
    # One immediate retry before declaring an outage: a single canary flake
    # on the intermittent tunnel must not impose the 120s outage cadence or
    # drain the shared pool (same rationale as bench.py's 2-try gate).
    rec = _canary_probe(timeout=_pool_bounded_timeout())
    if rec is not None:
        return rec
    print("[capture] backend not answering; polling for recovery", flush=True)
    while wait_pool["remaining"] > time.time() - t0:
        time.sleep(min(120, max(1.0, wait_pool["remaining"] - (time.time() - t0))))
        # Bound each probe by the remaining pool so --recovery-wait is a
        # real cap, not a lower bound (a hanging canary burns 150s/probe).
        rec = _canary_probe(timeout=_pool_bounded_timeout())
        if rec is not None:
            waited = round(time.time() - t0, 1)
            wait_pool["remaining"] -= waited
            print(f"[capture] backend recovered after {waited}s", flush=True)
            out_f.write(json.dumps(
                {"stage": "backend-recovered", "waited_s": waited, **rec}) + "\n")
            out_f.flush()
            return rec
    wait_pool["remaining"] = 0.0
    out_f.write(json.dumps(
        {"stage": "recovery-budget-exhausted",
         "waited_s": round(time.time() - t0, 1)}) + "\n")
    out_f.flush()
    return None


def run_cmd(name: str, cmd: list, timeout: float, out_f,
            wait_pool: dict | None = None) -> dict:
    """Run one stage; parse its last stdout line as JSON when possible.

    When ``wait_pool`` is given, a cheap canary gates the stage: if the
    backend is wedged the campaign polls for recovery (bounded by the shared
    pool) instead of burning the stage budget on a device-acquisition hang.
    A gate failure means the pool is gone — the caller must abort, not skip.
    """
    if wait_pool is not None and wait_for_backend(out_f, wait_pool) is None:
        # Out-of-band marker: rc values belong to the stage subprocess
        # (e.g. -2 = killed by SIGINT) and payloads may carry their own keys.
        rec = {"stage": name, "gate_exhausted": True,
               "error": "backend unreachable; campaign recovery budget exhausted"}
        out_f.write(json.dumps(rec) + "\n")
        out_f.flush()
        print(f"[capture] {name} -> {json.dumps(rec)[:300]}", flush=True)
        return rec
    t0 = time.time()  # after the gate: wall_s is pure stage runtime
    print(f"[capture] {name}: {' '.join(cmd[1:])}", flush=True)
    try:
        # start_new_session + killpg: a timed-out stage must take its WHOLE
        # process tree down. Stages are wrappers around wrappers (tpu_e2e ->
        # train.py, bench.py -> inner attempt); killing only the top process
        # orphans a grandchild that may be holding (or wedging) the chip —
        # the exact cascade the canary gates exist to stop.
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr,
            text=True, cwd=REPO, start_new_session=True,
        )
        try:
            stdout, _ = proc.communicate(timeout=timeout)
        except BaseException:
            # ANY abnormal exit from the wait (stage timeout, Ctrl-C, a
            # campaign kill) must take the detached stage tree down with it
            # — start_new_session means nobody else will signal it, and an
            # orphaned stage keeps holding (or wedging) the chip.
            import signal as _signal

            # Unconditional: the group can hold live grandchildren even
            # after the leader exited (they inherit the stdout pipe, so
            # communicate() was still blocked on them).
            try:
                os.killpg(proc.pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()
            raise
        lines = [ln for ln in (stdout or "").splitlines() if ln.strip()]
        try:
            payload = json.loads(lines[-1]) if lines else {}
        except json.JSONDecodeError:
            payload = {"raw": lines[-1][:400] if lines else ""}
        rec = {"stage": name, "rc": proc.returncode, **payload}
    except subprocess.TimeoutExpired:
        rec = {"stage": name, "rc": -1, "error": f"stage hung past {timeout:.0f}s"}
    rec["wall_s"] = round(time.time() - t0, 1)
    out_f.write(json.dumps(rec) + "\n")
    out_f.flush()
    print(f"[capture] {name} -> {json.dumps(rec)[:300]}", flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "tpu_capture.jsonl"))
    ap.add_argument("--stages", default="", help="comma list; empty = all")
    ap.add_argument("--mfu-budget", type=float, default=2400.0)
    ap.add_argument(
        "--recovery-wait", type=float, default=3600.0,
        help="campaign-wide budget (seconds) for polling backend recovery "
        "across ALL outages; when exhausted the campaign aborts (stages are "
        "never skipped while the backend is down)")
    ap.add_argument(
        "--force-risky", action="store_true",
        help="run RISKY_STAGES even before the critical records are banked "
        "(manual debugging only — this is how two rounds lost their number)")
    args = ap.parse_args()
    KNOWN = {
        "mfu", "sweep-top", "decode", "decode-int8", "decode-unroll",
        "ctx8k", "trainer",
        "parity-tpu", "sweep-full", "sweep2", "profile", "profile-decode",
        "e2e", "batch-sweep", "unroll-sweep", "mfu-350m", "mfu-1b",
        "mfu-1b-ladder", "serving", "mfu-wave3", "mfu-wave4", "ctx16k",
        # r5 stages (VERDICT r4 next-round list):
        "mfu-1b-wave5", "mfu-1b-wave6", "ctx8k-gqa", "serving-ab",
        "serving-kernel", "serving-spec", "mfu-refresh",
    }
    want = None
    if args.stages:
        want = {s.strip() for s in args.stages.split(",") if s.strip()}
        unknown = want - KNOWN
        if unknown:
            # Fail FAST and loud: a typo that silently ran only the canary
            # would waste the (possibly brief) hardware window this script
            # exists to exploit.
            ap.error(
                f"unknown stage(s) {sorted(unknown)}; known: {sorted(KNOWN)}"
            )

    def on(stage: str) -> bool:
        return want is None or stage in want

    py = sys.executable
    with open(args.out, "a") as f:
        f.write(json.dumps({"stage": "campaign-start", "ts": time.time()}) + "\n")
        f.flush()

        # 1. Environment canary: no point burning budgets on a dead tunnel.
        # Poll for recovery (bounded) rather than aborting outright — the
        # tunnel has come back mid-round before; the campaign should fire
        # the moment it does. ONE probe serves as both gate and record (a
        # second back-to-back probe would double flake exposure right at
        # the window-open moment).
        wait_pool = {"remaining": args.recovery_wait}
        rec = wait_for_backend(f, wait_pool)
        if rec is None:
            print("[capture] backend unreachable; aborting campaign", flush=True)
            return 1
        f.write(json.dumps({"stage": "canary", "rc": 0, **rec}) + "\n")
        f.flush()

        class _Abort(Exception):
            pass

        # Gate a stage on a canary probe ONLY after an unclean stage exit
        # (hang-kill or error) — that is when the wedge mechanism can have
        # fired. After a clean rc=0 stage (or the startup probe) the backend
        # was just alive; an extra probe would only add flake exposure.
        gate_state = {"needed": False}

        def gated(name: str, cmd: list, timeout: float) -> dict:
            """Stage with a conditional canary gate + shared recovery pool
            (a wedged backend after a killed hung stage must not cascade).
            Aborts the campaign when the pool is exhausted — never skips a
            stage."""
            pool = wait_pool if gate_state["needed"] else None
            rec = run_cmd(name, cmd, timeout, f, wait_pool=pool)
            if rec.get("gate_exhausted"):
                raise _Abort(name)
            # rc=0 can still leave the backend dead: bench.py reports a
            # banked result (rc=0) even when a later candidate wedged the
            # chip — it marks the record instead. Conversely a COMPLETED
            # measurement that failed its numeric bar (parity rc=1 with a
            # structured "delta") ran to a clean exit: nothing hung, no
            # wedge mechanism fired, no recovery gate needed.
            clean_exit = rec.get("rc") == 0 or "delta" in rec
            gate_state["needed"] = (
                not clean_exit or bool(rec.get("backend_wedged"))
            )
            return rec

        def risky(name: str, cmd: list, timeout: float) -> dict:
            """Risk-policy gate (VERDICT r3 #7): a stage probing an unproven
            kernel-config class runs ONLY after every critical record is
            banked. A deferred stage writes a structured skip record — the
            campaign log shows the policy fired, not a silent gap."""
            if not args.force_risky:
                banked = _critical_banked(args.out)
                missing = [s for s in CRITICAL_STAGES if s not in banked]
                if missing:
                    rec = {"stage": name, "skipped": True, "risk": "unproven",
                           "error": "deferred by risk policy: critical "
                                    f"stages not yet banked: {missing}"}
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
                    print(f"[capture] {name} deferred (risk policy): "
                          f"missing {missing}", flush=True)
                    return rec
            return gated(name, cmd, timeout)

        try:
            _run_stages(args, on, gated, risky, py)
        except _Abort as stage:
            print(f"[capture] recovery budget exhausted at stage {stage}; "
                  "aborting campaign", flush=True)
            return 1
    return 0


def _run_stages(args, on, gated, risky, py) -> None:
    # 2. The driver metric (races remat candidates incl. safe tail).
    if on("mfu"):
        gated(
            "mfu",
            [py, BENCH, "--skip-canary",
             "--timeout-budget", str(args.mfu_budget)],
            args.mfu_budget + 120,
        )

    # 3. TPU-side parity at pinned matmul precision — CRITICAL: banked
    # before any sweep (the script pins jax_default_matmul_precision=
    # "highest" itself; BASELINE.md:60-63's promised rerun). The torch
    # side runs on host CPU; --only jax reuses the recorded torch curve.
    # --steps MUST match the recorded torch curve (1500): a shorter
    # partial rerun overwrites the jax record and the final-loss delta
    # becomes meaningless (the script also guards this itself, and a
    # numeric FAIL now exits 1 with a structured {"delta": ...} line).
    if on("parity-tpu"):
        gated(
            "parity-tpu",
            [py, os.path.join(REPO, "scripts", "parity_experiment.py"),
             "--steps", "1500", "--only", "jax"],
            3600,
        )

    # 4. End-to-end operational exercise on the real chip — CRITICAL:
    # real-corpus train -> SIGTERM preemption -> resume -> evaluate,
    # through the CLIs (VERDICT r2 #3 / r3 next #4).
    if on("e2e"):
        gated(
            "e2e",
            [py, os.path.join(REPO, "scripts", "tpu_e2e.py"), "--steps", "300"],
            1800,
        )

    # 4b. THE round-5 bar (VERDICT r4 #1): >=50% MFU unnormalized, same
    # session, at the 1B scale — 47.0% banked, 3 points open. Runs right
    # after the critical trio: these are the points that close the round.
    # All proven classes (flash auto-block, XLA checkpoint policies,
    # Adafactor, dense CE); GQA is gradient-tested and the llama3-1b-gqa
    # preset quarters decode-side KV bandwidth (16 -> 4 KV heads) — at b8
    # the smaller KV write/read traffic is the openest lever left.
    # save_attn_res is the r5 policy that stops the flash forward running
    # twice in backward (the r4 profile's finding); same memory class as
    # save_attn. OOM raises cleanly — it cannot wedge.
    if on("mfu-1b-wave5"):
        for extra in (
            # GQA arm first: new preset, biggest headroom hypothesis.
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "8", "--ce", "dense"],
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "8"],
            # The banked 47.0% config with the double-flash-forward
            # removed (save_attn_res at 1B; OOM clean if it won't fit).
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "save_attn_res", "--batch", "4"],
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "save_attn_res", "--batch", "8"],
            # Past-the-knee probe on the champion arm.
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "12"],
            # bf16 gradient tree (train.grad_dtype): frees ~2.5 GB of the
            # ~5 GB fp32 grads at 1B — the HBM term that pins the b8
            # knee. fp32-per-leaf optimizer math unchanged; OOM clean.
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "12",
             "--grad-dtype", "bfloat16"],
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "16",
             "--grad-dtype", "bfloat16"],
        ):
            gated(
                "mfu-1b-wave5:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary", "--timeout-budget", "900"]
                + extra,
                1020,
            )

    # 4c. Wave 6 (2026-08-02): COMBINED levers. Wave-5 measured each r5
    # lever alone; the combinations are the unprobed cells, and the
    # save_attn_res arms are memory-gated in exactly the way the other
    # two levers relieve (b4 banked 45.4%, b8 OOM'd: bf16 grads free
    # ~2.5 GB of the fp32 gradient tree, GQA shrinks the saved KV
    # residuals G/H). All knobs are proven classes on this backend
    # (XLA remat policy + dtype casts + the GQA preset — no new kernel
    # configs); OOM raises cleanly.
    if on("mfu-1b-wave6"):
        for extra in (
            # The memory-relieved save_attn_res ladder, GQA first.
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "save_attn_res", "--batch", "8",
             "--grad-dtype", "bfloat16"],
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "save_attn_res", "--batch", "6",
             "--grad-dtype", "bfloat16"],
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "save_attn_res", "--batch", "6",
             "--grad-dtype", "bfloat16"],
            # save_attn (124M's same-session 50.27% winner) at 1B: saves
            # only the attention probs/outputs, lighter than _res.
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "save_attn", "--batch", "8",
             "--grad-dtype", "bfloat16"],
            # Stack GQA on the wave-5 champion (llama-1b full/b12/bf16
            # banked 48.4% — the best 1B measurement to date).
            ["--preset", "llama3-1b-gqa", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "12",
             "--grad-dtype", "bfloat16"],
            # Between the b12 champion and the b16 OOM.
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "14",
             "--grad-dtype", "bfloat16"],
            # Exact repeat of the wave-5 champion: today's backend shows
            # per-run transients in BOTH directions (15.7%/2.1% slow
            # outliers, a 50.27% fast outlier re-measured at 43.8%) — a
            # single 48.4% reading is not a banked champion until it
            # reproduces.
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "12",
             "--grad-dtype", "bfloat16"],
        ):
            gated(
                "mfu-1b-wave6:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary", "--timeout-budget", "900"]
                + extra,
                1020,
            )

    # 5. Most promising sweep points first. NOTE: fused CE is EXCLUDED as
    # an entire class: save_attn+fused hung the device twice (round 3),
    # and on 2026-08-01 save_big+fused — clean in two round-3 captures —
    # hung past 700s and the kill wedged the backend. The wedge is
    # intermittent within the class; no fused point runs on-chip again
    # (it also measured slower at every shape that completed).
    if on("sweep-top"):
        for remat, ce, batch in (
            ("save_big", "chunked", 32), ("save_attn", "chunked", 16),
            ("save_attn", "chunked", 32),
        ):
            gated(
                f"sweep:{remat}/{ce}/b{batch}",
                [py, BENCH, "--skip-canary", "--remat", remat, "--ce", ce,
                 "--batch", str(batch), "--timeout-budget", "900"],
                1020,
            )

    # 6a. Batch micro-sweep around the wave-1 winner (b16 > b24 > b32 at
    # save_attn/chunked): find the throughput knee. (No block-size points:
    # block overrides hang this backend — see the sweep2 comment above.)
    if on("batch-sweep"):
        # remat=none points (store everything, ZERO recompute): analytic MFU
        # charges remat recompute as waste, so if the activations fit, the
        # honest number jumps. CPU AOT (true peak = args + temps) says
        # none/b4 ~8.8 GiB, none/b8 ~14.5 GiB — but CPU AOT compiles NAIVE
        # attention (materialized (T,T) scores the TPU flash kernel never
        # allocates; its custom-VJP residuals are q/k/v/o/lse), so the TPU
        # footprint is smaller still: the ladder probes up to b16. OOM
        # raises cleanly — it cannot wedge. XLA checkpoint policy is a
        # proven class on this backend (same compile path as the measured
        # remat points).
        # Proven-class knee points FIRST (bank-most-important-first: a
        # short window must not close on speculative probes), then the
        # none ladder ascending — each OOM costs one bounded attempt
        # (bench.py classifies OOM as deterministic, never retried).
        for extra in (
            ["--remat", "save_attn", "--batch", "8"],
            ["--remat", "save_attn", "--batch", "12"],
            ["--remat", "save_attn", "--batch", "20"],
            ["--remat", "save_big", "--batch", "8"],
            ["--remat", "save_big", "--batch", "16"],
            ["--remat", "none", "--batch", "4"],
            ["--remat", "none", "--batch", "8"],
            ["--remat", "none", "--batch", "12"],
            ["--remat", "none", "--batch", "16"],
            # ce=dense (saved-logits head, r4): removes the chunked CE
            # backward's logits-matmul recompute (~10% of analytic step
            # FLOPs) for S*V*2 bytes of saved residual — plain XLA einsums,
            # same proven compile class as chunked.
            ["--remat", "none", "--batch", "8", "--ce", "dense"],
            ["--remat", "save_attn", "--batch", "16", "--ce", "dense"],
            ["--remat", "save_attn", "--batch", "8", "--ce", "dense"],
        ):
            gated(
                "bsweep:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary",
                 "--timeout-budget", "700"] + extra,
                820,
            )

    # 6b. The other BASELINE model configs on the one chip: 350M
    # (BASELINE config #2's model, mesh collapsed to 1 device) and the
    # Llama-style 1B (config #4) at a batch its optimizer state + remat
    # leave room for. OOM raises cleanly — it cannot wedge the chip.
    if on("mfu-350m"):
        # b16+dense: saved logits ~1.65 GB on top of the ~12.8 GiB b16
        # footprint — fits; the zero-recompute CE head is where the larger
        # models' MFU is most attainable too. (2026-08-01: the first three
        # points ran before the preset gained flash attention — the preset
        # now carries attention_impl='flash', so re-runs measure the real
        # configuration; the naive points stay banked for the comparison.)
        for extra in ([], ["--batch", "16"],
                      ["--batch", "16", "--ce", "dense"]):
            gated(
                "mfu-350m" + ("/" + "/".join(extra).replace("--", "")
                              if extra else ""),
                [py, BENCH, "--skip-canary", "--preset", "gpt2-350m-dp",
                 "--remat", "save_attn", "--timeout-budget", "800"] + extra,
                920,
            )
    # Single-chip 1B via Adafactor: fp32 params + ADAM moments are ~14.9 GB
    # of the 16 GB chip (impossible), but factored second moments are
    # ~0.2 GB — params 4.96 + v 0.2 + bf16 copy 2.5 + grads 4.96 leaves
    # room for full-remat activations at small batch. BASELINE config #4's
    # model, trained where Adam cannot. OOM raises cleanly (no wedge).
    # Batch points sized by CPU AOT memory analysis (r4): true peak
    # (args + temps; outputs alias donated state) is ~13.5 GiB at b2,
    # ~16.3 GiB at b4 — b2 fits the 16 GB chip, b4 is a marginal probe
    # (clean OOM if not), b8 (~22 GiB) was dropped.
    if on("mfu-1b"):
        for batch in (2, 4):
            gated(
                f"mfu-1b/adafactor/b{batch}",
                [py, BENCH, "--skip-canary", "--preset", "llama-1b",
                 "--optimizer", "adafactor", "--remat", "full",
                 "--batch", str(batch), "--timeout-budget", "800"],
                920,
            )

    # 6b'. 1B remat ladder (2026-08-01): b2/b4 at remat=full banked
    # 43.2%/45.1% — full remat charges the whole backward recompute as
    # waste, so LIGHTER policies raise honest MFU if the activations fit
    # (clean OOM otherwise), and a bigger batch amortizes fixed costs.
    # All proven classes: XLA checkpoint policies + the flash kernel +
    # dense CE, same compile paths measured at 124m.
    if on("mfu-1b-ladder"):
        for extra in (
            ["--remat", "full", "--batch", "6"],
            ["--remat", "save_big", "--batch", "2"],
            ["--remat", "save_big", "--batch", "4"],
            ["--remat", "dots_saveable", "--batch", "4"],
            ["--remat", "full", "--batch", "4", "--ce", "dense"],
        ):
            gated(
                "mfu-1b-ladder:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary", "--preset", "llama-1b",
                 "--optimizer", "adafactor", "--timeout-budget", "800"]
                + extra,
                920,
            )

    # 6b''. Third-wave large-model points (2026-08-01 after the ladder):
    # 1B full-remat rose monotonically b2 43.2 -> b4 45.1 -> b6 46.2 (b8
    # is the next rung; clean OOM if it doesn't fit); 350M flash banked
    # 40.2% at b32 — probe the knee upward + the save_big arm.
    # 6b'''. Fourth wave (post CE-scatter-fix, 2026-08-01): dense CE wins
    # the 124m race after the fix (43.8 > 42.7) — probe it at the larger
    # models; bracket the 350m knee (43.0 @ b48 > 38.6 @ b64); push the 1B
    # batch one more rung (47.0 @ b8; OOM is clean).
    if on("mfu-wave4"):
        for extra in (
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "8", "--ce", "dense"],
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "10"],
            ["--preset", "gpt2-350m-dp", "--remat", "save_attn",
             "--batch", "48", "--ce", "dense"],
            ["--preset", "gpt2-350m-dp", "--remat", "save_attn",
             "--batch", "56"],
        ):
            gated(
                "mfu-wave4:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary", "--timeout-budget", "900"]
                + extra,
                1020,
            )

    if on("mfu-wave3"):
        for extra in (
            ["--preset", "llama-1b", "--optimizer", "adafactor",
             "--remat", "full", "--batch", "8"],
            ["--preset", "gpt2-350m-dp", "--remat", "save_attn",
             "--batch", "48"],
            ["--preset", "gpt2-350m-dp", "--remat", "save_attn",
             "--batch", "64"],
            ["--preset", "gpt2-350m-dp", "--remat", "save_big",
             "--batch", "32"],
        ):
            gated(
                "mfu-wave3:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary", "--timeout-budget", "900"]
                + extra,
                1020,
            )

    # 6c. Second-wave sweep: remaining unmeasured points — batch 48 (does
    # throughput keep falling past 32?) and the 8k preset under the remat
    # policies that won at 1k context.
    if on("sweep2"):
        # Measured 2026-07-31: save_qkv_attn/b24 0.3964, /b32 0.3928 (loses
        # to save_attn 0.4059 — saving more residuals costs more HBM than
        # the recompute it avoids). --block-q 512 --block-kv 512 at T=1024
        # HUNG the chip (killed at 700s; same Mosaic-class wedge as
        # save_attn+fused) — block overrides are now excluded from
        # campaigns; the auto block size stands.
        for extra in (
            ["--remat", "save_attn", "--batch", "48"],
            # 8k comparison arms. The preset default became save_attn on
            # 2026-08-01 (same-day measured 24.2% vs dots_saveable 23.9%),
            # so the plain ctx8k stage now measures save_attn; these arms
            # keep the ALTERNATIVE policies in the series.
            ["--preset", "gpt2-8k-sp", "--remat", "dots_saveable"],
            ["--preset", "gpt2-8k-sp", "--remat", "save_big"],
        ):
            gated(
                "sweep2:" + "/".join(extra).replace("--", ""),
                [py, BENCH, "--skip-canary", "--timeout-budget", "900"] + extra,
                1020,
            )

    # 7. Decode throughput: dense bucketed + ragged serving shape (the
    # cached-decode path is proven on this backend; int8-KV is NOT — it is
    # its own risky stage below).
    if on("decode"):
        # The model default became decode_cache_layout='unstacked' on
        # 2026-08-01 after its clean on-chip proof (6,856 vs 4,129 tok/s)
        # — these default stages now measure it (metric suffix
        # '_unstacked'); the explicit stacked arm keeps the historical
        # unsuffixed series alive as the comparison baseline.
        gated("decode", [py, BENCH, "--skip-canary", "--mode", "decode"], 900)
        gated(
            "decode-stacked",
            [py, BENCH, "--skip-canary", "--mode", "decode",
             "--cache-layout", "stacked"], 900,
        )
        gated(
            "decode-ragged",
            [py, BENCH, "--skip-canary", "--mode", "decode", "--ragged"], 900,
        )

    # 8. 8k context on one chip (flash; the SP mesh needs multi-chip).
    if on("ctx8k"):
        gated(
            "ctx8k",
            [py, BENCH, "--skip-canary", "--preset", "gpt2-8k-sp",
             "--timeout-budget", "1200"],
            1320,
        )

    # 8a. GQA long-context arm (VERDICT r4 #7): at 8k the flash kernel's
    # K/V streaming is the wall; G=4 (12 query heads over 3 KV heads)
    # quarters those bytes inside the PROVEN kernel class (GQA flash is
    # gradient-tested; auto block size — block overrides stay excluded as
    # a wedge class). Target: >28% vs the 24.2% full-head record, or a
    # recorded refutation. The b12 arm spends the freed KV memory on
    # batch; the 16k arm re-measures the flagged 4.7% b4 anomaly under
    # GQA-adjacent conditions.
    if on("ctx8k-gqa"):
        for extra in (
            [],
            ["--batch", "12"],
            ["--remat", "dots_saveable"],
            ["--context", "16384", "--batch", "4"],
        ):
            gated(
                "ctx8k-gqa" + ("/" + "/".join(extra).replace("--", "")
                               if extra else ""),
                [py, BENCH, "--skip-canary", "--preset", "gpt2-8k-gqa",
                 "--timeout-budget", "1200"] + extra,
                1320,
            )

    # 8a'. 16k-context probe (2026-08-01): the 8k preset's RoPE
    # extrapolates; --context 16384 doubles the sequence on one chip
    # (flash auto-block is the proven kernel class; the grid just grows).
    # Distinct metric series mfu_gpt2-8k-sp_train_ctx16384.
    if on("ctx16k"):
        for ctx, batch in ((16384, 2), (16384, 4), (32768, 1)):
            gated(
                f"ctx16k/c{ctx}/b{batch}",
                [py, BENCH, "--skip-canary", "--preset", "gpt2-8k-sp",
                 "--context", str(ctx), "--batch", str(batch),
                 "--timeout-budget", "1200"],
                1320,
            )

    # 8b. Trainer-loop overlap: prefetch 0 vs 2 (VERDICT r2 #8 number).
    # 60 steps, not 20: the timed window holds 2 log-boundary pipeline
    # drains (~1 step latency each) regardless of length — at 20 steps
    # that's ~10% phantom "loop overhead", at 60 it is ~3%.
    # --batch 24 is PINNED (ADVICE r3 low #3): the banked prefetch series
    # (BASELINE.md trainer-loop table) was measured at batch 24; bench.py's
    # train default later moved to 16, and an unpinned stage would silently
    # extend the series with incomparable points.
    if on("trainer"):
        for depth in (0, 2):
            gated(
                f"trainer-prefetch{depth}",
                [py, BENCH, "--skip-canary", "--mode", "trainer", "--batch",
                 "24", "--prefetch", str(depth), "--steps", "60"],
                1020,
            )

    # 8c. r5 serving A/B (VERDICT r4 #2, the 8x gap): the pipelined
    # scheduler (batched admission prefill + double-buffered dispatch —
    # window k+1 enqueued before window k's readback) against the r4
    # synchronous baseline, SAME SESSION. Device programs are the proven
    # r4 classes (decode window scan + prefill/scatter; the batched
    # prefill is the same op family at batch > 1) — gated tier. Bar:
    # sps32 pipelined >= 2x the r4 904-918 tok/s record.
    if on("serving-ab"):
        for name, extra in (
            ("pipe-sps32", ["--steps-per-sched", "32"]),
            ("sync-sps32", ["--steps-per-sched", "32", "--no-pipeline"]),
            ("pipe-sps8", ["--steps-per-sched", "8"]),
            ("pipe-sps64", ["--steps-per-sched", "64"]),
        ):
            gated(
                f"serving-ab:{name}",
                [py, BENCH, "--skip-canary", "--mode", "serving"] + extra,
                1200,
            )

    # 8d. Mid-campaign bank refresh (VERDICT r4 #8): the gated tier above
    # can take hours; re-race the default config under CURRENT conditions
    # before the risky tier starts (whose probes can wedge the chip and
    # end the session) so last_banked is never older than the last safe
    # moment.
    if on("mfu-refresh"):
        gated(
            "mfu-refresh-mid",
            [py, BENCH, "--skip-canary", "--quick",
             "--timeout-budget", "600"],
            720,
        )

    # --- RISKY TIER from here down: unproven kernel-config classes, run
    # only after mfu + parity-tpu + e2e are banked (see module docstring).

    # 9a. Op-level trace at the measured-best config: the ground truth for
    # what to attack next (prints the top HLO ops by self time). The
    # profiler has never run on this backend — risky.
    if on("profile"):
        risky(
            "profile",
            [py, os.path.join(REPO, "scripts", "profile_capture.py"),
             "--preset", "gpt2-124m", "--batch", "16",
             "--remat", "save_attn", "--top", "40"],
            900,
        )
    # 9b. Serving-side ground truth: the decode step is ~7x off the weight-
    # read memory bound (2.08 ms/step vs ~0.3 theoretical) — find out
    # where those milliseconds go. (profile_capture now derives a
    # decode-specific --out itself and refuses to parse stale xplanes.)
    if on("profile-decode"):
        risky(
            "profile-decode",
            [py, os.path.join(REPO, "scripts", "profile_capture.py"),
             "--preset", "gpt2-124m", "--batch", "8", "--mode", "decode",
             "--steps", "2", "--top", "40"],
            900,
        )

    # 9c. int8-KV decode: the quantized cache kernel path has only CPU
    # evidence — an unproven class on this backend.
    if on("decode-int8"):
        risky(
            "decode-int8",
            [py, BENCH, "--skip-canary", "--mode", "decode",
             "--kv-dtype", "int8"], 900,
        )

    # 9c'. Decode with the depth scan fully unrolled: removes the inner
    # while loop whose boundary copies the whole KV cache every decode
    # step (AOT HLO: 4 cache-shaped copies/step -> 0 at gpt2-124m b8;
    # decode roofline hypothesis 1). Scan-unroll is an unproven compile
    # class on this backend — risky tier.
    if on("decode-unroll"):
        risky(
            "decode-unroll",
            [py, BENCH, "--skip-canary", "--mode", "decode",
             "--cache-layout", "stacked", "--decode-unroll"], 900,
        )

    # 9d. Layer-scan unroll at the winning config: unrolling trades
    # compile time + code size for cross-layer scheduling freedom — a
    # compile class never exercised on this backend.
    if on("unroll-sweep"):
        for unroll in (2, 4):
            risky(
                f"unroll:{unroll}",
                [py, BENCH, "--skip-canary", "--remat", "save_attn",
                 "--batch", "16", "--unroll", str(unroll),
                 "--timeout-budget", "700"],
                820,
            )

    # 9f. Continuous-batching serving throughput (paged engine, r4): pool
    # gather/scatter decode is a program class never compiled on this
    # backend — risky tier. sps=1 quantifies what multi-step scheduling
    # buys against the tunnel's per-dispatch latency.
    if on("serving"):
        risky(
            "serving",
            [py, BENCH, "--skip-canary", "--mode", "serving"], 1200,
        )
        risky(
            "serving-sps1",
            [py, BENCH, "--skip-canary", "--mode", "serving",
             "--steps-per-sched", "1"], 1200,
        )
        # Window-boundary host work measured ~134 ms at sps=8 (2026-08-01:
        # 96 windows over 12.9s, in-window compute ~16 ms) — the tunnel
        # round-trips dominate, so a larger window should multiply
        # throughput until reap-latency waste catches up.
        risky(
            "serving-sps32",
            [py, BENCH, "--skip-canary", "--mode", "serving",
             "--steps-per-sched", "32"], 1200,
        )

    # 9f''. Pallas paged-attention kernel (VERDICT r4 #3): gather-free
    # block-table decode. A NEW Mosaic kernel class on this backend —
    # risky tier unconditionally (the fused-CE precedent: interpret-clean
    # kernels can still wedge the chip). Same-session A/B against the
    # gather arm above.
    if on("serving-kernel"):
        risky(
            "serving-kernel:sps32",
            [py, BENCH, "--skip-canary", "--mode", "serving",
             "--steps-per-sched", "32", "--paged-attn", "kernel"], 1200,
        )

    # 9f'''. Speculative serving (VERDICT r4 #6): self-draft upper bound
    # (acceptance ~100% at greedy — measures the dispatch-amortization
    # ceiling; a real deployment brings a trained draft). Multi-token
    # paged verify is a new program shape (same XLA op family as the
    # proven gather path) — risky tier until first banked.
    if on("serving-spec"):
        for k in (4, 8):
            risky(
                f"serving-spec:k{k}",
                [py, BENCH, "--skip-canary", "--mode", "serving",
                 "--spec-draft", "self", "--spec-k", str(k)], 1200,
            )
        # Spec + the Pallas kernel: draft steps run the single-token
        # kernel, the verify the multi-token form — the same Mosaic class
        # as serving-kernel, so this arm runs ONLY once THIS campaign
        # banked a clean (rc==0, unwedged) serving-kernel record (a
        # wedge, a stale prior-round success, or absence must not
        # re-probe the class; enforced here, not by stage ordering).
        if _stage_proven_this_campaign(args.out, "serving-kernel"):
            risky(
                "serving-spec:k4-kernel",
                [py, BENCH, "--skip-canary", "--mode", "serving",
                 "--spec-draft", "self", "--spec-k", "4",
                 "--paged-attn", "kernel"], 1200,
            )
        else:
            rec = {"stage": "serving-spec:k4-kernel", "skipped": True,
                   "risk": "unproven",
                   "error": "deferred: no clean serving-kernel record "
                            "banked in this campaign (kernel class "
                            "unproven or wedged)"}
            with open(args.out, "a") as _f:
                _f.write(json.dumps(rec) + "\n")
            print("[capture] serving-spec:k4-kernel deferred (kernel class "
                  "not proven in this log)", flush=True)

    # 9e. The rest of the grid — RISKY (open-ended combos).
    if on("sweep-full"):
        risky(
            "sweep-full",
            [py, os.path.join(REPO, "scripts", "perf_sweep.py"),
             "--budget", "600"],
            3600 * 4,
        )

    # 10. LAST: bank-freshness refresh (VERDICT r4 #8). Hours of sweeps
    # and risky probes can separate the morning's champion from round
    # close; this final quick race re-measures the default config under
    # CURRENT backend conditions so bench.py's `last_banked` fallback is
    # never stale — the driver's round-end record either goes live or
    # carries a same-session number. Gated (proven class); targeted
    # --stages runs already refresh the log via their own mfu records.
    if on("mfu-refresh"):
        gated(
            "mfu-refresh",
            [py, BENCH, "--skip-canary", "--quick",
             "--timeout-budget", "600"],
            720,
        )


if __name__ == "__main__":
    sys.exit(main())
