#!/usr/bin/env python
"""One-shot TPU measurement campaign: run EVERYTHING the round needs the
moment hardware is reachable.

The axon TPU tunnel has been intermittent across rounds; when it comes back
there may be a short window. This script runs the full capture sequence in
priority order, each stage a bounded subprocess, appending structured
results to a JSONL log as they land — a partial window still banks the
most important numbers first.

Stages (priority order):
  1. canary        — environment probe (bench.py --_canary); abort if dead
  2. mfu           — the driver metric: bench.py default race (gpt2-124m)
  3. sweep-top     — the 4 most promising perf-sweep configs
  4. decode        — KV-cached decode throughput (+ ragged serving shape)
  5. ctx8k         — single-chip flash at 8k (gpt2-8k-sp)
  6. trainer       — full Trainer loop, prefetch 0 vs 2 (overlap win)
  7. parity-tpu    — scripts/parity_experiment.py with pinned matmul
                     precision (the BASELINE.md promised TPU rerun)
  8. sweep-full    — the remaining perf-sweep grid

Usage:
  python scripts/tpu_capture.py                 # full campaign
  python scripts/tpu_capture.py --stages mfu,decode
  python scripts/tpu_capture.py --out /tmp/capture.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def run_cmd(name: str, cmd: list, timeout: float, out_f) -> dict:
    """Run one stage; parse its last stdout line as JSON when possible."""
    t0 = time.time()
    print(f"[capture] {name}: {' '.join(cmd[1:])}", flush=True)
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, timeout=timeout,
            text=True, cwd=REPO,
        )
        lines = [ln for ln in (proc.stdout or "").splitlines() if ln.strip()]
        try:
            payload = json.loads(lines[-1]) if lines else {}
        except json.JSONDecodeError:
            payload = {"raw": lines[-1][:400] if lines else ""}
        rec = {"stage": name, "rc": proc.returncode, **payload}
    except subprocess.TimeoutExpired:
        rec = {"stage": name, "rc": -1, "error": f"stage hung past {timeout:.0f}s"}
    rec["wall_s"] = round(time.time() - t0, 1)
    out_f.write(json.dumps(rec) + "\n")
    out_f.flush()
    print(f"[capture] {name} -> {json.dumps(rec)[:300]}", flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "tpu_capture.jsonl"))
    ap.add_argument("--stages", default="", help="comma list; empty = all")
    ap.add_argument("--mfu-budget", type=float, default=2400.0)
    args = ap.parse_args()
    KNOWN = {
        "mfu", "sweep-top", "decode", "ctx8k", "trainer", "parity-tpu",
        "sweep-full",
    }
    want = None
    if args.stages:
        want = {s.strip() for s in args.stages.split(",") if s.strip()}
        unknown = want - KNOWN
        if unknown:
            # Fail FAST and loud: a typo that silently ran only the canary
            # would waste the (possibly brief) hardware window this script
            # exists to exploit.
            ap.error(
                f"unknown stage(s) {sorted(unknown)}; known: {sorted(KNOWN)}"
            )

    def on(stage: str) -> bool:
        return want is None or stage in want

    py = sys.executable
    with open(args.out, "a") as f:
        f.write(json.dumps({"stage": "campaign-start", "ts": time.time()}) + "\n")

        # 1. Environment canary: no point burning budgets on a dead tunnel.
        rec = run_cmd("canary", [py, BENCH, "--_canary"], 180, f)
        if rec.get("rc") != 0 or not rec.get("ok"):
            print("[capture] backend unreachable; aborting campaign", flush=True)
            return 1

        # 2. The driver metric (races remat candidates incl. safe tail).
        if on("mfu"):
            run_cmd(
                "mfu",
                [py, BENCH, "--skip-canary",
                 "--timeout-budget", str(args.mfu_budget)],
                args.mfu_budget + 120, f,
            )

        # 3. Most promising sweep points first (fused CE is the untested
        # lever; batch 24 is the measured-best round-1 batch).
        if on("sweep-top"):
            for remat, ce, batch in (
                ("save_big", "fused", 24), ("save_attn", "fused", 24),
                ("save_big", "chunked", 32), ("save_attn", "chunked", 16),
            ):
                run_cmd(
                    f"sweep:{remat}/{ce}/b{batch}",
                    [py, BENCH, "--skip-canary", "--remat", remat, "--ce", ce,
                     "--batch", str(batch), "--timeout-budget", "900"],
                    1020, f,
                )

        # 4. Decode throughput: dense bucketed + ragged serving shape.
        if on("decode"):
            run_cmd("decode", [py, BENCH, "--skip-canary", "--mode", "decode"], 900, f)
            run_cmd(
                "decode-ragged",
                [py, BENCH, "--skip-canary", "--mode", "decode", "--ragged"], 900, f,
            )

        # 5. 8k context on one chip (flash; the SP mesh needs multi-chip).
        if on("ctx8k"):
            run_cmd(
                "ctx8k",
                [py, BENCH, "--skip-canary", "--preset", "gpt2-8k-sp",
                 "--timeout-budget", "1200"],
                1320, f,
            )

        # 6. Trainer-loop overlap: prefetch 0 vs 2 (VERDICT r2 #8 number).
        if on("trainer"):
            for depth in (0, 2):
                run_cmd(
                    f"trainer-prefetch{depth}",
                    [py, BENCH, "--skip-canary", "--mode", "trainer",
                     "--prefetch", str(depth), "--steps", "20"],
                    1020, f,
                )

        # 7. TPU-side parity (the script pins jax_default_matmul_precision=
        # "highest" itself — BASELINE.md:60-63's promised rerun). The torch
        # side runs on host CPU; --only jax reuses the recorded torch curve.
        if on("parity-tpu"):
            run_cmd(
                "parity-tpu",
                [py, os.path.join(REPO, "scripts", "parity_experiment.py"),
                 "--steps", "300", "--only", "jax"],
                3600, f,
            )

        # 8. The rest of the grid.
        if on("sweep-full"):
            run_cmd(
                "sweep-full",
                [py, os.path.join(REPO, "scripts", "perf_sweep.py"),
                 "--budget", "600"],
                3600 * 4, f,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
