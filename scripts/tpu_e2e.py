#!/usr/bin/env python
"""End-to-end real-corpus training exercise on live hardware.

Drives the full operational story the unit suite can only simulate on the
virtual CPU mesh — on the real chip, through the real CLIs:

  1. `scripts/train.py` pretrains a small byte-level model on REAL text
     (the parity harness's harvested-prose corpus, data/parity/train.bin),
     checkpointing on an interval.
  2. Mid-run the harness delivers SIGTERM (cloud-preemption shape); the
     trainer must save a preemption checkpoint at the next log boundary and
     exit cleanly (trainer.py preemption path, VERDICT r2 #3).
  3. A second `scripts/train.py` invocation RESUMES from that checkpoint
     (same command line — resume is the default) and trains to completion.
  4. `scripts/evaluate.py` loads the final checkpoint and reports val loss.
  5. `scripts/generate_text.py` decodes from the final checkpoint — the
     trained model must SERVE, completing the reference user journey.

Emits ONE JSON line: preemption step, resume step, final/eval losses, and
pass/fail checks (resumed from the preemption checkpoint; loss fell vs
init ln(256); eval loss finite and sane; the final checkpoint decodes
tokens through generate_text). Exit 0 iff every check passes.

Usage:  python scripts/tpu_e2e.py [--steps 300] [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PARITY = os.path.join(REPO, "data", "parity")


def wait_for_step(metrics_path: str, step: int, timeout: float) -> bool:
    """Poll the run's metrics JSONL until a `step >= step` record lands."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        if os.path.exists(metrics_path):
            try:
                with open(metrics_path) as f:
                    for ln in f:
                        rec = json.loads(ln)
                        if rec.get("step", -1) >= step and "loss" in rec:
                            return True
            except (json.JSONDecodeError, OSError):
                pass  # mid-write line; retry
        time.sleep(0.5)
    return False


def read_metrics(metrics_path: str) -> list:
    out = []
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            for ln in f:
                try:
                    out.append(json.loads(ln))
                except json.JSONDecodeError:
                    pass
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300,
                    help="steps the RESUMED run trains past the preemption "
                    "checkpoint (phase 2 total = preempted_step + steps)")
    ap.add_argument("--preempt-at", type=int, default=0,
                    help="send SIGTERM once this step is logged (0 = 50)")
    ap.add_argument("--out-dir", default="",
                    help="work dir for checkpoints/metrics (default: tmp)")
    ap.add_argument("--phase-timeout", type=float, default=600.0)
    args = ap.parse_args()

    train_bin = os.path.join(PARITY, "train.bin")
    val_bin = os.path.join(PARITY, "val.bin")
    if not os.path.exists(train_bin):
        print(json.dumps({"error": f"no real-text corpus at {train_bin}; run "
                          "scripts/parity_experiment.py once to build it"}))
        return 1

    work = args.out_dir or tempfile.mkdtemp(prefix="tpu_e2e_")
    os.makedirs(work, exist_ok=True)
    ckpt_dir = os.path.join(work, "ckpt")
    metrics = os.path.join(work, "metrics.jsonl")
    preempt_at = args.preempt_at or 50

    # Byte-level model sized to make this a real (but fast) TPU run: the
    # corpus is uint16 byte tokens, vocab 256. Checkpoint every 50 so the
    # preemption save and the interval save both get exercised.
    def train_cmd(steps: int) -> list:
        return [
            sys.executable, os.path.join(REPO, "scripts", "train.py"),
            "--preset", "tiny",
            "--steps", str(steps),
            "--override",
            "model.d_model=256", "model.n_layers=4", "model.n_heads=8",
            "model.context_length=256",
            f"data.train_path={train_bin}", f"data.val_path={val_bin}",
            f"train.train_steps={steps}",
            "train.batch_size=16", "train.checkpoint_interval=50",
            "train.eval_interval=0", "train.log_interval=10",
            "train.lr=1e-3", "train.seed=7",
            f"train.checkpoint_dir={ckpt_dir}",
            f"train.metrics_path={metrics}",
        ]

    result: dict = {"preempt_at": preempt_at, "work": work}

    # --- Phase 1: train until preempt_at, then SIGTERM -----------------
    # Phase 1's step budget is effectively unbounded: on a fast backend the
    # whole nominal run can finish between two 0.5s metric polls, which
    # would make every preemption check spuriously fail. With a huge budget
    # SIGTERM always lands mid-run; phase 2's target is computed from the
    # step the preemption checkpoint actually recorded.
    err1 = open(os.path.join(work, "phase1.stderr"), "w")
    p1 = subprocess.Popen(train_cmd(1_000_000), stdout=err1,
                          stderr=subprocess.STDOUT, cwd=REPO)
    try:
        if not wait_for_step(metrics, preempt_at, args.phase_timeout):
            print(json.dumps({**result, "error":
                              f"phase1: step {preempt_at} never logged "
                              f"(see {work}/phase1.stderr)"}))
            return 1
        p1.send_signal(signal.SIGTERM)
        rc1 = p1.wait(timeout=args.phase_timeout)
    except subprocess.TimeoutExpired:
        print(json.dumps({**result, "error": "phase1: hung after SIGTERM"}))
        return 1
    finally:
        # The unbounded-step child must NEVER outlive this harness — an
        # orphan would hold the chip indefinitely. Covers every exit path
        # (including an outer SIGTERM raising through the waits above).
        if p1.poll() is None:
            p1.kill()
            p1.wait()
        err1.close()
    recs = read_metrics(metrics)
    preempt_recs = [r for r in recs if r.get("event") == "preempted"]
    ckpts = sorted(
        int(d.split("-")[1]) for d in os.listdir(ckpt_dir)
        if d.startswith("step-")) if os.path.isdir(ckpt_dir) else []
    result.update({
        "phase1_rc": rc1,
        "preempted_step": preempt_recs[-1]["step"] if preempt_recs else None,
        "ckpts_after_preempt": ckpts,
    })
    if result["preempted_step"] is None:
        print(json.dumps({**result, "error":
                          "phase1: no preemption event recorded "
                          f"(see {work}/phase1.stderr)"}))
        return 1

    # --- Phase 2: resume from the preemption checkpoint and finish -----
    total_steps = result["preempted_step"] + args.steps
    result["total_steps"] = total_steps
    try:
        with open(os.path.join(work, "phase2.stderr"), "w") as err2:
            rc2 = subprocess.run(train_cmd(total_steps), stdout=err2,
                                 stderr=subprocess.STDOUT, cwd=REPO,
                                 timeout=args.phase_timeout).returncode
    except subprocess.TimeoutExpired:
        print(json.dumps({**result, "error": "phase2: resume run hung"}))
        return 1
    recs = read_metrics(metrics)
    resume_recs = [r for r in recs if r.get("event") == "resumed"]
    step_losses = [r for r in recs if "loss" in r and "step" in r]
    final_ckpts = sorted(
        int(d.split("-")[1]) for d in os.listdir(ckpt_dir)
        if d.startswith("step-")) if os.path.isdir(ckpt_dir) else []
    result.update({
        "phase2_rc": rc2,
        "resumed_from": resume_recs[-1].get("step") if resume_recs else None,
        "final_step": step_losses[-1]["step"] if step_losses else None,
        "first_loss": step_losses[0]["loss"] if step_losses else None,
        "final_loss": step_losses[-1]["loss"] if step_losses else None,
        "ckpts_final": final_ckpts,
    })

    # --- Phase 3: standalone evaluation of the final checkpoint --------
    try:
        ev = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "evaluate.py"),
             "--model_path", ckpt_dir, "--data", val_bin, "--iters", "20"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, cwd=REPO,
            timeout=args.phase_timeout, text=True)
        eval_lines = [ln for ln in ev.stdout.splitlines() if ln.strip()]
        eval_rec = {}
        for ln in reversed(eval_lines):
            try:
                eval_rec = json.loads(ln)
                break
            except json.JSONDecodeError:
                continue
        result["eval"] = eval_rec
    except subprocess.TimeoutExpired:
        print(json.dumps({**result, "error": "phase3: evaluate hung"}))
        return 1

    # --- Phase 4: generation from the final checkpoint (the reference
    # user journey ends with generate_text; the operational story must
    # prove the trained checkpoint actually SERVES, not just evaluates) --
    try:
        # stderr goes to its own file, NOT merged: a JAX/absl warning line
        # on the merged stream would satisfy the generated-length check
        # with zero tokens actually decoded.
        with open(os.path.join(work, "phase4.stderr"), "w") as err4:
            gen = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "scripts", "generate_text.py"),
                 "--model_path", ckpt_dir, "--input_text", "The ",
                 "--max_new_tokens", "48", "--temperature", "0"],
                stdout=subprocess.PIPE, stderr=err4, cwd=REPO,
                timeout=args.phase_timeout, text=True)
        # rstrip newlines ONLY: a briefly-trained byte model legitimately
        # greedy-decodes whitespace (spaces are the most common byte), and
        # the check is "decode ran and produced tokens", not text quality.
        gen_out = (gen.stdout or "").rstrip("\r\n")
        result["generate_rc"] = gen.returncode
        result["generated_chars"] = len(gen_out)
        result["generated_tail"] = gen_out[-80:]
    except subprocess.TimeoutExpired:
        print(json.dumps({**result, "error": "phase4: generate hung"}))
        return 1

    # --- Checks --------------------------------------------------------
    import math
    eval_loss = result.get("eval", {}).get("val_loss")
    checks = {
        "phase1_clean_exit": rc1 == 0,
        "preemption_checkpoint_saved": (
            result["preempted_step"] is not None
            and result["preempted_step"] in result["ckpts_after_preempt"]),
        "resumed_from_preemption": (
            result["resumed_from"] == result["preempted_step"]),
        "ran_to_completion": result["final_step"] == total_steps and rc2 == 0,
        "loss_fell": (
            result["final_loss"] is not None
            and result["final_loss"] < math.log(256.0) - 1.0),
        "eval_sane": (
            isinstance(eval_loss, (int, float))
            and eval_loss == eval_loss and eval_loss < math.log(256.0)),
        "generates_text": (
            result.get("generate_rc") == 0
            and result.get("generated_chars", 0) > len("The ")),
    }
    result["checks"] = checks
    result["ok"] = all(checks.values())
    if not args.out_dir and result["ok"]:
        shutil.rmtree(work, ignore_errors=True)
        result["work"] = ""
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
