#!/usr/bin/env python
"""Training entry point.

Mirror of the reference CLI (`/root/reference/scripts/train_transformer.py`),
redesigned: presets + dotted overrides instead of a mutable global dict, JAX
multi-host init instead of torchrun env vars, `--data synthetic` for a
zero-setup smoke run.

Examples:
  python scripts/train.py --preset tiny --data synthetic --override train.train_steps=100
  python scripts/train.py --preset gpt2-124m \
      --override data.train_path=data/train.bin data.val_path=data/val.bin
"""

from __future__ import annotations

import argparse
import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pretraining_llm_tpu.utils.platform import apply_platform_env

apply_platform_env()

from pretraining_llm_tpu.parallel.mesh import initialize_distributed

# Must run before anything touches a device (see mesh.initialize_distributed).
initialize_distributed()

import jax  # noqa: E402

from pretraining_llm_tpu.config import get_preset, list_presets  # noqa: E402
from pretraining_llm_tpu.training.trainer import Trainer  # noqa: E402


def parse_overrides(pairs):
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"override must be key=value, got {pair!r}")
        key, raw = pair.split("=", 1)
        try:
            out[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            out[key] = raw  # plain string
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--preset", default="gpt2-124m", help=f"one of {list_presets()}")
    parser.add_argument(
        "--override", nargs="*", default=[], metavar="SECTION.KEY=VALUE",
        help="dotted config overrides, e.g. train.lr=1e-4",
    )
    parser.add_argument(
        "--data", default="files", choices=["files", "synthetic"],
        help="'synthetic' trains on a generated Markov stream (no files needed)",
    )
    parser.add_argument(
        "--obs-dir", default="", metavar="DIR",
        help="enable run-wide telemetry under DIR: events.jsonl (EventBus), "
        "spans.trace.json (Perfetto), metrics.prom (Prometheus textfile); "
        "analyze offline with scripts/obs_report.py. Explicit obs.* "
        "overrides win over the derived paths",
    )
    parser.add_argument("--no-resume", action="store_true", help="ignore existing checkpoints")
    parser.add_argument("--steps", type=int, default=None, help="override total steps")
    parser.add_argument(
        "--compile-only", action="store_true",
        help="compile the train step, print per-device memory analysis "
        "(size a big config BEFORE burning pod time on an OOM), and exit",
    )
    args = parser.parse_args()

    overrides = parse_overrides(args.override)
    if args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)
        for key, fname in (
            ("obs.events_path", "events.jsonl"),
            ("obs.spans_path", "spans.trace.json"),
            ("obs.prometheus_path", "metrics.prom"),
        ):
            overrides.setdefault(key, os.path.join(args.obs_dir, fname))
    config = get_preset(args.preset).with_overrides(overrides)
    if jax.process_index() == 0:
        print(f"preset={config.name} devices={jax.device_count()} "
              f"params={config.model.num_params()/1e6:.1f}M")
    if args.compile_only:
        compile_only(config)
        return
    trainer = Trainer(config, synthetic_data=(args.data == "synthetic"), resume=not args.no_resume)
    final = trainer.train(steps=args.steps)
    if jax.process_index() == 0:
        print("final:", final, f"exit_reason={trainer.exit_reason}")
    # Return-code contract for scripts/supervisor.py (see resilience/):
    # preemption means "checkpointed, relaunch me"; an exhausted rollback
    # budget means "systemic anomaly, stop relaunching". EXIT_WEDGED is
    # raised by the watchdog itself via os._exit.
    from pretraining_llm_tpu.resilience import EXIT_ANOMALY, EXIT_PREEMPTED

    rc = {
        "preempted": EXIT_PREEMPTED,
        "anomaly_budget": EXIT_ANOMALY,
        "anomaly_no_checkpoint": EXIT_ANOMALY,
    }.get(trainer.exit_reason, 0)
    if rc:
        sys.exit(rc)


def compile_only(config) -> None:
    """AOT-compile the exact training program from shape specs only — no
    params materialize, no data loads — and report XLA's per-device memory
    breakdown (donated/aliased state buffers counted once)."""
    import json as _json
    import time as _time

    from pretraining_llm_tpu.parallel.mesh import build_mesh, needs_mesh
    from pretraining_llm_tpu.training import train_step as ts

    mesh = build_mesh(config.mesh) if needs_mesh(config.mesh) else None
    t0 = _time.time()
    compiled = ts.lower_train_step(config, mesh).compile()
    dt = _time.time() - t0
    mem = compiled.memory_analysis()
    gib = 2**30
    alias = getattr(mem, "alias_size_in_bytes", 0)
    report = {
        "compile_s": round(dt, 1),
        "devices": jax.device_count(),
        "per_device_GiB": {
            "arguments": round(mem.argument_size_in_bytes / gib, 3),
            "outputs": round(mem.output_size_in_bytes / gib, 3),
            "aliased (donated state, counted once)": round(alias / gib, 3),
            "temps": round(mem.temp_size_in_bytes / gib, 3),
            "total_peak_estimate": round(
                (mem.argument_size_in_bytes + mem.output_size_in_bytes
                 + mem.temp_size_in_bytes - alias) / gib, 3,
            ),
        },
    }
    if jax.process_index() == 0:
        print(_json.dumps(report))


if __name__ == "__main__":
    main()
