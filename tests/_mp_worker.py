"""Subprocess worker for the 2-process distributed checkpoint/resume test.

Each worker is one "host" of a 2-process jax.distributed run on the CPU
backend (2 local devices -> 4 global devices), exercising the real multi-host
code paths the reference never tested (its DDP launch at
/root/reference/scripts/train_transformer.py:15-29 shipped broken — SURVEY §A):
cross-host mesh construction, `make_array_from_process_local_data` batch
assembly, all-process checkpoint save with internal barriers, and per-process
data-RNG resume.

Modes:
  straight  train 6 steps in one run
  part1     train 3 steps (periodic checkpoint lands at step 3), exit = "kill"
  part2     resume from the step-3 checkpoint, train to step 6
  preempt   SIGTERM lands on process 1 ONLY mid-run; the stop flag syncs at
            the next log boundary so BOTH processes enter the collective
            checkpoint save together and stop at the same step (the
            asymmetric-signal case that deadlocks naive handlers)

The final-step loss of part2 must bit-exactly equal straight's.
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # Installed JAX predates the jax_num_cpu_devices config knob. The backend
    # is still uninitialized here, so the XLA flag (read at backend init)
    # produces the same 2 local virtual CPU devices.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=2"
        ).strip()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--mode", choices=["straight", "part1", "part2", "preempt"], required=True
    )
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--workdir", required=True)
    args = ap.parse_args()

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{args.port}",
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes
    assert jax.device_count() == 2 * args.num_processes

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.training.trainer import Trainer

    cfg = get_preset("tiny")
    cfg = cfg.replace(
        train=dataclasses.replace(
            cfg.train,
            batch_size=8,
            train_steps=6,
            checkpoint_interval=3,
            checkpoint_dir=os.path.join(args.workdir, "ckpt"),
            eval_interval=0,
            log_interval=1,
            metrics_path="",
        )
    )
    steps = {"straight": 6, "part1": 3, "part2": 6, "preempt": 20}[args.mode]
    if args.mode == "preempt":
        cfg = cfg.replace(
            train=dataclasses.replace(
                cfg.train, train_steps=20, checkpoint_interval=0, log_interval=2
            )
        )
    trainer = Trainer(cfg, synthetic_data=True, resume=True)
    if args.mode == "part2":
        assert trainer.start_step == 3, f"expected resume from step 3, got {trainer.start_step}"
    if args.mode == "preempt" and args.process_id == 1:
        # Asymmetric preemption: only THIS host gets the signal; the stop
        # must still be collective (flag synced at log boundaries).
        import signal

        real_iter = trainer.train_iterator

        class SelfSigterm:
            def __init__(self):
                self.n = 0

            def __iter__(self):
                return self

            def __next__(self):
                self.n += 1
                if self.n == 5:
                    os.kill(os.getpid(), signal.SIGTERM)
                return next(real_iter)

            def state(self):  # keep the data-RNG sidecar flowing
                return real_iter.state()

        trainer.train_iterator = SelfSigterm()

    # Record the steps THIS process actually checkpointed at — a per-process
    # signal (the shared checkpoint dir can't distinguish divergent saves).
    saved_steps = []
    orig_save = trainer.save

    def recording_save(step, **kw):
        saved_steps.append(int(step))
        return orig_save(step, **kw)

    trainer.save = recording_save
    last = trainer.train(steps=steps)

    out = {
        "mode": args.mode,
        "process": args.process_id,
        "start_step": trainer.start_step,
        # preempt stops before a log boundary ever fills `last`; all other
        # modes must still crash loudly if the loss metric goes missing.
        "loss": last.get("loss") if args.mode == "preempt" else last["loss"],
        "saved_steps": saved_steps,
    }
    path = os.path.join(args.workdir, f"result.{args.mode}.p{args.process_id}.json")
    with open(path, "w") as f:
        json.dump(out, f)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
