"""Test harness: force an 8-device virtual CPU backend.

This is the fake-distributed-backend the reference lacks entirely (SURVEY §4):
every mesh/pjit/psum/ring-attention test runs against 8 virtual CPU devices,
so multi-chip semantics are exercised without TPU hardware.

jax is pre-imported by the environment's sitecustomize with a TPU backend
registered, but backends initialize lazily — flipping the platform config here
(before any test touches a device) is sufficient.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Installed JAX predates the jax_num_cpu_devices config knob. Backends
    # initialize lazily and nothing has touched a device yet, so the XLA
    # flag (read at backend init) produces the same 8 virtual CPU devices.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import numpy as np
import pytest
from jax.sharding import Mesh


AXES = ("data", "fsdp", "tensor", "seq", "expert", "pipe")


@pytest.fixture(scope="session")
def mesh8() -> Mesh:
    """2 data x 2 fsdp x 2 tensor mesh over the 8 virtual devices."""
    devs = np.asarray(jax.devices()).reshape(2, 2, 2, 1, 1, 1)
    return Mesh(devs, AXES)


@pytest.fixture(scope="session")
def mesh_seq4() -> Mesh:
    """2 data x 4 seq mesh for ring-attention tests."""
    devs = np.asarray(jax.devices()).reshape(2, 1, 1, 4, 1, 1)
    return Mesh(devs, AXES)


@pytest.fixture(scope="session")
def mesh_exp4() -> Mesh:
    """2 data x 4 expert mesh for MoE expert-parallel tests."""
    devs = np.asarray(jax.devices()).reshape(2, 1, 1, 1, 4, 1)
    return Mesh(devs, AXES)


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_per_module():
    """Bound the XLA CPU client's native-state accumulation.

    A full-suite run compiles many hundreds of executables into ONE
    process; twice (2026-08-02) the run segfaulted INSIDE XLA's
    backend_compile ~430 tests deep (main-thread stack in
    jax/_src/compiler.py backend_compile_and_load — not reproducible on
    any module in isolation, i.e. a native accumulation effect, not a
    test bug). Dropping the compiled-executable caches at each module
    boundary keeps within-module reuse (fixtures' jitted fns stay hot
    across a module's tests) while releasing the native executables of
    every previous module."""
    yield
    jax.clear_caches()
