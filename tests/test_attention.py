"""Attention ops: naive vs blockwise/flash numerics, masking semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.ops.attention import multihead_attention, naive_attention
from pretraining_llm_tpu.ops.flash_attention import blockwise_attention
from pretraining_llm_tpu.utils import jax_compat


def _qkv(key, b=2, t=64, h=4, dh=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, dh), dtype) for k in ks)


def test_naive_matches_explicit_softmax():
    q, k, v = _qkv(jax.random.key(0))
    out = naive_attention(q, k, v)
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    t = q.shape[1]
    mask = np.tril(np.ones((t, t), bool))
    scores = np.where(mask[None, None], scores, -np.inf)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_kv", [(16, 16), (32, 8), (8, 32), (64, 64)])
def test_blockwise_matches_naive(causal, block_q, block_kv):
    q, k, v = _qkv(jax.random.key(1))
    want = naive_attention(q, k, v, causal=causal)
    got = blockwise_attention(q, k, v, causal=causal, block_q=block_q, block_kv=block_kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_blockwise_gradients_match_naive():
    q, k, v = _qkv(jax.random.key(2), t=32)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v) ** 2)

    def loss_block(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, block_q=8, block_kv=8) ** 2)

    g1 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_dispatch_via_multihead():
    q, k, v = _qkv(jax.random.key(3))
    want = multihead_attention(q, k, v, impl="naive")
    got = multihead_attention(q, k, v, impl="flash", block_q=16, block_kv=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_flash_heads_major_matches_default():
    """The heads-major flash entry (operands (B, H|G, T, D)) == the
    default layout, values and grads — through the multihead dispatch and
    end-to-end through a model forward with flash_heads_major=True."""
    q, k, v = _qkv(jax.random.key(6))
    want = multihead_attention(q, k, v, impl="flash", block_q=16, block_kv=16)
    got = multihead_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), impl="flash", block_q=16, block_kv=16,
        heads_major=True,
    )
    np.testing.assert_allclose(
        np.asarray(got.transpose(0, 2, 1, 3)), np.asarray(want),
        rtol=1e-5, atol=1e-5,
    )
    with pytest.raises(ValueError, match="flash TRAINING"):
        multihead_attention(q, k, v, impl="naive", heads_major=True)

    import dataclasses

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.models import transformer

    base = dataclasses.replace(
        get_preset("tiny").model, compute_dtype="float32",
        attention_impl="flash",
    )
    hm_cfg = dataclasses.replace(base, flash_heads_major=True)
    params = transformer.init_params(base, jax.random.key(0))
    tok = jax.random.randint(jax.random.key(1), (2, 32), 0, base.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)

    def loss(cfg_):
        return transformer.loss_fn(params, tok, tgt, cfg_)

    l0, l1 = float(loss(base)), float(loss(hm_cfg))
    np.testing.assert_allclose(l1, l0, rtol=1e-6)
    g0 = jax.grad(lambda p: transformer.loss_fn(p, tok, tgt, base))(params)
    g1 = jax.grad(lambda p: transformer.loss_fn(p, tok, tgt, hm_cfg))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        g0, g1,
    )


def test_kv_cache_masking_matches_full_context():
    """Decode semantics: attending over a padded cache == attending the prefix."""
    b, t, h, dh = 1, 16, 2, 8
    q, k, v = _qkv(jax.random.key(4), b=b, t=t, h=h, dh=dh)
    full = naive_attention(q, k, v)
    # Simulate cache of capacity 32 holding only t valid entries.
    pad = 32 - t
    k_pad = jnp.concatenate([k, jnp.ones((b, pad, h, dh))], axis=1)
    v_pad = jnp.concatenate([v, jnp.ones((b, pad, h, dh))], axis=1)
    kv_mask = (jnp.arange(32) < t)[None, :]
    cached = naive_attention(
        q,
        k_pad,
        v_pad,
        q_positions=jnp.arange(t),
        kv_positions=jnp.arange(32),
        kv_mask=kv_mask,
    )
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full), rtol=1e-5, atol=1e-5)


def test_bf16_inputs_fp32_softmax():
    q, k, v = _qkv(jax.random.key(5), dtype=jnp.bfloat16)
    out = naive_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = naive_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-2, atol=2e-2
    )


def test_shard_mapped_flash_kernel_matches_dense(mesh8):
    """The pallas kernel wrapped per-shard over (data, fsdp, tensor) ==
    dense attention — and incompatible layouts return None (fallback)."""
    import functools

    from pretraining_llm_tpu.ops.flash_attention import shard_mapped_kernel
    from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention

    b, t, h, dh = 4, 32, 4, 8
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (b, t, h, dh), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, h, dh), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, h, dh), jnp.float32)
    kernel = functools.partial(
        pallas_flash_attention, causal=True, block_q=16, block_kv=16,
        interpret=True,
    )
    got = jax.jit(
        lambda q, k, v: shard_mapped_kernel(kernel, q, k, v, mesh8)
    )(q, k, v)
    want = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    # Head count not divisible by the tensor axis -> None (caller falls back).
    q3 = q[:, :, :3]
    assert shard_mapped_kernel(kernel, q3, k[:, :, :3], v[:, :, :3], mesh8) is None


@pytest.mark.skipif(
    not jax_compat._HAS_MODERN_SHARD_MAP,
    reason="partial-manual shard_map regions need jax.shard_map (>=0.6); the "
    "legacy fallback lowers them through PartitionId, which XLA aborts on",
)
def test_flash_dispatch_manual_region_classification(monkeypatch):
    """Dispatch must distinguish FULLY-manual from PARTIAL-manual regions.

    Inside a partial-manual region (the pipeline: manual over 'pipe' only)
    activations are still auto-sharded over data/fsdp, so a direct
    pallas_call would be replicated by GSPMD (all-gathering the global
    batch) — the dispatcher must use the blockwise fallback there, and only
    call the kernel directly when every nontrivial mesh axis is manual
    (ADVICE r2 low #2).
    """
    import pretraining_llm_tpu.ops.flash_attention as fa
    import pretraining_llm_tpu.ops.pallas_flash as pf
    from jax.sharding import Mesh, PartitionSpec as P
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    calls = []

    def fake_kernel(q, k, v, *, causal=True, block_q=0, block_kv=0, **kw):
        calls.append(q.shape)
        return blockwise_attention(q, k, v, causal=causal)

    monkeypatch.setattr(fa, "_pallas_available", lambda: True)
    monkeypatch.setattr(pf, "pallas_flash_attention", fake_kernel)

    from tests.conftest import AXES

    devs = np.asarray(jax.devices()).reshape(2, 1, 1, 1, 1, 4)
    mesh = Mesh(devs, AXES)  # 2 data x 4 pipe
    ks = jax.random.split(jax.random.key(13), 3)
    q, k, v = (jax.random.normal(kk, (4, 32, 4, 8), jnp.float32) for kk in ks)
    want = naive_attention(q, k, v, causal=True)

    def body(q, k, v):
        return fa.flash_attention(q, k, v, causal=True)

    # Partial-manual ('pipe' only, data stays auto): kernel must NOT be
    # called directly — blockwise fallback handles the auto axes via GSPMD.
    with activation_mesh(mesh):
        got = jax.jit(
            jax_compat.shard_map(
                body, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                axis_names={"pipe"}, check_vma=False,
            )
        )(q, k, v)
    assert calls == [], "direct kernel call inside a partial-manual region"
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    # Fully-manual (every nontrivial axis manual): operands are per-device
    # local arrays — the direct kernel call is the correct path.
    with activation_mesh(mesh):
        got2 = jax.jit(
            jax_compat.shard_map(
                body, mesh=mesh,
                in_specs=(P("data"), P("data"), P("data")), out_specs=P("data"),
                axis_names={"data", "pipe"}, check_vma=False,
            )
        )(q, k, v)
    assert len(calls) == 1, "fully-manual region must take the direct kernel path"
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_flash_blockwise_fallback_warns(monkeypatch, mesh_seq4):
    """VERDICT r2 #9: when the Pallas dispatch can't express the layout
    per-shard it must WARN that the blockwise JAX path took over."""
    import pretraining_llm_tpu.ops.flash_attention as fa

    monkeypatch.setattr(fa, "_pallas_available", lambda: True)
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    ks = jax.random.split(jax.random.key(14), 3)
    q, k, v = (jax.random.normal(kk, (4, 32, 4, 8), jnp.float32) for kk in ks)
    with activation_mesh(mesh_seq4):  # seq-sharded: not expressible per-shard
        with pytest.warns(UserWarning, match="falling back to blockwise"):
            got = fa.flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(naive_attention(q, k, v, causal=True)),
        rtol=1e-5, atol=1e-5,
    )


def test_shard_mapped_kernel_rejects_indivisible_batch(mesh8):
    """Batch not divisible by the data x fsdp shards -> None (fallback),
    never a shard_map trace error."""
    import functools

    from pretraining_llm_tpu.ops.flash_attention import shard_mapped_kernel
    from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention

    ks = jax.random.split(jax.random.key(12), 3)
    q, k, v = (jax.random.normal(kk, (2, 32, 4, 8), jnp.float32) for kk in ks)
    kernel = functools.partial(pallas_flash_attention, causal=True, interpret=True)
    assert shard_mapped_kernel(kernel, q, k, v, mesh8) is None  # 2 % 4 != 0
