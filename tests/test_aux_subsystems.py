"""Auxiliary subsystems: checkify assertions, profiler capture, failure save."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.training import checkpoint as ckpt
from pretraining_llm_tpu.training.trainer import Trainer
from pretraining_llm_tpu.utils.debug import checked_loss
from pretraining_llm_tpu.utils.profiling import StepProfiler, trace

CFG = get_preset("tiny").model


def test_checked_loss_passes_on_valid_input():
    params = transformer.init_params(CFG, jax.random.key(0))
    x = jax.random.randint(jax.random.key(1), (2, 16), 0, CFG.vocab_size)
    err, loss = jax.jit(functools.partial(checked_loss, cfg=CFG))(params, x, jnp.roll(x, -1, 1))
    err.throw()  # no error
    assert np.isfinite(float(loss))


def test_checked_loss_catches_out_of_range_tokens():
    params = transformer.init_params(CFG, jax.random.key(0))
    x = jnp.full((2, 16), CFG.vocab_size + 7, jnp.int32)  # out of range
    err, _ = jax.jit(functools.partial(checked_loss, cfg=CFG))(params, x, x)
    with pytest.raises(Exception, match="out of range"):
        err.throw()


def test_profiler_trace_capture(tmp_path):
    logdir = str(tmp_path / "trace")
    with trace(logdir):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    # xplane protobuf dumps land under plugins/profile/<run>/
    found = []
    for root, _, files in os.walk(logdir):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane dump under {logdir}"


def test_step_profiler_window(tmp_path):
    logdir = str(tmp_path / "sp")
    prof = StepProfiler(logdir, start_step=2, n_steps=2)
    for s in range(6):
        prof.step(s)
        jnp.sum(jnp.ones((8, 8))).block_until_ready()
    prof.close()
    found = []
    for root, _, files in os.walk(logdir):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found


def test_trainer_saves_on_failure(tmp_path):
    ckdir = str(tmp_path / "ck")
    cfg = get_preset("tiny").with_overrides(
        {
            "train.train_steps": 10,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 100,
            "train.checkpoint_dir": ckdir,
        }
    )
    t = Trainer(cfg, synthetic_data=True, resume=False)

    # Inject a data-source failure mid-run (the fault-injection hook SURVEY §5
    # asks for: a host dying between steps).
    real_iter = t.train_iterator

    class Exploding:
        def __init__(self):
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n > 4:
                raise RuntimeError("host lost")
            return next(real_iter)

    t.train_iterator = Exploding()
    with pytest.raises(RuntimeError, match="host lost"):
        t.train()
    # The last good state (step 4) must have been checkpointed.
    latest = ckpt.latest_checkpoint(ckdir)
    assert latest is not None and latest.endswith("step-4")

    # And a fresh trainer resumes from it.
    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 4
    t2.train()
    assert ckpt.latest_checkpoint(ckdir).endswith("step-10")


def test_trainer_checkpoints_on_sigterm(tmp_path):
    """TPU preemption delivers SIGTERM: the loop must checkpoint at the next
    step boundary and return cleanly (no exception), and a fresh trainer
    resumes from the preemption point."""
    import signal

    ckdir = str(tmp_path / "ck")
    cfg = get_preset("tiny").with_overrides(
        {
            "train.train_steps": 10,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 1,  # stop checks happen at log boundaries
            "train.checkpoint_dir": ckdir,
            # Synchronous sampling: this test's SIGTERM fires while PRODUCING
            # batch 4, and only prefetch=0 ties production to consumption so
            # the checkpoint step is deterministic (step-4). Preemption with
            # the prefetcher active is covered by
            # test_preemption_with_prefetch_resumes_exactly.
            "data.prefetch": 0,
        }
    )
    t = Trainer(cfg, synthetic_data=True, resume=False)
    real_iter = t.train_iterator

    class Preempting:
        """Delivers SIGTERM to our own process while fetching batch 4."""

        def __init__(self):
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == 4:
                os.kill(os.getpid(), signal.SIGTERM)
            return next(real_iter)

    t.train_iterator = Preempting()
    t.train()  # returns instead of dying
    latest = ckpt.latest_checkpoint(ckdir)
    assert latest is not None and latest.endswith("step-4")
    # The handler is uninstalled after train() (back to default/previous).
    assert signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL, signal.default_int_handler)

    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 4
    t2.train()
    assert ckpt.latest_checkpoint(ckdir).endswith("step-10")


def test_preemption_with_prefetch_resumes_exactly(tmp_path):
    """SIGTERM with the prefetch feed active: the worker runs ahead of the
    consumer, so the stop lands at an earlier step boundary — but the
    checkpointed data-RNG frontier is the CONSUMED one, so resume replays
    the queued batches identically: the stitched (pre-preempt + resumed)
    loss sequence must equal an uninterrupted run's."""
    import signal

    def run(ckdir, preempt_at_batch):
        cfg = get_preset("tiny").with_overrides(
            {
                "train.train_steps": 8,
                "train.checkpoint_interval": 0,
                "train.eval_interval": 0,
                "train.log_interval": 1,
                "train.checkpoint_dir": ckdir,
                "data.prefetch": 2,
            }
        )
        losses = []

        class Capture:
            def log(self, rec):
                if "loss" in rec:
                    losses.append(round(float(rec["loss"]), 6))

        t = Trainer(cfg, synthetic_data=True, resume=False, logger=Capture())
        if preempt_at_batch:
            real_iter = t.train_iterator

            class Preempting:
                n = 0

                def __iter__(self):
                    return self

                def __next__(self):
                    Preempting.n += 1
                    if Preempting.n == preempt_at_batch:
                        os.kill(os.getpid(), signal.SIGTERM)
                    return next(real_iter)

                def state(self):
                    return real_iter.state()

                def set_state(self, s):
                    real_iter.set_state(s)

            t.train_iterator = Preempting()
        t.train()
        return cfg, losses

    _, clean = run(str(tmp_path / "clean"), 0)
    assert len(clean) == 8

    ckdir = str(tmp_path / "pre")
    cfg, first = run(ckdir, 4)
    # The preemption-step's own loss is never logged (the loop breaks to
    # checkpoint before the log line), so `first` is a strict prefix.
    assert len(first) < 7  # genuinely preempted early
    assert first == clean[: len(first)], (first, clean)

    t2 = Trainer(cfg, synthetic_data=True, resume=True, logger=None)
    start = t2.start_step
    assert 0 < start < 8

    losses2 = []

    class Capture2:
        def log(self, rec):
            if "loss" in rec:
                losses2.append(round(float(rec["loss"]), 6))

    t2.logger = Capture2()
    t2.train()
    # Exact resume: the continuation reproduces the uninterrupted run's
    # suffix bit-for-bit — the queued-but-unconsumed batches at preemption
    # time were re-drawn identically from the checkpointed frontier.
    assert losses2 == clean[start:], (start, losses2, clean)


def test_trainer_reusable_after_sigterm(tmp_path):
    """A preempted run's stop flag must not leak into the next train() call
    (incremental training via train(steps=N) on the same object)."""
    ckdir = str(tmp_path / "ck")
    cfg = get_preset("tiny").with_overrides(
        {
            "train.train_steps": 4,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 1,
            "train.checkpoint_dir": ckdir,
            # Synchronous sampling ties the SIGTERM (fired while PRODUCING
            # batch 2) to step 2 deterministically — see the sigterm test.
            "data.prefetch": 0,
        }
    )
    t = Trainer(cfg, synthetic_data=True, resume=False)
    t.start_step = 0
    real_iter = t.train_iterator

    class OneShotPreempt:
        def __init__(self):
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == 2:
                os.kill(os.getpid(), __import__("signal").SIGTERM)
            return next(real_iter)

    t.train_iterator = OneShotPreempt()
    t.train(steps=2)  # preempted at step 2
    assert ckpt.latest_checkpoint(ckdir).endswith("step-2")
    t.start_step = 2
    t.train(steps=4)  # stale flag cleared at entry: runs to completion
    assert ckpt.latest_checkpoint(ckdir).endswith("step-4")


def test_async_checkpointing_exact_and_ordered(tmp_path):
    """checkpoint_async writes off-thread but must (a) snapshot the state
    of the step it was requested at — not a later one — and (b) leave a
    loadable checkpoint identical to the sync path."""
    import dataclasses as dc

    ckdir_async = str(tmp_path / "a")
    ckdir_sync = str(tmp_path / "s")
    base = get_preset("tiny").with_overrides(
        {
            "train.train_steps": 6,
            "train.checkpoint_interval": 2,
            "train.eval_interval": 0,
            "train.log_interval": 100,
        }
    )
    cfg_a = base.replace(train=dc.replace(base.train, checkpoint_dir=ckdir_async,
                                          checkpoint_async=True))
    cfg_s = base.replace(train=dc.replace(base.train, checkpoint_dir=ckdir_sync))

    Trainer(cfg_a, synthetic_data=True, resume=False).train()
    Trainer(cfg_s, synthetic_data=True, resume=False).train()

    for step in (2, 4, 6):
        pa, ea = ckpt.load_checkpoint(f"{ckdir_async}/step-{step}",
                                      _template(cfg_a))
        ps, es = ckpt.load_checkpoint(f"{ckdir_sync}/step-{step}",
                                      _template(cfg_s))
        assert ea["step"] == es["step"] == step
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            pa["params"], ps["params"],
        )


def _template(cfg):
    from pretraining_llm_tpu.training import train_step as ts_mod

    return jax.eval_shape(lambda: ts_mod.init_train_state(cfg, jax.random.key(cfg.train.seed)))


def test_async_checkpoint_write_failure_surfaces(tmp_path, monkeypatch):
    """A failed background write must raise at the next join, not vanish."""
    import dataclasses as dc

    from pretraining_llm_tpu.training import trainer as trainer_mod

    cfg = get_preset("tiny").with_overrides(
        {
            "train.train_steps": 4,
            "train.checkpoint_interval": 2,
            "train.eval_interval": 0,
            "train.log_interval": 100,
        }
    )
    cfg = cfg.replace(train=dc.replace(cfg.train, checkpoint_dir=str(tmp_path / "ck"),
                                       checkpoint_async=True))
    t = Trainer(cfg, synthetic_data=True, resume=False)

    def broken_save(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(trainer_mod.ckpt, "save_checkpoint", broken_save)
    t.save(2)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        t.join_pending_save()
