"""Unit tests for bench.py's candidate-racing wrapper.

The wrapper is the driver's only window onto the chip; its failure handling
is load-bearing (round-2 recorded an unattributable 0.0 for the whole round).
These tests drive `wrapper_main` with monkeypatched `_attempt`/`_run_canary`
to pin the round-3 on-chip lessons:

  * a hung attempt triggers a cheap canary before more budget is spent;
  * a wedged backend (canary dead after the kill) is polled for recovery
    instead of burning full attempt timeouts, and reported as an
    ENVIRONMENT error if it never returns;
  * a candidate that hangs twice (with recovery between) is abandoned —
    retrying a chip-wedging program forever would wedge the chip forever.
"""

import json

import bench


class _FakeTime:
    """Deterministic clock: sleep() advances it, monotonic() reads it."""

    def __init__(self):
        self.t = 0.0

    def monotonic(self):
        return self.t

    def sleep(self, s):
        self.t += s

    def perf_counter(self):  # pragma: no cover - not used by the wrapper
        return self.t


def _wrapper_args(**over):
    # race_repeats=1 keeps the candidate-racing tests single-sample; the
    # median-of-N repeat pass has its own dedicated tests below.
    opts = {"preset": "gpt2-124m", "timeout_budget": "600",
            "race_repeats": "1"}
    opts.update({k: str(v) for k, v in over.items()})
    argv = ["--skip-canary"]
    for k, v in opts.items():
        argv += [f"--{k.replace('_', '-')}", v]
    return bench.parse_args(argv)


def _run(monkeypatch, capsys, attempts_script, canary_script, args=None):
    """Run wrapper_main with scripted attempt/canary outcomes.

    attempts_script: list of (rec|None, err) popped per _attempt call; a hang
    advances the fake clock by the attempt timeout (like a real kill would).
    canary_script: list of (ok, detail) popped per _run_canary call; the
    last entry repeats forever.
    """
    ft = _FakeTime()
    monkeypatch.setattr(bench, "time", ft)
    calls = {"attempts": [], "canaries": 0}

    def fake_attempt(a, remat, timeout, attention="", batch_override=0,
                     ce_override=""):
        rec, err = attempts_script.pop(0)
        calls["attempts"].append((remat, attention))
        calls.setdefault("batches", []).append(batch_override)
        calls.setdefault("ces", []).append(ce_override)
        ft.sleep(timeout if "hung" in err else 5.0)
        return rec, err

    def fake_canary(timeout):
        i = min(calls["canaries"], len(canary_script) - 1)
        calls["canaries"] += 1
        ft.sleep(5.0 if canary_script[i][0] else timeout)
        return canary_script[i]

    monkeypatch.setattr(bench, "_attempt", fake_attempt)
    monkeypatch.setattr(bench, "_run_canary", fake_canary)
    rc = bench.wrapper_main(args or _wrapper_args())
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return rc, json.loads(out), calls


def _ok(value, remat):
    return ({"metric": "mfu_gpt2-124m_train", "value": value,
             "unit": "fraction_of_peak_bf16", "vs_baseline": value / 0.5,
             "remat": remat}, "")


HUNG = (None, "hung past 150s (killed)")


def test_hang_with_live_canary_moves_to_next_candidate(monkeypatch, capsys):
    # Candidate 1 hangs; canary says the backend is fine => the program was
    # the problem; candidate 2 succeeds and is reported.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[HUNG, _ok(0.41, "save_attn"), _ok(0.39, "save_attn"),
                        _ok(0.38, "none"), _ok(0.37, "none")],
        canary_script=[(True, {"ok": True})],
    )
    assert rc == 0
    assert rec["value"] == 0.41
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn", "save_attn", "none", "none"]
    # Rungs reach the inner run at THEIR batch and CE head (the r5
    # save_attn_res+dense rung leads, then the save_attn pair, then none).
    assert calls["batches"] == [0, 0, 0, 8, 8]
    assert calls["ces"] == ["dense", "dense", "", "dense", ""]
    assert calls["canaries"] == 1  # exactly one cheap probe after the hang


def test_wedged_backend_is_an_environment_error(monkeypatch, capsys):
    # Hang, then the canary never answers again: the wrapper must poll
    # canaries (not burn full attempts) and report an environment error.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[HUNG],
        canary_script=[(False, "canary hung past 150s (backend unreachable)")],
    )
    assert rc == 1
    assert rec["value"] == 0.0
    assert rec.get("environment_error") is True
    assert "wedged" in rec["error"]
    # Only the first attempt burned a full timeout; everything after was
    # cheap canary polls.
    assert len(calls["attempts"]) == 1
    assert calls["canaries"] >= 2


def test_wedged_then_recovered_retries_same_candidate(monkeypatch, capsys):
    # Hang -> canary dead -> canary recovers -> the SAME candidate gets one
    # retry and succeeds. (Budget must outlive the burnt share: a hang costs
    # min(attempt_timeout, share), so share > 2*attempt_timeout + polls.)
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[HUNG, _ok(0.40, "save_attn_res"),
                        _ok(0.38, "save_attn"), _ok(0.37, "save_attn"),
                        _ok(0.36, "none"), _ok(0.35, "none")],
        canary_script=[(False, "dead"), (True, {"ok": True})],
        args=_wrapper_args(timeout_budget=4200, attempt_timeout=150),
    )
    assert rc == 0
    assert rec["value"] == 0.40  # best of the race, from the retried candidate
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn_res", "save_attn", "save_attn",
        "none", "none"]


def test_double_hang_abandons_candidate(monkeypatch, capsys):
    # A candidate that hangs twice (backend recovering in between) is the
    # problem itself; the wrapper must move on, not wedge the chip a third
    # time.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[HUNG, HUNG, _ok(0.39, "save_attn"),
                        _ok(0.38, "save_attn"), _ok(0.37, "none"),
                        _ok(0.36, "none")],
        canary_script=[(False, "dead"), (True, {"ok": True})],
        args=_wrapper_args(timeout_budget=4200, attempt_timeout=150),
    )
    assert rc == 0
    assert rec["value"] == 0.39
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn_res", "save_attn", "save_attn",
        "none", "none"]


def test_wedge_with_banked_result_reports_it_immediately(monkeypatch, capsys):
    # Candidate 1 already banked a number; candidate 2 hangs and wedges the
    # backend. The wrapper must report the banked result NOW, not poll the
    # dead backend for the rest of the budget.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.30, "save_big"), HUNG],
        canary_script=[(False, "dead")],
    )
    assert rc == 0
    assert rec["value"] == 0.30
    assert len(calls["attempts"]) == 2
    assert calls["canaries"] == 1  # one classifying probe, zero polling


def test_race_reports_best_of_successes(monkeypatch, capsys):
    # Both new policies succeed: the better number wins and the known-good
    # tail is never run (budget preserved).
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.41, "save_attn_res"), _ok(0.40, "save_attn"),
                        _ok(0.39, "save_attn"), _ok(0.30, "none"),
                        _ok(0.28, "none")],
        canary_script=[(True, {"ok": True})],
    )
    assert rc == 0
    assert rec["value"] == 0.41
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn", "save_attn", "none", "none"]
    assert calls["batches"] == [0, 0, 0, 8, 8]
    # Every successful rung's measurement is banked on the winner (r4):
    # losing contenders' values must not vanish from the campaign log.
    assert [r["value"] for r in rec["rungs"]] == [
        0.41, 0.40, 0.39, 0.30, 0.28]


def test_race_repeats_bank_same_session_median(monkeypatch, capsys):
    # VERDICT #1: the race winner is re-run until --race-repeats same-config
    # samples exist; the record banks {best, median, n, spread} and a
    # value_median, while `value` keeps the best-sample series semantics.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.41, "save_attn_res"), _ok(0.40, "save_attn"),
                        _ok(0.39, "save_attn"), _ok(0.30, "none"),
                        _ok(0.28, "none"), _ok(0.37, "save_attn_res"),
                        _ok(0.44, "save_attn_res")],
        canary_script=[(True, {"ok": True})],
        args=_wrapper_args(race_repeats=3),
    )
    assert rc == 0
    # Repeats re-run the WINNER's exact config (save_attn_res + dense).
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn", "save_attn", "none", "none",
        "save_attn_res", "save_attn_res"]
    assert calls["ces"][-2:] == ["dense", "dense"]
    assert rec["race"] == {"best": 0.44, "median": 0.41, "n": 3,
                           "spread": 0.07, "values": [0.41, 0.37, 0.44]}
    assert rec["value_median"] == 0.41
    # A repeat that beats the original becomes the headline value...
    assert rec["value"] == 0.44
    # ...and every sample (5 race rungs + 2 repeats) stays in the evidence.
    assert len(rec["rungs"]) == 7


def test_race_repeat_failure_keeps_partial_samples(monkeypatch, capsys):
    # A deterministic failure during repeats must stop the sampling loop
    # cold (no retry ladder): the median is over the samples that exist.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.41, "save_attn_res"), _ok(0.40, "save_attn"),
                        _ok(0.39, "save_attn"), _ok(0.30, "none"),
                        _ok(0.28, "none"), _ok(0.39, "save_attn_res"),
                        (None, "rc=1: RuntimeError: boom")],
        canary_script=[(True, {"ok": True})],
        args=_wrapper_args(race_repeats=4),
    )
    assert rc == 0
    assert rec["value"] == 0.41
    assert rec["race"]["n"] == 2
    assert rec["race"]["values"] == [0.41, 0.39]
    assert rec["race"]["median"] == 0.4
    assert calls["canaries"] == 0  # not a hang: no probe burned


def test_hung_race_repeat_marks_wedge_and_reports(monkeypatch, capsys):
    # A repeat that hangs and kills the backend must still report the
    # collected samples NOW, marked backend_wedged for chained callers.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.41, "save_attn_res"), _ok(0.40, "save_attn"),
                        _ok(0.39, "save_attn"), _ok(0.30, "none"),
                        _ok(0.28, "none"), HUNG],
        canary_script=[(False, "dead")],
        args=_wrapper_args(race_repeats=3),
    )
    assert rc == 0
    assert rec["value"] == 0.41
    assert rec.get("backend_wedged") is True
    assert rec["race"]["n"] == 1
    assert calls["canaries"] == 1  # one classifying probe, zero polling


def test_explicit_batch_drops_override_rungs(monkeypatch, capsys):
    # `--batch 24` is a series point the caller chose; the race must not
    # silently answer it with a batch-8 measurement (code-review r4). With
    # the none@8 rung dropped there is no second CONTENDER, so a first-rung
    # success ends the race — the measured-slower save_big fallback must
    # not burn hardware window that cannot improve the number.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.40, "save_attn_res"), _ok(0.39, "save_attn"),
                        _ok(0.38, "save_attn")],
        canary_script=[(True, {"ok": True})],
        args=_wrapper_args(batch=24),
    )
    assert rc == 0
    assert rec["value"] == 0.40
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn", "save_attn"]
    assert calls["batches"] == [0, 0, 0]  # no per-candidate override in play
    assert calls["ces"] == ["dense", "dense", ""]  # ce rungs race at --batch


def test_matching_explicit_batch_keeps_override_rung(monkeypatch, capsys):
    # `--batch 8` equals the none rung's own batch: the rung stays, so a
    # banked none@8 race win is reproducible at its explicit batch.
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.40, "save_attn_res"), _ok(0.39, "save_attn"),
                        _ok(0.38, "save_attn"), _ok(0.52, "none"),
                        _ok(0.50, "none")],
        canary_script=[(True, {"ok": True})],
        args=_wrapper_args(batch=8),
    )
    assert rc == 0
    assert rec["value"] == 0.52
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn", "save_attn", "none", "none"]


def test_explicit_ce_drops_override_rungs(monkeypatch, capsys):
    # `--ce chunked` applies to every rung; the dense-overridden rung would
    # be a duplicate of its plain sibling (or a contradiction of the
    # caller's choice) and must not burn a contender share (code-review r4).
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[_ok(0.40, "save_attn"), _ok(0.38, "none")],
        canary_script=[(True, {"ok": True})],
        args=_wrapper_args(ce="chunked"),
    )
    assert rc == 0
    assert rec["value"] == 0.40
    assert [r for r, _ in calls["attempts"]] == ["save_attn", "none"]
    assert calls["ces"] == ["", ""]  # no per-candidate CE override in play


def test_oom_is_deterministic_not_transient(monkeypatch, capsys):
    # XLA OOM surfaces as RESOURCE_EXHAUSTED (a transient_markers match),
    # but retrying the identical compile only drains the rung's budget
    # share: one bounded attempt, then the next candidate (code-review r4).
    oom = (None, "rc=1: XlaRuntimeError: RESOURCE_EXHAUSTED: Out of memory "
                 "while trying to allocate 18.3GiB")
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[oom, _ok(0.41, "save_attn"), _ok(0.40, "save_attn"),
                        _ok(0.39, "none"), _ok(0.38, "none")],
        canary_script=[(True, {"ok": True})],
    )
    assert rc == 0
    assert rec["value"] == 0.41
    # Exactly ONE attempt on the OOM-ing candidate, no backoff retries.
    assert [r for r, _ in calls["attempts"]] == [
        "save_attn_res", "save_attn", "save_attn", "none", "none"]


def test_environment_error_carries_last_banked(monkeypatch, capsys):
    # VERDICT r3 #8: when the backend is dead the driver's JSON must point
    # at the banked evidence, not leave a bare 0.0.
    banked = {"metric": "mfu_gpt2-124m_train", "value": 0.416,
              "unit": "fraction_of_peak_bf16", "stage": "bsweep:batch/16",
              "capture_path": "data/captures/tpu_capture_r03.jsonl",
              "commit": "abc1234 2026-07-31T00:00:00+00:00"}
    monkeypatch.setattr(bench, "_last_banked", lambda metric: dict(banked))
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[HUNG],
        canary_script=[(False, "canary hung past 150s (backend unreachable)")],
    )
    assert rc == 1
    assert rec.get("environment_error") is True
    assert rec["last_banked"]["value"] == 0.416
    assert rec["last_banked"]["capture_path"].startswith("data/captures/")


def test_last_banked_scans_capture_jsonl(tmp_path, monkeypatch):
    # The scanner must pick the best rc==0 record for the metric, skipping
    # error records, other metrics, and the known-bogus rc==0-with-error
    # shape (ADVICE r3 medium: a FAIL record now carries an error marker).
    cap = tmp_path / "data" / "captures"
    cap.mkdir(parents=True)
    recs = [
        {"stage": "mfu", "rc": 0, "metric": "mfu_gpt2-124m_train",
         "value": 0.406, "unit": "fraction_of_peak_bf16"},
        {"stage": "bsweep:batch/16", "rc": 0, "metric": "mfu_gpt2-124m_train",
         "value": 0.416, "unit": "fraction_of_peak_bf16", "batch": 16},
        {"stage": "mfu", "rc": 1, "metric": "mfu_gpt2-124m_train",
         "value": 0.9},  # failed stage: ignored
        {"stage": "decode", "rc": 0,
         "metric": "decode_tokens_per_sec_gpt2-124m", "value": 3841.0},
        {"stage": "mfu", "rc": 0, "metric": "mfu_gpt2-124m_train",
         "value": 0.0, "error": "environment: dead"},  # error: ignored
    ]
    with open(cap / "tpu_capture_r99.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    best = bench._last_banked("mfu_gpt2-124m_train", repo=str(tmp_path))
    assert best is not None
    assert best["value"] == 0.416
    assert best["stage"] == "bsweep:batch/16"
    assert best["capture_path"].endswith("tpu_capture_r99.jsonl")
    assert bench._last_banked("mfu_llama-1b_train", repo=str(tmp_path)) is None


def test_last_banked_carries_latest_refresh(tmp_path):
    # VERDICT r5 #8: the banked record must carry FRESHNESS — the most
    # recent mfu-refresh value + timestamp — alongside the all-time best,
    # so a dead-backend round end distinguishes "peak banked long ago"
    # from "reproduced this session".
    cap = tmp_path / "data" / "captures"
    cap.mkdir(parents=True)
    r03 = [
        {"stage": "campaign-start", "rc": 0, "ts": "2026-07-28T09:00:00Z"},
        {"stage": "mfu", "rc": 0, "metric": "mfu_gpt2-124m_train",
         "value": 0.503, "unit": "fraction_of_peak_bf16"},
    ]
    r05 = [
        {"stage": "campaign-start", "rc": 0, "ts": "2026-08-01T10:00:00Z"},
        # Refresh records carry no "ts" of their own: the file's
        # campaign-start stamp is the session they ran in.
        {"stage": "mfu-refresh-mid", "rc": 0,
         "metric": "mfu_gpt2-124m_train", "value": 0.374},
        {"stage": "mfu-refresh", "rc": 0, "metric": "mfu_gpt2-124m_train",
         "value": 0.359},
    ]
    for name, recs in (("tpu_capture_r03.jsonl", r03),
                       ("tpu_capture_r05.jsonl", r05)):
        with open(cap / name, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
    best = bench._last_banked("mfu_gpt2-124m_train", repo=str(tmp_path))
    assert best["value"] == 0.503  # the all-time best stays the headline
    fresh = best["latest_refresh"]
    assert fresh["value"] == 0.359  # the LAST refresh, not the best one
    assert fresh["stage"] == "mfu-refresh"
    assert fresh["ts"] == "2026-08-01T10:00:00Z"
    assert fresh["capture_path"].endswith("tpu_capture_r05.jsonl")


def test_mode_flag_guards_reject_foreign_knobs():
    """Every mode rejects the other modes' knobs (a silently-ignored flag
    would bank a record indistinguishable from the baseline while the
    operator believes they measured the override config)."""
    import pytest

    cases = [
        # (mode runner, argv, rejected-flag fragment)
        (bench.run_serving_bench, ["--mode", "serving", "--remat",
                                   "save_attn"], "--remat"),
        (bench.run_serving_bench, ["--mode", "serving", "--decode-unroll"],
         "--decode-unroll"),
        (bench.run_decode_bench, ["--mode", "decode", "--steps-per-sched",
                                  "4"], "--steps-per-sched"),
        (bench.run_decode_bench, ["--mode", "decode", "--optimizer",
                                  "adafactor"], "--optimizer"),
        (bench.run_decode_bench, ["--mode", "decode", "--context", "2048"],
         "--context"),
        (bench.run_trainer_bench, ["--mode", "trainer", "--cache-layout",
                                   "stacked"], "--cache-layout"),
        (bench.run_trainer_bench, ["--mode", "trainer", "--context",
                                   "2048"], "--context"),
    ]
    import re

    for runner, argv, frag in cases:
        args = bench.parse_args(argv)
        with pytest.raises(ValueError, match=re.escape(frag)):
            runner(args)


def test_error_result_metric_mirrors_success_series():
    """A failed run's metric name must match the success series of the
    SAME invocation (decode layout suffixes, serving suffixes, ctx)."""
    # Default decode (unstacked default) fails -> _unstacked series.
    rec = bench.error_result(
        bench.parse_args(["--mode", "decode"]), "boom", 1)
    assert rec["metric"] == "decode_tokens_per_sec_gpt2-124m_unstacked"
    # Explicit stacked -> the historical unsuffixed series.
    rec = bench.error_result(
        bench.parse_args(["--mode", "decode", "--cache-layout", "stacked"]),
        "boom", 1)
    assert rec["metric"] == "decode_tokens_per_sec_gpt2-124m"
    # Serving default -> _unstacked.
    rec = bench.error_result(
        bench.parse_args(["--mode", "serving"]), "boom", 1)
    assert rec["metric"] == "serving_tokens_per_sec_gpt2-124m_unstacked"
    # Train with a context override -> _ctxN series.
    rec = bench.error_result(
        bench.parse_args(["--context", "16384",
                          "--preset", "gpt2-8k-sp"]), "boom", 1)
    assert rec["metric"] == "mfu_gpt2-8k-sp_train_ctx16384"


def test_structured_inner_error_is_relayed(monkeypatch, capsys):
    # Deterministic inner failures relay the inner run's structured record.
    inner = {"metric": "mfu_gpt2-124m_train", "value": 0.0,
             "unit": "fraction_of_peak_bf16", "vs_baseline": 0.0,
             "error": "RuntimeError: boom", "attempts": 1}
    rc, rec, calls = _run(
        monkeypatch, capsys,
        attempts_script=[(inner, "rc=1: RuntimeError")] * 8,
        canary_script=[(True, {"ok": True})],
    )
    assert rc == 1
    assert rec["error"] == "RuntimeError: boom"
