"""Capacity observability: occupancy sampling, the scheduler decision
log, live /debug endpoints, and the offline slot-second waterfall.

The correctness bar mirrors the tracing/metrics layer's: the instruments
ride EXISTING sync points, so greedy outputs must stay bit-identical with
the layer on vs. off at every pipeline depth, and an instrumented run
must pull exactly as many device arrays to host as a plain one (the
``np.asarray`` spy). On top of that, decision records must JOIN: every
preemption/eviction carries the trace_id the req_* event stream knows,
and the capacity waterfall's segments must sum to wall time — the same
arithmetic the ci_smoke.sh capacity gate enforces over HTTP.
"""

import dataclasses
import importlib.util
import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, run_engine_loop
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.capacity import (
    DECISION_KINDS,
    CapacitySampler,
    DecisionLog,
)
from pretraining_llm_tpu.observability.events import EVENT_KINDS, EventBus
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.tracing import Tracer

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")

# The offline analyzer is the CI gate's logic: import it as a module so
# the waterfall assertions here use EXACTLY what the gate runs.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_capacity", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(12, 10, 11, 12)):
    rng = np.random.default_rng(7)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _reference_greedy(params, prompt, n_new):
    toks = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


def _tiny_pool_engine(params, **kw):
    """Pool sized so the preemption/eviction ladder actually fires (the
    test_serving_pipeline preemption-replay sizing, cache on)."""
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_blocks", 8)
    kw.setdefault("block_size", 8)
    kw.setdefault("steps_per_sched", 4)
    kw.setdefault("pipeline_depth", 2)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(params, CFG, temperature=0.0, **kw)


# -- unit: the instruments themselves ---------------------------------------


def test_decision_log_kinds_and_ring():
    log = DecisionLog(maxlen=3)
    with pytest.raises(ValueError, match="unknown decision kind"):
        log.record("coffee_break")
    for i in range(5):
        log.record("preempt", rid=i)
    assert [r["rid"] for r in log.tail()] == [2, 3, 4]  # ring bounded
    assert log.counts_snapshot() == {"preempt": 5}  # totals survive eviction
    assert log.tail(1)[0]["rid"] == 4
    with pytest.raises(ValueError, match="maxlen"):
        DecisionLog(maxlen=0)


def test_capacity_event_kinds_documented():
    # The new kinds are part of the documented vocabulary, and every
    # decision kind is a closed set the analyzer can label.
    assert "cap_window" in EVENT_KINDS
    assert "decision" in EVENT_KINDS
    assert set(DECISION_KINDS) == {
        "reject_busy", "reject_infeasible", "preempt", "evict_cold",
        "reclaim_spec", "expire_inflight", "defer_prefill_chunk",
        # fleet tier (frontend/router.py)
        "eject_replica", "redrive", "brownout_shed",
        # integrity sentinel (resilience/integrity.py + router)
        "quarantine", "drop_corrupt_block",
        # process-worker fleet (frontend/worker.py + router)
        "fleet_drain", "upgrade_refused",
        # disaggregated prefill/decode tiers (frontend/router.py)
        "kv_migrate", "kv_migration_reject",
        # live SLO engine (observability/slo.py)
        "slo_alert",
    }


def test_sampler_record_schema_and_bus():
    events = []
    bus = EventBus()
    bus.subscribe(events.append)
    samp = CapacitySampler(4, 23, maxlen=2, bus=bus)
    rec = samp.observe_window(
        window=0, kind="decode", t_dispatch_s=1.0, t_reap_s=1.5, steps=4,
        rows=3, tokens_committed=10, waiting=1, pool_free=5, pool_cold=2,
        host_blocked_s=0.1, cum_tokens=10, cum_prefill_tokens=30,
        cum_rework_prefill_tokens=0, cum_preemptions=0,
    )
    assert rec["pool_live"] == 23 - 5 - 2
    assert rec["slot_tokens"] == 12 and rec["dur_s"] == pytest.approx(0.5)
    assert events and events[0]["event"] == "cap_window"
    assert events[0]["rows_capacity"] == 4
    # JSONL-serializable by the bus's own strict encoder.
    json.dumps(events[0], allow_nan=False)
    for i in range(3):
        samp.observe_window(
            window=i + 1, kind="decode", t_dispatch_s=2.0 + i,
            t_reap_s=2.5 + i, steps=4, rows=1, tokens_committed=4,
            waiting=0, pool_free=7, pool_cold=0, host_blocked_s=0.0,
            cum_tokens=14 + 4 * i, cum_prefill_tokens=30,
            cum_rework_prefill_tokens=0, cum_preemptions=0,
        )
    assert len(samp.tail()) == 2  # ring bounded
    assert samp.windows_sampled == 4


# -- decision log + trace linkage under real pool pressure ------------------


def _pressured_run(params, *, registry=None, events=None):
    """Seeded loadgen against a tiny-pool engine behind the full frontend
    (admission + tracing + bus): returns (loop, engine, report)."""
    eng = _tiny_pool_engine(params)
    bus = EventBus()
    if events is not None:
        bus.subscribe(events.append)
    tracer = Tracer(SpanRecorder(), sample=1.0, seed=3)
    admission = AdmissionController(max_queue_depth=8, registry=registry)
    loop = EngineLoop(
        eng, admission=admission, bus=bus, tracer=tracer, registry=registry,
    )
    spec = LoadSpec(
        n_requests=4, mode="closed", concurrency=4, seed=11,
        vocab_size=CFG.vocab_size, prompt_len_min=10, prompt_len_max=12,
        max_new_min=20, max_new_max=24,
    )
    with loop:
        report = run_engine_loop(loop, spec)
    return loop, eng, report


def test_decision_log_preemption_and_eviction_with_trace_linkage(params):
    events = []
    loop, eng, report = _pressured_run(params, events=events)
    assert all(o.status == "done" for o in report.outcomes)
    counts = loop.decisions.counts_snapshot()
    assert counts.get("preempt", 0) >= 1, counts
    assert counts.get("evict_cold", 0) >= 1, counts
    assert eng.stats["preemptions"] == counts["preempt"]
    # Rework accounting: every preemption forces a re-prefill, and the
    # recomputed-token stat counts what was actually paid.
    assert eng.stats.get("preempted_tokens_recomputed", 0) >= 1
    # Linkage: every preempt decision names a trace the req_* stream knows.
    known = {
        e["trace_id"] for e in events
        if e["event"].startswith("req_") and "trace_id" in e
    }
    assert len(known) == 4
    preempts = [r for r in loop.decisions.tail() if r["decision"] == "preempt"]
    for rec in preempts:
        assert rec["trace_id"] in known
        assert rec["blocks_reclaimed"] >= 1
        assert rec["victim_admit_order"] >= 0
    # The same records went over the bus as typed `decision` events.
    bus_decisions = [e for e in events if e["event"] == "decision"]
    assert len(bus_decisions) == sum(counts.values())
    # Occupancy sampling rode every reap.
    caps = [e for e in events if e["event"] == "cap_window"]
    assert len(caps) == eng.stats["windows_reaped"]
    assert all(c["rows_capacity"] == 2 and c["pool_total"] == 7 for c in caps)


def test_capacity_report_on_pressured_run(params):
    """The offline fold over the same events the CI gate reads: segments
    sum to wall within 1%, the binding constraint is named, and every
    decision joins (problems empty)."""
    events = []
    _loop, _eng, _report = _pressured_run(params, events=events)
    cap = obs_report.build_capacity_report(events)
    assert cap["problems"] == []
    assert cap["n_windows"] >= 1
    wall = cap["wall_s"]
    total = sum(cap["segments"].values())
    assert abs(total - wall) <= 0.01 * wall
    assert cap["binding_constraint"] in cap["constraint_scores"]
    # A tiny pool with a queue must surface as pool pressure somewhere:
    # preemption rework or pool-starved idle time exists.
    assert (
        cap["segments"]["preempted_rework"] + cap["segments"]["pool_starved"]
    ) >= 0.0
    assert cap["decisions"].get("preempt", 0) >= 1
    assert cap["decisions_by_trace"]  # the "why was trace X" join


def test_capacity_report_synthetic_waterfall():
    """Deterministic arithmetic check: hand-built windows with known
    overlap, idle rows, uncommitted slots, and a rework gap."""
    def win(i, t0, t1, rows, steps, committed, waiting, prefill, rework):
        return {
            "event": "cap_window", "t_wall": 0.0, "window": i,
            "t_dispatch_s": t0, "t_reap_s": t1, "steps": steps,
            "rows": rows, "rows_capacity": 2, "tokens_committed": committed,
            "waiting": waiting, "pool_free": 1, "pool_cold": 0,
            "pool_total": 7, "cum_prefill_tokens": prefill,
            "cum_rework_prefill_tokens": rework, "cum_preemptions": 0,
        }
    events = [
        # Full window, all committed: pure productive.
        win(0, 0.0, 1.0, 2, 4, 8, 0, 10, 0),
        # Overlapping window (pipelined): only [1.0, 1.5] is new coverage;
        # half the rows idle with requests waiting -> pool-starved.
        win(1, 0.5, 1.5, 1, 4, 4, 1, 10, 0),
        # Gap [1.5, 2.5] whose prefill was ALL rework -> preempted_rework;
        # then a window with uncommitted slots -> spec_wasted.
        win(2, 2.5, 3.0, 2, 4, 4, 0, 20, 10),
    ]
    cap = obs_report.build_capacity_report(events)
    segs = cap["segments"]
    assert cap["wall_s"] == pytest.approx(3.0)
    assert sum(segs.values()) == pytest.approx(3.0)
    # productive: 1.0 (win0) + 0.5*0.5 (win1 active half) + 0.5*0.5 (win2
    # committed half of its 0.5s full-rows coverage)
    assert segs["productive"] == pytest.approx(1.0 + 0.25 + 0.25)
    assert segs["pool_starved"] == pytest.approx(0.25)   # win1 idle half
    assert segs["preempted_rework"] == pytest.approx(1.0)  # the gap
    assert segs["spec_wasted"] == pytest.approx(0.25)    # win2 uncommitted
    assert segs["admission_starved"] == pytest.approx(0.0)
    assert sum(cap["constraint_scores"].values()) > 0


def test_capacity_report_strict_catches_unjoinable_decision():
    events = [{
        "event": "decision", "t_wall": 0.0, "decision": "preempt",
        "trace_id": "feedfacefeedfacefeedfacefeedface", "t_s": 1.0,
    }]
    cap = obs_report.build_capacity_report(events)
    assert any("no matching req_*" in p for p in cap["problems"])
    assert any("no cap_window" in p for p in cap["problems"])


# -- bit-identity and the no-sync guarantee ---------------------------------


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_outputs_identical_with_capacity_layer(params, depth):
    """Greedy outputs with the full capacity layer installed (sampler +
    decision log + bus + registry) are bit-identical to a plain run
    through the preemption/eviction workload at every depth."""
    prompts = _prompts(2, lengths=(12, 10))
    n_new = 24

    def run(instrument):
        eng = _tiny_pool_engine(params, pipeline_depth=depth)
        if instrument:
            reg = MetricsRegistry("pllm_serving_")
            bus = EventBus()
            eng.capacity = CapacitySampler(
                eng.max_batch, eng.alloc.n_blocks - 1, bus=bus,
            )
            eng.capacity.bind(reg)
            eng.decisions = DecisionLog(bus=bus)
            eng.preempt_counter = reg.counter(
                "preemptions_total", "preemptions")
            eng.preempt_tokens_counter = reg.counter(
                "preempted_tokens_recomputed_total", "rework")
        for p in prompts:
            eng.submit(p, n_new)
        return eng.run(pipeline=True), eng

    out_plain, _ = run(False)
    out_inst, eng = run(True)
    assert out_inst == out_plain
    assert eng.stats["preemptions"] >= 1  # the workload really preempted
    assert eng.decisions.counts_snapshot().get("preempt", 0) >= 1
    assert eng.preempt_counter.value == eng.stats["preemptions"]
    for rid, p in zip(sorted(out_inst), prompts):
        assert out_inst[rid] == _reference_greedy(params, p, n_new)


def test_capacity_sampling_adds_no_device_syncs(params, monkeypatch):
    """Occupancy sampling + decision logging ride the reap's EXISTING
    host transfers: instrumented and plain runs must pull the same
    number of device arrays (np.asarray on a jax.Array is the sync)."""
    prompts = _prompts(2, lengths=(12, 10))

    def run(instrument):
        eng = _tiny_pool_engine(params)
        if instrument:
            reg = MetricsRegistry("pllm_serving_")
            eng.capacity = CapacitySampler(
                eng.max_batch, eng.alloc.n_blocks - 1, bus=EventBus(),
            )
            eng.capacity.bind(reg)
            eng.decisions = DecisionLog(bus=EventBus())
        for p in prompts:
            eng.submit(p, 24)
        real = np.asarray
        pulls = [0]

        def spy(a, *args, **kw):
            if isinstance(a, jax.Array):
                pulls[0] += 1
            return real(a, *args, **kw)

        monkeypatch.setattr(np, "asarray", spy)
        try:
            out = eng.run(pipeline=True)
        finally:
            monkeypatch.undo()
        return out, pulls[0], eng

    out_plain, pulls_plain, _ = run(False)
    out_inst, pulls_inst, eng = run(True)
    assert out_inst == out_plain
    assert pulls_inst == pulls_plain  # zero extra device syncs
    assert eng.capacity.windows_sampled == eng.stats["windows_reaped"]
    assert eng.decisions.counts_snapshot().get("preempt", 0) >= 1


# -- typed gauges/counters on the registry ----------------------------------


def test_admission_gauges_and_preemption_counters(params):
    reg = MetricsRegistry("pllm_serving_")
    loop, eng, _report = _pressured_run(params, registry=reg)
    text = reg.render(extra_gauges=loop.metrics())
    assert "# TYPE pllm_serving_admission_queue_depth gauge" in text
    assert "pllm_serving_admission_queue_depth_limit 8.0" in text
    assert "# TYPE pllm_serving_admission_outstanding_tokens gauge" in text
    assert "# TYPE pllm_serving_preemptions_total counter" in text
    assert "# TYPE pllm_serving_preempted_tokens_recomputed_total counter" in text
    assert 'pllm_serving_deadline_shed_total{kind="admission"} 0.0' in text
    assert "# TYPE pllm_serving_capacity_rows_active gauge" in text
    assert 'pllm_serving_capacity_pool_blocks{state="free"}' in text
    assert "pllm_serving_capacity_pool_blocks_limit 7.0" in text
    assert "# TYPE pllm_serving_capacity_window_occupancy histogram" in text
    # The typed preemption counter agrees with the engine stat, and the
    # admission gauges drained back to zero at run end.
    assert eng.preempt_counter.value == eng.stats["preemptions"] >= 1
    m = loop.metrics()
    assert m["admission_live_requests"] == 0
    assert m["admission_outstanding_tokens"] == 0


def test_frontend_config_capacity_ring_validation():
    assert FrontendConfig().capacity_ring == 512
    with pytest.raises(ValueError, match="capacity_ring"):
        FrontendConfig(capacity_ring=-1)


def test_engine_loop_capacity_ring_zero_disables(params):
    eng = _tiny_pool_engine(params)
    loop = EngineLoop(eng, capacity_ring=0)
    assert loop.capacity is None and loop.decisions is None
    assert eng.capacity is None and eng.decisions is None
    with pytest.raises(ValueError, match="capacity_ring"):
        EngineLoop(eng, capacity_ring=-1)


# -- /debug endpoints --------------------------------------------------------


def test_debug_endpoints_pool_accounting(params):
    eng = _tiny_pool_engine(params)
    admission = AdmissionController(max_queue_depth=8)
    loop = EngineLoop(eng, admission=admission)
    gw = ServingGateway(loop, port=0)
    loop.start()
    gw.start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        with urllib.request.urlopen(f"{base}/debug/engine", timeout=30) as r:
            dbg = json.loads(r.read())
        pool = dbg["pool"]
        # The gate's invariant: the debug view's block accounting ties
        # out against the allocator exactly.
        assert pool["total"] == eng.alloc.n_blocks - 1 == 7
        assert pool["free"] == eng.alloc.available
        assert pool["free"] + pool["cold"] + pool["live"] == pool["total"]
        assert dbg["rows"] == {"active": 0, "capacity": 2}
        assert dbg["admission"]["max_queue_depth"] == 8
        assert dbg["decisions"]["counts"] == {}
        with urllib.request.urlopen(f"{base}/debug/requests", timeout=30) as r:
            assert json.loads(r.read())["requests"] == []
        # Now run pressure through the HTTP-adjacent loop and re-read.
        spec = LoadSpec(
            n_requests=4, mode="closed", concurrency=4, seed=11,
            vocab_size=CFG.vocab_size, prompt_len_min=10, prompt_len_max=12,
            max_new_min=20, max_new_max=24,
        )
        run_engine_loop(loop, spec)
        with urllib.request.urlopen(
            f"{base}/debug/engine?tail=8", timeout=30
        ) as r:
            dbg = json.loads(r.read())
        assert dbg["decisions"]["counts"].get("preempt", 0) >= 1
        assert dbg["occupancy"], "occupancy ring tail missing"
        last = dbg["occupancy"][-1]
        assert last["rows_capacity"] == 2 and last["pool_total"] == 7
        assert dbg["windows_sampled"] == eng.stats["windows_reaped"]
        pool = dbg["pool"]
        assert pool["free"] + pool["cold"] + pool["live"] == pool["total"]
        assert pool["free"] == eng.alloc.available
        assert dbg["prefix_cache"]["cold"] == eng.prefix_cache.evictable
    finally:
        gw.stop()
        loop.stop()


def test_debug_requests_live_state(params):
    """Mid-decode, /debug/requests shows phase/row/blocks for an active
    request. Throttle the tick so 'mid-generation' is reliably observable
    (the test_frontend idiom)."""
    import time as _time

    eng = _tiny_pool_engine(params)
    orig = eng.pipeline_tick

    def slow_tick():
        _time.sleep(0.05)
        return orig()

    eng.pipeline_tick = slow_tick
    loop = EngineLoop(eng)
    loop.start()
    try:
        h = loop.submit(_prompts(1)[0], 32, deadline_s=300.0)
        deadline = _time.monotonic() + 30.0
        seen = None
        while _time.monotonic() < deadline:
            recs = loop.debug_requests()
            active = [r for r in recs if r.get("phase") == "decode"]
            if active:
                seen = active[0]
                break
            _time.sleep(0.01)
        assert seen is not None, "request never observed on a row"
        assert seen["row"] in (0, 1)
        assert seen["blocks_held"] >= 1
        assert seen["status"] == "active"
        assert seen["deadline_remaining_s"] > 0
        h.result(timeout=300)
    finally:
        loop.stop()
