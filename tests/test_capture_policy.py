"""Tests for the capture campaign's hardware-window risk policy.

VERDICT r3 next #7: two rounds lost their driver-facing number because an
unproven kernel-config probe wedged the chip during the only hardware
window. The policy — critical stages (mfu, parity-tpu, e2e) banked before
ANY risky probe — is now code in scripts/tpu_capture.py; these tests pin
the classification logic it rests on.
"""

import importlib
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))

tpu_capture = importlib.import_module("tpu_capture")


def _write(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_critical_stage_set_is_the_verdict_trio():
    assert set(tpu_capture.CRITICAL_STAGES) == {"mfu", "parity-tpu", "e2e"}


def test_risky_stages_cover_the_unproven_classes():
    # Every class that has wedged (or never touched) this backend must be
    # behind the gate; the proven capture stages must NOT be.
    assert {"profile", "profile-decode", "decode-int8", "unroll-sweep",
            "sweep-full"} <= tpu_capture.RISKY_STAGES
    for proven in ("mfu", "parity-tpu", "e2e", "decode", "ctx8k", "trainer",
                   "sweep-top", "batch-sweep", "mfu-350m", "mfu-1b"):
        assert proven not in tpu_capture.RISKY_STAGES


def test_critical_banked_requires_all_three(tmp_path):
    out = tmp_path / "cap.jsonl"
    _write(out, [
        {"stage": "campaign-start"},
        {"stage": "mfu", "rc": 0, "metric": "mfu_gpt2-124m_train",
         "value": 0.41},
    ])
    assert tpu_capture._critical_banked(str(out)) == {"mfu"}

    _write(out, [
        {"stage": "mfu", "rc": 0, "value": 0.41},
        {"stage": "parity-tpu", "rc": 0, "delta": 0.0003, "pass": True},
        {"stage": "e2e", "rc": 0, "all_checks": True},
    ])
    assert tpu_capture._critical_banked(str(out)) == {
        "mfu", "parity-tpu", "e2e"}


def test_failed_critical_stage_does_not_count(tmp_path):
    out = tmp_path / "cap.jsonl"
    _write(out, [
        {"stage": "mfu", "rc": 0, "value": 0.0,
         "error": "environment: backend unreachable"},
        {"stage": "e2e", "rc": -1, "error": "stage hung past 1800s"},
    ])
    assert tpu_capture._critical_banked(str(out)) == set()


def test_honest_parity_fail_counts_as_banked(tmp_path):
    # A numeric parity FAIL exits 1 (ADVICE r3 medium fix) but the
    # measurement is complete — the window was not lost, risky probes may
    # proceed. A parity CRASH (no delta) must not count.
    out = tmp_path / "cap.jsonl"
    _write(out, [
        {"stage": "parity-tpu", "rc": 1, "delta": 0.0112, "pass": False},
    ])
    assert tpu_capture._critical_banked(str(out)) == {"parity-tpu"}
    _write(out, [
        {"stage": "parity-tpu", "rc": 1,
         "raw": "Traceback (most recent call last): ..."},
    ])
    assert tpu_capture._critical_banked(str(out)) == set()


def test_parity_rc0_without_delta_does_not_count(tmp_path):
    # An --only jax run with the torch twin record missing trains one side
    # and exits 0 WITHOUT comparing curves — no delta, no measurement, no
    # unlock (code-review r4 finding).
    out = tmp_path / "cap.jsonl"
    _write(out, [
        {"stage": "parity-tpu", "rc": 0,
         "raw": "[jax] step 1500 loss 1.18"},
    ])
    assert tpu_capture._critical_banked(str(out)) == set()


def test_full_campaign_runs_criticals_first_and_defers_risky(
        tmp_path, monkeypatch):
    """Drive tpu_capture.main() end-to-end with stubbed stage execution:
    the campaign must run mfu -> parity-tpu -> e2e before everything else,
    and with all criticals succeeding the risky tier must RUN (not defer).
    (The criticals-FAIL deferral path is pinned by the next test.)"""
    out = tmp_path / "cap.jsonl"
    ran = []

    def fake_run_cmd(name, cmd, timeout, out_f, wait_pool=None):
        ran.append(name)
        rec = {"stage": name, "rc": 0}
        if name == "parity-tpu":
            rec.update(delta=0.0003, **{"pass": True})
        out_f.write(json.dumps(rec) + "\n")
        out_f.flush()
        return rec

    monkeypatch.setattr(tpu_capture, "run_cmd", fake_run_cmd)
    monkeypatch.setattr(tpu_capture, "wait_for_backend",
                        lambda out_f, pool: {"ok": True})
    monkeypatch.setattr(
        "sys.argv", ["tpu_capture.py", "--out", str(out)])
    assert tpu_capture.main() == 0

    # Priority order: the three criticals lead, in order.
    assert ran[:3] == ["mfu", "parity-tpu", "e2e"]
    # The risky tier RAN because the criticals banked.
    for risky_stage in ("profile", "profile-decode", "decode-int8",
                        "sweep-full", "serving", "serving-sps1"):
        assert risky_stage in ran, f"{risky_stage} should have run"
    # Risky stages come strictly after EVERY non-risky stage, whatever the
    # non-risky ordering is. Two deliberate exceptions: 'mfu-refresh' is
    # the bank-freshness re-fire that closes the campaign AFTER the risky
    # tier (VERDICT r4 #8 — last_banked must reflect end-of-session
    # conditions), and the 'serving-ab' A/B arms are gated-tier (proven
    # r4 program classes) but grouped with the serving block for
    # same-session comparability.
    def is_risky(s):
        return (
            s in tpu_capture.RISKY_STAGES
            or s.startswith(("unroll", "serving"))
        ) and not s.startswith("serving-ab")

    first_risky = min(i for i, s in enumerate(ran) if is_risky(s))
    last_nonrisky = max(
        i for i, s in enumerate(ran)
        if not is_risky(s) and s != "mfu-refresh"
    )
    assert first_risky > last_nonrisky
    # The freshness refresh is the campaign's LAST stage.
    assert ran[-1] == "mfu-refresh"


def test_full_campaign_defers_risky_when_criticals_fail(
        tmp_path, monkeypatch):
    out = tmp_path / "cap.jsonl"
    ran = []

    def fake_run_cmd(name, cmd, timeout, out_f, wait_pool=None):
        ran.append(name)
        # Every stage fails (e.g. each inner run errors out).
        rec = {"stage": name, "rc": 1, "error": "boom"}
        out_f.write(json.dumps(rec) + "\n")
        out_f.flush()
        return rec

    monkeypatch.setattr(tpu_capture, "run_cmd", fake_run_cmd)
    monkeypatch.setattr(tpu_capture, "wait_for_backend",
                        lambda out_f, pool: {"ok": True})
    monkeypatch.setattr(
        "sys.argv", ["tpu_capture.py", "--out", str(out)])
    assert tpu_capture.main() == 0

    # No risky stage may have executed...
    for s in ran:
        assert s not in tpu_capture.RISKY_STAGES
        assert not s.startswith("unroll")
    # ...and each deferral left a structured skip record.
    recs = [json.loads(ln) for ln in open(out)]
    deferred = [r for r in recs if r.get("skipped")]
    assert {r["stage"] for r in deferred} >= {
        "profile", "profile-decode", "decode-int8", "sweep-full"}
    # Two legitimate deferral reasons: the critical-trio gate, and the
    # spec-kernel arm's own prerequisite (a clean serving-kernel record
    # in THIS campaign log — absent here by construction).
    assert all(
        "critical stages not yet banked" in r["error"]
        or "no clean serving-kernel record" in r["error"]
        for r in deferred
    )


def test_stage_proven_this_campaign_semantics(tmp_path):
    """The spec-kernel prerequisite gate: only a clean (rc==0, no error,
    unwedged) serving-kernel record from THIS campaign (after the last
    campaign-start marker) counts — wedged rc==0 records and stale
    prior-round successes must not unlock the class."""
    import json as _json

    log = tmp_path / "cap.jsonl"

    def write(recs):
        log.write_text("".join(_json.dumps(r) + "\n" for r in recs))

    proven = lambda: tpu_capture._stage_proven_this_campaign(
        str(log), "serving-kernel")
    # Missing log: nothing proven.
    assert not tpu_capture._stage_proven_this_campaign(
        str(tmp_path / "absent.jsonl"), "serving-kernel")
    # Clean record in this campaign: proven.
    write([{"stage": "campaign-start"},
           {"stage": "serving-kernel:sps32", "rc": 0}])
    assert proven()
    # rc==0 but the backend wedged during the stage: NOT proven.
    write([{"stage": "campaign-start"},
           {"stage": "serving-kernel:sps32", "rc": 0,
            "backend_wedged": True}])
    assert not proven()
    # Clean record from a PREVIOUS campaign only: NOT proven.
    write([{"stage": "campaign-start"},
           {"stage": "serving-kernel:sps32", "rc": 0},
           {"stage": "campaign-start"},
           {"stage": "mfu", "rc": 0}])
    assert not proven()
    # Failed in this campaign after succeeding earlier: NOT proven.
    write([{"stage": "campaign-start"},
           {"stage": "serving-kernel:sps32", "rc": 1, "error": "hang"}])
    assert not proven()


def test_missing_log_means_nothing_banked(tmp_path):
    assert tpu_capture._critical_banked(str(tmp_path / "absent.jsonl")) == set()


def test_latest_record_wins_over_stale_success(tmp_path):
    # The default log is append-only across campaigns: a round-N success
    # must not unlock risky probes when THIS campaign's rerun just failed
    # (code-review r4 finding on the first policy draft).
    out = tmp_path / "cap.jsonl"
    _write(out, [
        {"stage": "campaign-start"},
        {"stage": "mfu", "rc": 0, "value": 0.41},
        {"stage": "campaign-start"},
        {"stage": "mfu", "rc": -1, "error": "stage hung past 2520s"},
    ])
    assert tpu_capture._critical_banked(str(out)) == set()
    # ...and a later recovery re-banks it.
    with open(out, "a") as f:
        f.write(json.dumps({"stage": "mfu", "rc": 0, "value": 0.40}) + "\n")
    assert tpu_capture._critical_banked(str(out)) == {"mfu"}


def test_annotated_parity_record_does_not_count(tmp_path):
    # A parity record carrying BOTH a delta and a curation "error"
    # annotation (e.g. superseded as spurious) must not unlock the gate.
    out = tmp_path / "cap.jsonl"
    _write(out, [
        {"stage": "parity-tpu", "rc": 1, "delta": 1.1571,
         "error": "superseded: spurious step-count mismatch"},
    ])
    assert tpu_capture._critical_banked(str(out)) == set()


def test_perf_sweep_never_probes_wedge_combos():
    """The sweep grid must filter every known/adjacent wedge-class combo
    and the provably-over-ceiling capacity points, with honest reasons."""
    import itertools

    perf_sweep = importlib.import_module("perf_sweep")
    combos = [dict(zip(perf_sweep.GRID, v))
              for v in itertools.product(*perf_sweep.GRID.values())]
    probed = [c for c in combos if not perf_sweep._excluded(c)]
    # fused CE is excluded as an entire class (save_attn+fused hung twice
    # round 3; save_big+fused hung round 4 despite two prior clean
    # captures — the wedge is intermittent within the class):
    for c in probed:
        assert c["ce"] != "fused"
        assert not (c["remat"] == "none" and c["batch"] > 16)
    # Reasons are per-exclusion and distinguish wedge from capacity.
    assert "wedge" in perf_sweep._excluded(
        {"remat": "save_attn", "ce": "fused", "batch": 8})
    assert "wedge" in perf_sweep._excluded(
        {"remat": "save_big", "ce": "fused", "batch": 24})
    assert "OOM" in perf_sweep._excluded(
        {"remat": "none", "ce": "chunked", "batch": 32})
