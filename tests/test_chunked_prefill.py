"""Chunked prefill interleaved into decode windows.

The correctness bar (CPU-enforced, gather path): greedy tokens are
BIT-IDENTICAL with chunking on vs off at every pipeline depth, with and
without the prefix cache and speculative decoding — a chunk boundary
that moved a single token would be a commit-discipline bug, not a perf
trade-off. On top of identity: decode rows are never starved by chunk
traffic (a decode window dispatches on EVERY tick that has decode-phase
rows), the allocator stays conserved through mid-prefill cancellation
and preemption, and the pinned {chunk, tail} shape discipline serves
novel prompt lengths with ZERO new compiles once warm.
"""

import dataclasses
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.device import CompileWatcher

# The offline analyzer doubles as the trace-tree checker: import it as a
# module so the tests assert with EXACTLY the logic the CI gate runs.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_chunked", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
DRAFT_CFG = dataclasses.replace(CFG, n_layers=1, d_model=16, n_heads=2)

DEPTHS = [1, 2, 3]


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def draft_params():
    return transformer.init_params(DRAFT_CFG, jax.random.key(99))


def _prompts(n, lengths=(5, 19, 14, 7, 23, 3, 16, 6)):
    rng = np.random.default_rng(42)
    out = []
    for i in range(n):
        p = int(lengths[i % len(lengths)])
        out.append(rng.integers(0, CFG.vocab_size, size=p).tolist())
    return out


def _reference_greedy(params, cfg, prompt, n_new):
    toks = generate(
        params, cfg, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


def _run(params, prompts, n_new, *, chunk, depth, pipeline=True, **kw):
    eng = ServingEngine(
        params, CFG, temperature=0.0, pipeline_depth=depth,
        prefill_chunk_tokens=chunk, **kw,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run(pipeline=pipeline)
    return [out[r] for r in rids], eng


# -- bit-identity: chunked on vs off --------------------------------------


@pytest.mark.parametrize("depth", DEPTHS)
@pytest.mark.parametrize("cache", [False, True])
def test_chunked_identity(params, depth, cache):
    """Chunked vs monolithic prefill over admission churn (more requests
    than rows) must agree bit-for-bit, and with the reference greedy.
    A 6-token budget makes most prompts take several chunks and forces
    per-tick deferrals (the budget loop), not just the happy path."""
    prompts = _prompts(6)
    n_new = 9
    off, _ = _run(
        params, prompts, n_new, chunk=0, depth=depth,
        max_batch=2, n_blocks=32, block_size=8, steps_per_sched=4,
        prefix_cache=cache,
    )
    on, eng = _run(
        params, prompts, n_new, chunk=6, depth=depth,
        max_batch=2, n_blocks=32, block_size=8, steps_per_sched=4,
        prefix_cache=cache,
    )
    assert on == off
    assert eng.stats["prefill_chunks"] > len(prompts)
    for got, p in zip(on, prompts):
        assert got == _reference_greedy(params, CFG, p, n_new)
    assert eng.stats["windows_reaped"] == eng.stats["windows"]


@pytest.mark.parametrize("depth", DEPTHS)
@pytest.mark.parametrize("cache", [False, True])
def test_chunked_identity_speculative(params, draft_params, depth, cache):
    """Same identity bar through the speculative scheduler: chunk commits
    must never disturb the draft/target verify state of rows excluded
    from a spec round mid-prefill."""
    prompts = _prompts(5)
    n_new = 8
    spec = dict(draft_params=draft_params, draft_cfg=DRAFT_CFG, spec_k=2)
    off, _ = _run(
        params, prompts, n_new, chunk=0, depth=depth,
        max_batch=2, n_blocks=32, block_size=8, prefix_cache=cache, **spec,
    )
    on, eng = _run(
        params, prompts, n_new, chunk=5, depth=depth,
        max_batch=2, n_blocks=32, block_size=8, prefix_cache=cache, **spec,
    )
    assert on == off
    assert eng.stats["prefill_chunks"] > len(prompts)
    for got, p in zip(on, prompts):
        assert got == _reference_greedy(params, CFG, p, n_new)


def test_chunked_identity_sync_scheduler(params):
    """The synchronous scheduler (run(pipeline=False)) rides the same
    chunk lane with host-resolved first tokens — identical too."""
    prompts = _prompts(4)
    off, _ = _run(
        params, prompts, 7, chunk=0, depth=1, pipeline=False,
        max_batch=2, n_blocks=32, block_size=8,
    )
    on, eng = _run(
        params, prompts, 7, chunk=4, depth=1, pipeline=False,
        max_batch=2, n_blocks=32, block_size=8,
    )
    assert on == off
    assert eng.stats["prefill_chunks"] > 0


def test_chunk_stats_and_stop_token(params):
    """Token accounting: every prompt token goes through the chunk lane
    exactly once (no cache, no preemption), and stop tokens landing after
    a chunked prefill still truncate identically."""
    prompts = _prompts(3)
    n_new = 12
    refs = [_reference_greedy(params, CFG, p, n_new) for p in prompts]
    stop = refs[0][4]
    off, _ = _run(
        params, prompts, n_new, chunk=0, depth=2,
        max_batch=3, n_blocks=32, block_size=8, stop_token=stop,
    )
    on, eng = _run(
        params, prompts, n_new, chunk=6, depth=2,
        max_batch=3, n_blocks=32, block_size=8, stop_token=stop,
    )
    assert on == off
    assert eng.stats["prefill_chunk_tokens"] == sum(len(p) for p in prompts)
    assert eng.stats["prefill_tokens"] == sum(len(p) for p in prompts)


# -- decode windows are never starved by chunk traffic ---------------------


def test_decode_never_skipped_while_chunks_stream(params):
    """Structural starvation guard: on EVERY pipeline tick where decode-
    phase rows exist, a decode window is dispatched — chunk programs ride
    ALONGSIDE decode windows, never instead of them (so a decode row can
    never be skipped even once, let alone two consecutive windows). A
    2-token budget against 19+ token prompts maximizes chunk pressure."""
    prompts = _prompts(4, lengths=(19, 23, 16, 14))
    eng = ServingEngine(
        params, CFG, temperature=0.0, pipeline_depth=2,
        prefill_chunk_tokens=2, max_batch=2, n_blocks=48, block_size=8,
        steps_per_sched=2,
    )
    decode_dispatches = []
    orig_window = eng._dispatch_window

    def spy_window(*a, **kw):
        decode_dispatches.append(True)
        return orig_window(*a, **kw)

    eng._dispatch_window = spy_window
    rids = [eng.submit(p, 8) for p in prompts]
    skipped = []
    while eng.has_work() or eng._inflight:
        had_decode = eng._n_decode_rows() > 0
        before = len(decode_dispatches)
        eng.pipeline_tick()
        if had_decode and len(decode_dispatches) == before:
            skipped.append(eng.stats["windows"])
    assert not skipped, f"decode window skipped at {skipped}"
    out = eng.finished
    assert set(out) == set(rids)
    # The tiny budget really did defer work across ticks...
    assert eng.stats["chunk_deferrals"] > 0
    # ...and chunks genuinely interleaved with live decode windows.
    assert eng.stats["chunk_windows_interleaved"] > 0


# -- allocator conservation through mid-prefill teardown -------------------


def _tick_until_mid_prefill(eng):
    """Advance the pipelined scheduler until some row is mid-prefill."""
    for _ in range(50):
        eng.pipeline_tick()
        mid = [
            r for r in eng.rows
            if r is not None and r.prefill_pos is not None
        ]
        if mid:
            return mid[0]
    raise AssertionError("no row ever entered the mid-prefill phase")


@pytest.mark.parametrize("cache", [False, True])
def test_cancel_mid_prefill_conserves_blocks(params, cache):
    """Cancelling a request whose prompt is only partially streamed must
    free (or cache-publish) exactly the blocks it held: after the drain,
    idle + cold-cached == n_blocks - 1 and a cache flush returns every
    block to the free list."""
    n_blocks = 32
    prompts = _prompts(3, lengths=(23, 5, 19))
    eng = ServingEngine(
        params, CFG, temperature=0.0, pipeline_depth=2,
        prefill_chunk_tokens=3, max_batch=2, n_blocks=n_blocks,
        block_size=8, prefix_cache=cache,
    )
    rids = [eng.submit(p, 6) for p in prompts]
    victim = _tick_until_mid_prefill(eng)
    assert 0 < victim.prefill_pos < len(victim.prompt)
    assert eng.cancel(victim.rid)
    out = eng.run(pipeline=True)
    assert set(out) == set(rids) - {victim.rid}
    for rid, p in zip(rids, prompts):
        if rid != victim.rid:
            assert out[rid] == _reference_greedy(params, CFG, p, 6)
    cold = eng.prefix_cache.evictable if cache else 0
    assert eng.alloc.available + cold == n_blocks - 1
    if cache:
        eng.prefix_cache.flush()
        assert eng.alloc.available == n_blocks - 1


def test_preemption_mid_decode_with_chunking_conserves_blocks(params):
    """A pool too small for both rows' growth forces preemption while the
    chunk lane is active: recompute-on-resume must re-stream the victim's
    committed prompt+tokens through chunks and still match the reference
    greedy, with the allocator fully accounted for at drain."""
    n_blocks = 8
    prompts = _prompts(2, lengths=(12, 10))
    n_new = 24
    on, eng = _run(
        params, prompts, n_new, chunk=4, depth=2,
        max_batch=2, n_blocks=n_blocks, block_size=8, steps_per_sched=4,
    )
    assert eng.stats["preemptions"] >= 1
    for got, p in zip(on, prompts):
        assert got == _reference_greedy(params, CFG, p, n_new)
    assert eng.alloc.available == n_blocks - 1
    # Rework accounting: the resumed prompt's re-streamed tokens are
    # counted as recompute, not fresh prefill demand.
    assert eng.stats["preempted_tokens_recomputed"] > 0


# -- pinned {chunk, tail} shapes: zero recompiles once warm ----------------


def test_no_recompiles_for_novel_prompt_lengths_once_warm(params):
    """Monolithic prefill compiled one program per prompt-length bucket;
    the chunk lane pins every dispatch to the SAME (row-bucket, chunk)
    shape — tails pad into the chunk bucket — so an engine warmed on a
    handful of lengths serves arbitrary novel lengths with zero new
    compiles."""
    eng = ServingEngine(
        params, CFG, temperature=0.0, pipeline_depth=2,
        prefill_chunk_tokens=8, max_batch=2, n_blocks=48, block_size=8,
    )
    # Warm: a solo request (row-bucket 1), then a full batch (row-bucket
    # 2) — covers every group shape the steady state can produce.
    r0 = eng.submit(_prompts(1, lengths=(11,))[0], 6)
    eng.run(pipeline=True)
    warm_prompts = _prompts(3, lengths=(17, 9, 21))
    for p in warm_prompts:
        eng.submit(p, 6)
    eng.run(pipeline=True)
    assert r0 in eng.finished

    w = CompileWatcher().start()
    try:
        before = w.summary()["compiles"]
        # Novel lengths (never seen above), served both solo and batched.
        novel = _prompts(4, lengths=(13, 26, 7, 18))
        rids = [eng.submit(p, 6) for p in novel]
        out = eng.run(pipeline=True)
        assert set(rids) <= set(out)
        assert w.summary()["compiles"] == before, (
            "novel prompt lengths recompiled the chunk lane"
        )
    finally:
        w.stop()
    for rid, p in zip(rids, novel):
        assert out[rid] == _reference_greedy(params, CFG, p, 6)


# -- observability: spans, waterfall, metrics, decision join ---------------


def test_chunk_spans_waterfall_metrics_and_decision_join(params):
    """The full observability wiring of the chunk lane through a traced
    EngineLoop: every done trace tree is complete with `req.prefill_chunk`
    spans standing in for the monolithic prefill span, the waterfall grows
    a `chunked_prefill_s` segment that still sums to e2e within 1%, the
    typed chunk counters land in /metrics, and every `defer_prefill_chunk`
    decision joins to a known trace (the --capacity --strict contract)."""
    from pretraining_llm_tpu.frontend.admission import AdmissionController
    from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
    from pretraining_llm_tpu.observability.events import EventBus
    from pretraining_llm_tpu.observability.export import lint_exposition
    from pretraining_llm_tpu.observability.metrics import MetricsRegistry
    from pretraining_llm_tpu.observability.spans import SpanRecorder
    from pretraining_llm_tpu.observability.tracing import Tracer

    eng = ServingEngine(
        params, CFG, temperature=0.0, pipeline_depth=2,
        prefill_chunk_tokens=2, max_batch=2, n_blocks=48, block_size=8,
        steps_per_sched=2,
    )
    recorder = SpanRecorder()
    registry = MetricsRegistry("pllm_serving_")
    with EngineLoop(
        eng, admission=AdmissionController(max_queue_depth=8),
        bus=EventBus(), tracer=Tracer(recorder, sample=1.0, seed=5),
        registry=registry,
    ) as loop:
        handles = [loop.submit(p, 6) for p in _prompts(4, lengths=(19, 23, 16, 21))]
        for h in handles:
            assert h.result(timeout=300)[0] == "done"
        metrics_text = registry.render(extra_gauges=loop.metrics())
        defers = [
            r for r in eng.decisions.tail()
            if r["decision"] == "defer_prefill_chunk"
        ]

    trace = recorder.to_chrome_trace()
    groups = obs_report.group_request_spans(trace)
    assert len(groups) == 4
    saw_chunk_segment = False
    for tid, spans in groups.items():
        assert obs_report.check_trace_tree(tid, spans) == [], tid
        names = {s["name"] for s in spans}
        assert "req.prefill_chunk" in names
        assert "req.prefill" not in names  # the lane fully replaced it
        wf = obs_report.request_waterfall(tid, spans)
        assert abs(wf["sum_error_s"]) <= max(1e-6, 0.01 * wf["e2e_s"])
        if wf["segments"]["chunked_prefill_s"] > 0:
            saw_chunk_segment = True
    assert saw_chunk_segment

    assert lint_exposition(metrics_text) == []
    for counter, want in (
        ("prefill_chunks_total", eng.stats["prefill_chunks"]),
        ("prefill_chunk_tokens_total", eng.stats["prefill_chunk_tokens"]),
        ("chunk_windows_interleaved_total",
         eng.stats["chunk_windows_interleaved"]),
        ("chunk_windows_dedicated_total",
         eng.stats["chunk_windows_dedicated"]),
    ):
        assert f"pllm_serving_{counter} {float(want)}" in metrics_text, counter
    assert eng.stats["prefill_chunks"] > 0

    # The 2-token budget against 16+ token prompts MUST have deferred,
    # and every deferral names a trace the span export knows — the join
    # obs_report --capacity --strict enforces.
    assert defers
    for rec in defers:
        assert rec["trace_id"] in groups, rec


# -- knob validation -------------------------------------------------------


def test_negative_chunk_tokens_rejected(params):
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ServingEngine(
            params, CFG, max_batch=2, n_blocks=16, block_size=8,
            prefill_chunk_tokens=-1,
        )


def test_serving_config_chunk_knob():
    from pretraining_llm_tpu.config import ServingConfig

    assert ServingConfig().prefill_chunk_tokens == 0
    assert ServingConfig(prefill_chunk_tokens=64).prefill_chunk_tokens == 64
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ServingConfig(prefill_chunk_tokens=-2)
