"""Config layer: presets, validation, overrides, analytic param counts."""

import dataclasses

import pytest

from pretraining_llm_tpu.config import Config, MeshConfig, ModelConfig, get_preset, list_presets


def test_presets_exist():
    names = list_presets()
    for required in (
        "gpt2-124m",
        "gpt2-350m-dp",
        "gpt2-1p3b-fsdp",
        "llama-1b",
        "gpt2-8k-sp",
        "reference-3b",
        "tiny",
    ):
        assert required in names


def test_reference_3b_param_count():
    # SURVEY.md §2.5: the reference's default config is 3.161B params
    # (103.0M tok-embed + 1.0M pos-embed + 64 x 46.16M blocks + 103.1M lm_head).
    cfg = get_preset("reference-3b").model
    n = cfg.num_params()
    assert abs(n - 3.161e9) / 3.161e9 < 0.01, n


def test_gpt2_124m_param_count():
    cfg = get_preset("gpt2-124m").model
    n = cfg.num_params()
    assert abs(n - 124e6) / 124e6 < 0.05, n


def test_unknown_override_rejected():
    cfg = get_preset("tiny")
    with pytest.raises(KeyError):
        cfg.with_overrides({"model.not_a_key": 1})
    with pytest.raises(KeyError):
        cfg.with_overrides({"nonsection.x": 1})


def test_override_applies():
    cfg = get_preset("tiny").with_overrides({"model.n_layers": 3, "train.lr": 1e-5})
    assert cfg.model.n_layers == 3
    assert cfg.train.lr == 1e-5


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        ModelConfig(activation="tanh")
    with pytest.raises(ValueError):
        ModelConfig(d_model=30, n_heads=4)
    with pytest.raises(ValueError):
        ModelConfig(tie_embeddings=True, lm_head_bias=True)


def test_mesh_sizes():
    assert MeshConfig(data=-1, fsdp=2).sizes(8) == (4, 2, 1, 1, 1, 1)
    assert MeshConfig(data=2, fsdp=2, tensor=2).sizes(8) == (2, 2, 2, 1, 1, 1)
    assert MeshConfig(data=-1, expert=4).sizes(8) == (2, 1, 1, 1, 4, 1)
    with pytest.raises(ValueError):
        MeshConfig(data=3).sizes(8)


def test_moe_validation():
    with pytest.raises(ValueError):
        ModelConfig(n_experts=4, experts_per_token=5)
    with pytest.raises(ValueError):
        ModelConfig(n_experts=4, expert_capacity_factor=0.0)
    ModelConfig(n_experts=4, experts_per_token=2)  # valid


def test_json_roundtrip():
    cfg = get_preset("llama-1b")
    restored = Config.from_json(cfg.to_json())
    assert restored == cfg


def test_serving_config_wiring():
    from pretraining_llm_tpu.config import ServingConfig

    cfg = get_preset("tiny").with_overrides(
        {"serving.pipeline_depth": 3, "serving.admit_batch": 4}
    )
    assert cfg.serving.pipeline_depth == 3
    assert cfg.serving.admit_batch == 4
    assert Config.from_json(cfg.to_json()).serving == cfg.serving
    # Pre-serving checkpoints (no "serving" section) load with defaults.
    import json as _json

    raw = _json.loads(get_preset("tiny").to_json())
    raw.pop("serving")
    legacy = Config.from_json(_json.dumps(raw))
    assert legacy.serving == ServingConfig()
    with pytest.raises(ValueError):
        ServingConfig(pipeline_depth=0)
    with pytest.raises(ValueError):
        ServingConfig(admit_batch=-1)


# Perf-preset intent table. Round 4 found the 350M preset silently running
# NAIVE attention for every pre-2026-08-01 measurement (only gpt2-124m set
# attention_impl="flash") — caught by a human reading a profile. This table
# makes that a class that cannot recur: every preset used for performance
# work must match its declared attention/remat/CE intent exactly, so a
# silently-defaulted knob fails CI instead of burning a hardware session.
# "tiny" is deliberately absent (test-only, perf knobs irrelevant).
_PERF_INTENT = {
    #                   attention_impl  remat             ce_impl
    "gpt2-124m":       ("flash",        "none",           "chunked"),
    "gpt2-350m-dp":    ("flash",        "none",           "chunked"),
    "gpt2-1p3b-fsdp":  ("flash",        "dots_saveable",  "chunked"),
    "llama-1b":        ("flash",        "dots_saveable",  "chunked"),
    "gpt2-8k-sp":      ("ring",         "save_attn",      "chunked"),
    "gpt2-8k-gqa":     ("ring",         "save_attn",      "chunked"),
    "reference-3b":    ("flash",        "dots_saveable",  "chunked"),
    "llama3-1b-gqa":   ("flash",        "dots_saveable",  "chunked"),
    "moe-8x350m":      ("flash",        "dots_saveable",  "chunked"),
}


def test_every_perf_preset_has_declared_intent():
    """Every registered preset is either in the intent table or 'tiny'."""
    missing = set(list_presets()) - set(_PERF_INTENT) - {"tiny"}
    assert not missing, (
        f"presets {sorted(missing)} have no declared perf intent; add them to "
        "_PERF_INTENT so attention/remat/CE knobs cannot silently default"
    )


@pytest.mark.parametrize("name", sorted(_PERF_INTENT))
def test_preset_perf_knobs_match_intent(name):
    attn, remat, ce = _PERF_INTENT[name]
    m = get_preset(name).model
    assert m.attention_impl == attn, (
        f"{name}: attention_impl={m.attention_impl!r}, intent {attn!r} "
        "(the round-4 350M silent-naive bug class)"
    )
    assert m.remat == remat, f"{name}: remat={m.remat!r}, intent {remat!r}"
    assert m.ce_impl == ce, f"{name}: ce_impl={m.ce_impl!r}, intent {ce!r}"
