"""Config layer: presets, validation, overrides, analytic param counts."""

import dataclasses

import pytest

from pretraining_llm_tpu.config import Config, MeshConfig, ModelConfig, get_preset, list_presets


def test_presets_exist():
    names = list_presets()
    for required in (
        "gpt2-124m",
        "gpt2-350m-dp",
        "gpt2-1p3b-fsdp",
        "llama-1b",
        "gpt2-8k-sp",
        "reference-3b",
        "tiny",
    ):
        assert required in names


def test_reference_3b_param_count():
    # SURVEY.md §2.5: the reference's default config is 3.161B params
    # (103.0M tok-embed + 1.0M pos-embed + 64 x 46.16M blocks + 103.1M lm_head).
    cfg = get_preset("reference-3b").model
    n = cfg.num_params()
    assert abs(n - 3.161e9) / 3.161e9 < 0.01, n


def test_gpt2_124m_param_count():
    cfg = get_preset("gpt2-124m").model
    n = cfg.num_params()
    assert abs(n - 124e6) / 124e6 < 0.05, n


def test_unknown_override_rejected():
    cfg = get_preset("tiny")
    with pytest.raises(KeyError):
        cfg.with_overrides({"model.not_a_key": 1})
    with pytest.raises(KeyError):
        cfg.with_overrides({"nonsection.x": 1})


def test_override_applies():
    cfg = get_preset("tiny").with_overrides({"model.n_layers": 3, "train.lr": 1e-5})
    assert cfg.model.n_layers == 3
    assert cfg.train.lr == 1e-5


def test_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        ModelConfig(activation="tanh")
    with pytest.raises(ValueError):
        ModelConfig(d_model=30, n_heads=4)
    with pytest.raises(ValueError):
        ModelConfig(tie_embeddings=True, lm_head_bias=True)


def test_mesh_sizes():
    assert MeshConfig(data=-1, fsdp=2).sizes(8) == (4, 2, 1, 1, 1, 1)
    assert MeshConfig(data=2, fsdp=2, tensor=2).sizes(8) == (2, 2, 2, 1, 1, 1)
    assert MeshConfig(data=-1, expert=4).sizes(8) == (2, 1, 1, 1, 4, 1)
    with pytest.raises(ValueError):
        MeshConfig(data=3).sizes(8)


def test_moe_validation():
    with pytest.raises(ValueError):
        ModelConfig(n_experts=4, experts_per_token=5)
    with pytest.raises(ValueError):
        ModelConfig(n_experts=4, expert_capacity_factor=0.0)
    ModelConfig(n_experts=4, experts_per_token=2)  # valid


def test_json_roundtrip():
    cfg = get_preset("llama-1b")
    restored = Config.from_json(cfg.to_json())
    assert restored == cfg
