"""Data pipeline: memmap loader, sharding, determinism, prefetch."""

import numpy as np
import pytest

from pretraining_llm_tpu.data import loader


@pytest.fixture()
def token_file(tmp_path):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 1000, size=10_000, dtype=np.uint16)
    path = tmp_path / "train.bin"
    tokens.tofile(path)
    return str(path), tokens


def test_batch_shapes_and_shift(token_file):
    path, tokens = token_file
    it = loader.get_batch_iterator(path, batch_size=4, context_length=16, seed=0)
    x, y = next(it)
    assert x.shape == (4, 16) and y.shape == (4, 16)
    assert x.dtype == np.int32
    # y is x shifted by one in the source stream
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])


def test_seeded_determinism(token_file):
    path, _ = token_file
    a = loader.get_batch_iterator(path, 4, 16, seed=7)
    b = loader.get_batch_iterator(path, 4, 16, seed=7)
    for _ in range(3):
        xa, ya = next(a)
        xb, yb = next(b)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    c = loader.get_batch_iterator(path, 4, 16, seed=8)
    assert not np.array_equal(next(a)[0], next(c)[0])


def test_rng_state_roundtrip(token_file):
    path, _ = token_file
    it = loader.get_batch_iterator(path, 4, 16, seed=7)
    next(it)
    saved = it.state()
    x1, _ = next(it)
    it2 = loader.get_batch_iterator(path, 4, 16, seed=999)
    it2.set_state(saved)
    x2, _ = next(it2)
    np.testing.assert_array_equal(x1, x2)


def test_contiguous_sharding(token_file):
    """Shards draw from disjoint contiguous regions — sequences stay intact
    (the reference's strided shard destroys them, SURVEY §A B1)."""
    path, tokens = token_file
    it0 = loader.get_batch_iterator(path, 8, 16, seed=0, shard_index=0, shard_count=2)
    it1 = loader.get_batch_iterator(path, 8, 16, seed=0, shard_index=1, shard_count=2)
    x0, _ = next(it0)
    x1, _ = next(it1)
    # Every sampled window must be a verbatim slice of the original stream.
    flat = tokens.astype(np.int32)
    for row in np.concatenate([x0, x1]):
        matches = np.where(flat[: len(flat) - 16] == row[0])[0]
        assert any(np.array_equal(flat[m : m + 16], row) for m in matches)
    # Shard 1's windows come from the second half (minus overlap).
    src1 = tokens[len(tokens) // 2 :].astype(np.int32)
    row = x1[0]
    matches = np.where(src1[: len(src1) - 16] == row[0])[0]
    assert any(np.array_equal(src1[m : m + 16], row) for m in matches)


def test_too_small_file_rejected(tmp_path):
    path = tmp_path / "tiny.bin"
    np.arange(10, dtype=np.uint16).tofile(path)
    with pytest.raises(ValueError, match="context_length"):
        loader.get_batch_iterator(str(path), 1, 64)


def test_synthetic_stream_is_learnable_and_deterministic():
    a = loader.synthetic_iterator(64, 32, 4, seed=3)
    b = loader.synthetic_iterator(64, 32, 4, seed=3)
    xa, _ = next(a)
    xb, _ = next(b)
    np.testing.assert_array_equal(xa, xb)
    # Markov structure: conditional entropy < uniform
    data = a.source.data
    assert len(np.unique(data)) > 8


def test_device_prefetch_passthrough(token_file):
    path, _ = token_file
    it = loader.get_batch_iterator(path, 2, 8, seed=0)
    ref = loader.get_batch_iterator(path, 2, 8, seed=0)
    pref = loader.device_prefetch(it, lambda b: b, depth=2)
    for _ in range(5):
        x1, y1 = next(pref)
        x2, y2 = next(ref)
        np.testing.assert_array_equal(x1, x2)


def test_device_prefetch_propagates_errors():
    def bad_iter():
        yield (np.zeros((1, 2)), np.zeros((1, 2)))
        raise RuntimeError("loader exploded")

    pref = loader.device_prefetch(bad_iter(), lambda b: b, depth=1)
    next(pref)
    with pytest.raises(RuntimeError, match="loader exploded"):
        next(pref)


def test_device_prefetcher_state_is_consumed_frontier(token_file):
    """VERDICT r2 #8: state() must report the RNG frontier of the batches
    the consumer actually TOOK — not the producer's run-ahead — so a
    checkpoint + resume replays the queue-resident batches identically."""
    path, _ = token_file
    it = loader.get_batch_iterator(path, 2, 8, seed=9)
    ref = loader.get_batch_iterator(path, 2, 8, seed=9)
    pref = loader.DevicePrefetcher(it, lambda b: b, depth=4)

    got = [next(pref) for _ in range(3)]
    want = [next(ref) for _ in range(3)]
    for (x1, y1), (x2, y2) in zip(got, want):
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)

    # Resume from the prefetcher's frontier: the continuation equals the
    # synchronous iterator's (which consumed exactly 3 batches).
    resumed = loader.get_batch_iterator(path, 2, 8, seed=9)
    resumed.set_state(pref.state())
    for _ in range(3):
        x_r, y_r = next(resumed)
        x_w, y_w = next(ref)
        np.testing.assert_array_equal(x_r, x_w)
        np.testing.assert_array_equal(y_r, y_w)
    pref.close()


def test_device_prefetch_stops_after_delivered_error():
    """After surfacing the worker's exception, the stream terminates with
    StopIteration — it must never block forever on the drained queue."""
    def bad_iter():
        yield (np.zeros((1, 2)), np.zeros((1, 2)))
        raise RuntimeError("loader exploded")

    pref = loader.device_prefetch(bad_iter(), lambda b: b, depth=1)
    next(pref)
    with pytest.raises(RuntimeError, match="loader exploded"):
        next(pref)
    with pytest.raises(StopIteration):
        next(pref)


def test_device_prefetch_stopiteration_is_permanent():
    """Iterator contract: after exhaustion, EVERY next() raises StopIteration
    (the old generator implementation did; consumers may probe repeatedly)."""
    def finite():
        yield (np.zeros((1, 2)), np.zeros((1, 2)))

    pref = loader.device_prefetch(finite(), lambda b: b, depth=1)
    next(pref)
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(pref)


def test_mixture_iterator_weights_and_exact_resume(tmp_path):
    """Weighted multi-corpus sampling (beyond-reference): rows draw their
    source by weight; the whole mixture checkpoints through ONE RNG state
    and resumes bit-exactly."""
    # Two distinguishable corpora: disjoint token-id ranges.
    a = (np.arange(40_000) % 100).astype(np.uint16)          # ids 0-99
    bpath_ids = (np.arange(40_000) % 100 + 200).astype(np.uint16)  # ids 200-299
    pa, pb = tmp_path / "a.bin", tmp_path / "b.bin"
    a.tofile(pa)
    bpath_ids.tofile(pb)

    spec = f"{pa}:3,{pb}:1"
    it = loader.get_batch_iterator(spec, 16, 8, seed=11)
    from pretraining_llm_tpu.data.loader import MixtureIterator

    assert isinstance(it, MixtureIterator)
    counts = [0, 0]
    for _ in range(60):
        x, y = next(it)
        assert x.shape == (16, 8)
        from_a = (x[:, 0] < 100)
        counts[0] += int(from_a.sum())
        counts[1] += int((~from_a).sum())
        # Shift-by-one target structure holds per row regardless of source.
        np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    frac_a = counts[0] / sum(counts)
    assert 0.70 < frac_a < 0.80, frac_a  # weight 3:1 -> 0.75 expected

    # Exact resume through the single RNG state.
    st = it.state()
    want = [next(it) for _ in range(3)]
    it2 = loader.get_batch_iterator(spec, 16, 8, seed=11)
    it2.set_state(st)
    for wx, wy in want:
        gx, gy = next(it2)
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gy, wy)


def test_mixture_spec_parsing():
    from pretraining_llm_tpu.data.loader import parse_mixture

    assert parse_mixture("a.bin:3,b.bin:1") == [("a.bin", 3.0), ("b.bin", 1.0)]
    assert parse_mixture("a.bin,b.bin") == [("a.bin", 1.0), ("b.bin", 1.0)]
    assert parse_mixture("a.bin:0.25, b.bin:0.75") == [
        ("a.bin", 0.25), ("b.bin", 0.75),
    ]
    with pytest.raises(ValueError):
        parse_mixture(",")


def test_mixture_detection_and_malformed_entries(tmp_path):
    from pretraining_llm_tpu.data.loader import is_mixture, parse_mixture

    # A real file whose NAME contains a comma is not a mixture.
    weird = tmp_path / "run 1,final.bin"
    (np.arange(100) % 7).astype(np.uint16).tofile(weird)
    assert not is_mixture(str(weird))
    assert is_mixture("a.bin:3,b.bin:1")
    assert not is_mixture("plain.bin")

    with pytest.raises(ValueError, match="malformed"):
        parse_mixture("a.bin:3,:1")  # empty path
    with pytest.raises(ValueError, match="malformed"):
        parse_mixture("a.bin:,b.bin:1")  # dangling ':'
