"""Packed-document attention masking (doc_mask_token).

The reference (and GPT-2/3-style packing) lets attention cross document
boundaries inside a packed window; with ``doc_mask_token`` set, attention
is confined to each document. The load-bearing invariant is ISOLATION:
tokens of a later document produce identical activations regardless of
what the earlier documents contained.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.ops.attention import naive_attention
from pretraining_llm_tpu.ops.flash_attention import blockwise_attention
from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention


def _masked_reference(q, k, v, seg):
    """Dense reference: causal AND same-document."""
    b, t, h, d = q.shape
    g = k.shape[2]
    kr = jnp.repeat(k, h // g, axis=2)
    vr = jnp.repeat(v, h // g, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kr) / d**0.5
    causal = jnp.tril(jnp.ones((t, t), bool))
    mask = causal[None, None] & (seg[:, None, :, None] == seg[:, None, None, :])
    s = jnp.where(mask, s, -jnp.inf)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), vr)


@pytest.fixture(scope="module")
def qkv_seg():
    b, t, h, g, d = 2, 256, 4, 2, 32
    q = jax.random.normal(jax.random.key(1), (b, t, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (b, t, g, d), jnp.float32)
    v = jax.random.normal(jax.random.key(3), (b, t, g, d), jnp.float32)
    # different boundaries per row; row 1 has three documents
    seg = jnp.stack([
        jnp.where(jnp.arange(t) < 100, 0, 1),
        jnp.clip(jnp.searchsorted(jnp.array([60, 177]), jnp.arange(t), side="right"), 0, 2),
    ]).astype(jnp.int32)
    return q, k, v, seg


def test_naive_segments_match_reference(qkv_seg):
    q, k, v, seg = qkv_seg
    got = naive_attention(q, k, v, segments=seg)
    np.testing.assert_allclose(got, _masked_reference(q, k, v, seg), atol=2e-5)


@pytest.mark.parametrize("blocks", [(0, 0), (128, 64)])
def test_blockwise_segments_match_reference(qkv_seg, blocks):
    q, k, v, seg = qkv_seg
    bq, bk = blocks
    got = blockwise_attention(q, k, v, segments=seg, block_q=bq, block_kv=bk)
    np.testing.assert_allclose(got, _masked_reference(q, k, v, seg), atol=2e-5)


@pytest.mark.parametrize("blocks", [(0, 0), (128, 128)])
def test_pallas_segments_match_reference_fwd_and_grad(qkv_seg, blocks):
    """Interpret-mode kernel vs dense reference: forward AND all three
    gradients, on both the multi-block and fused single-block backward
    paths (blocks=(0,0) -> one 256-block -> fused kernel)."""
    q, k, v, seg = qkv_seg
    bq, bk = blocks

    def kern(q, k, v):
        return pallas_flash_attention(
            q, k, v, segments=seg, block_q=bq, block_kv=bk, interpret=True
        )

    np.testing.assert_allclose(
        kern(q, k, v), _masked_reference(q, k, v, seg), atol=2e-5
    )
    gk = jax.grad(lambda *a: (kern(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda *a: (_masked_reference(*a, seg) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(a, b, atol=2e-4)


def _packed_tokens(cfg, key, n_prefix):
    """Tokens with a separator at position n_prefix - 1 (sep id = 0)."""
    t = cfg.context_length
    toks = jax.random.randint(key, (1, t), 1, cfg.vocab_size)
    return toks.at[0, n_prefix - 1].set(cfg.doc_mask_token)


@pytest.mark.parametrize("impl", ["naive", "flash"])
def test_model_cross_document_isolation(impl):
    """The second document's logits are IDENTICAL regardless of the first
    document's content (and measurably different without doc masking)."""
    cfg = dataclasses.replace(
        get_preset("tiny").model,
        compute_dtype="float32",
        attention_impl=impl,
        doc_mask_token=0,
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    cut = 20  # separator at index 19; doc 2 starts at 20
    a = _packed_tokens(cfg, jax.random.key(1), cut)
    # Same doc-2 suffix, totally different doc-1 prefix.
    b = a.at[0, : cut - 1].set(
        jax.random.randint(jax.random.key(2), (cut - 1,), 1, cfg.vocab_size)
    )
    la, _ = transformer.forward(params, a, cfg)
    lb, _ = transformer.forward(params, b, cfg)
    np.testing.assert_array_equal(
        np.asarray(la[0, cut:]), np.asarray(lb[0, cut:])
    )
    # Sanity: WITHOUT doc masking the same probe leaks.
    cfg_off = dataclasses.replace(cfg, doc_mask_token=-1)
    la_off, _ = transformer.forward(params, a, cfg_off)
    lb_off, _ = transformer.forward(params, b, cfg_off)
    assert float(jnp.abs(la_off[0, cut:] - lb_off[0, cut:]).max()) > 1e-4


def test_model_flash_equals_naive_with_doc_mask():
    toks = None
    logits = {}
    for impl in ("naive", "flash"):
        cfg = dataclasses.replace(
            get_preset("tiny").model,
            compute_dtype="float32",
            attention_impl=impl,
            doc_mask_token=0,
        )
        params = transformer.init_params(cfg, jax.random.key(0))
        if toks is None:
            toks = _packed_tokens(cfg, jax.random.key(5), 13)
        logits[impl], _ = transformer.forward(params, toks, cfg)
    np.testing.assert_allclose(
        logits["naive"], logits["flash"], atol=2e-4, rtol=1e-4
    )


def test_doc_mask_trains():
    """loss_fn path: finite loss, finite grads, loss decreases."""
    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.training import train_step as ts

    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dataclasses.replace(tiny.model, doc_mask_token=0),
        train=dataclasses.replace(tiny.train, lr=3e-3, batch_size=8),
    )
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, None)
    it = loader.synthetic_iterator(
        cfg.model.vocab_size, cfg.model.context_length, 8, seed=0
    )
    first = last = None
    for i in range(15):
        x, y = next(it)
        state, m = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert np.isfinite(last)
    assert last < first - 0.3, (first, last)


def test_doc_mask_checkpoint_still_generates():
    """A model trained with packing masks must DECODE (the e2e contract):
    generate() sanitizes doc_mask_token (a decode session is one document)
    and matches the unmasked-config generation exactly."""
    from pretraining_llm_tpu.generation.generate import generate

    cfg = dataclasses.replace(
        get_preset("tiny").model, compute_dtype="float32", doc_mask_token=0
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(9), (2, 8), 1, cfg.vocab_size)
    got = np.asarray(
        generate(params, cfg, prompt, 8, jax.random.key(7), temperature=0.0)
    )
    cfg_off = dataclasses.replace(cfg, doc_mask_token=-1)
    want = np.asarray(
        generate(params, cfg_off, prompt, 8, jax.random.key(7), temperature=0.0)
    )
    np.testing.assert_array_equal(got, want)


def test_doc_mask_validation_and_decode_rejection():
    with pytest.raises(ValueError, match="ring/ulysses"):
        ModelConfig(attention_impl="ring", doc_mask_token=0)
    with pytest.raises(ValueError, match="pipeline"):
        ModelConfig(pipeline_stages=2, n_layers=12, doc_mask_token=0)
    with pytest.raises(ValueError, match="vocab"):
        ModelConfig(vocab_size=100, doc_mask_token=100)
    # cached decode must refuse doc masking
    cfg = dataclasses.replace(get_preset("tiny").model, doc_mask_token=0)
    params = transformer.init_params(cfg, jax.random.key(0))
    cache = transformer.make_kv_cache(cfg, 1, 8)
    toks = jnp.ones((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="doc_mask"):
        transformer.forward(params, toks, cfg, kv_cache=cache,
                            cache_index=jnp.int32(0))
