"""EMA parameter shadow (train.ema_decay): math, checkpoint, sharding, CLIs."""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.data import loader
from pretraining_llm_tpu.training import train_step as ts


def _cfg(**train_kw):
    cfg = get_preset("tiny")
    return cfg.replace(train=dc.replace(cfg.train, ema_decay=0.9, batch_size=8,
                                        **train_kw))


def test_ema_update_math():
    """ema_{t+1} = d * ema_t + (1-d) * params_{t+1}, in fp32."""
    cfg = _cfg()
    state = ts.init_train_state(cfg, jax.random.key(0))
    assert "ema" in state
    # init: shadow == params
    np.testing.assert_array_equal(
        np.asarray(state["ema"]["tok_embed"]["embedding"]),
        np.asarray(state["params"]["tok_embed"]["embedding"], np.float32),
    )
    step = ts.build_train_step(cfg, None)
    it = loader.synthetic_iterator(
        cfg.model.vocab_size, cfg.model.context_length, 8, seed=0
    )
    x, y = next(it)
    prev_ema = jax.tree.map(jnp.copy, state["ema"])
    state, _ = step(state, (jnp.asarray(x), jnp.asarray(y)))
    want = jax.tree.map(
        lambda e, p: 0.9 * e + 0.1 * p.astype(jnp.float32),
        prev_ema, state["params"],
    )
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(state["ema"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_ema_off_by_default():
    cfg = get_preset("tiny")
    assert "ema" not in ts.init_train_state(cfg, jax.random.key(0))


def test_ema_validation():
    from pretraining_llm_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="ema_decay"):
        TrainConfig(ema_decay=1.0)


def test_ema_sharded_step_and_checkpoint_roundtrip(tmp_path, mesh8):
    """EMA shards like the params and round-trips through the checkpoint;
    --ema loading returns the shadow, not the raw params."""
    from pretraining_llm_tpu.generation.generate import load_model_for_inference
    from pretraining_llm_tpu.training import checkpoint as ckpt

    mesh = mesh8
    tiny = get_preset("tiny")
    cfg = tiny.replace(
        mesh=dc.replace(tiny.mesh, data=2, fsdp=2, tensor=2),
        train=dc.replace(tiny.train, ema_decay=0.5, batch_size=8),
    )
    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh, cfg)
    step = ts.build_train_step(cfg, mesh)
    x = jax.random.randint(
        jax.random.key(1), (8, cfg.model.context_length), 0, cfg.model.vocab_size
    )
    sharded, _ = step(sharded, (x, jnp.roll(x, -1, axis=1)))
    # shadow diverged from params (params moved, ema lags)
    d_p = np.asarray(sharded["params"]["tok_embed"]["embedding"], np.float32)
    d_e = np.asarray(sharded["ema"]["tok_embed"]["embedding"])
    assert np.abs(d_p - d_e).max() > 0

    ckpt.save_checkpoint(
        str(tmp_path / "ck"), 1, jax.device_get(sharded),
        extra={"step": 1, "config": dc.asdict(cfg), "preset": "tiny"},
    )
    raw, _ = load_model_for_inference(str(tmp_path / "ck"))
    shadow, _ = load_model_for_inference(str(tmp_path / "ck"), use_ema=True)
    np.testing.assert_array_equal(
        np.asarray(shadow["tok_embed"]["embedding"]), d_e
    )
    assert np.abs(
        np.asarray(raw["tok_embed"]["embedding"], np.float32) - d_e
    ).max() > 0


def test_ema_missing_fails_loudly(tmp_path):
    from pretraining_llm_tpu.generation.generate import load_model_for_inference
    from pretraining_llm_tpu.training import checkpoint as ckpt

    cfg = get_preset("tiny")  # no ema
    state = ts.init_train_state(cfg, jax.random.key(0))
    ckpt.save_checkpoint(
        str(tmp_path / "ck"), 0, jax.device_get(state),
        extra={"step": 0, "config": dc.asdict(cfg), "preset": "tiny"},
    )
    with pytest.raises(ValueError, match="no EMA shadow"):
        load_model_for_inference(str(tmp_path / "ck"), use_ema=True)


def test_ema_enabled_mid_run_seeds_from_params(tmp_path):
    """Resuming with ema_decay>0 from a checkpoint that has no shadow must
    seed it from the restored params, not crash."""
    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.training.trainer import Trainer

    tiny = get_preset("tiny")
    base = tiny.replace(
        train=dc.replace(
            tiny.train, batch_size=8, train_steps=3, checkpoint_interval=2,
            checkpoint_dir=str(tmp_path / "ck"), eval_interval=0,
            log_interval=10, save_final=True, metrics_path="",
        ),
    )
    it = loader.synthetic_iterator(
        base.model.vocab_size, base.model.context_length, 8, seed=0
    )
    Trainer(base, train_iterator=it).train()

    resumed_cfg = base.replace(train=dc.replace(base.train, ema_decay=0.9,
                                                train_steps=5))
    it2 = loader.synthetic_iterator(
        base.model.vocab_size, base.model.context_length, 8, seed=0
    )
    tr = Trainer(resumed_cfg, train_iterator=it2)
    assert "ema" in tr.state  # seeded, not crashed
    tr.train()  # shadow updates through the remaining steps
