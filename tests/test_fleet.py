"""Multi-replica serving fleet: router tier, serving-path fault
injection, and drain/redrive of in-flight requests.

The correctness bar extends the frontend tests' contract across replica
failure: a request redriven to a survivor (after a crash, hang, or
administrative drain of its replica) must resume from its committed
token frontier and finish with greedy output BIT-IDENTICAL to a run
that never saw the disturbance — at every pipeline depth, prefix cache
on or off — with the survivor's allocator accounting matching an
undisturbed engine and zero requests lost.
"""

import dataclasses
import importlib.util
import json
import os
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.admission import (
    AdmissionController,
    RejectedBusy,
)
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import (
    FleetAction,
    LoadSpec,
    build_schedule,
    rolling_restart_plan,
)
from pretraining_llm_tpu.frontend.replica import Replica, ReplicaUnavailable
from pretraining_llm_tpu.frontend.router import Router, prefix_digest
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import (
    MetricsRegistry,
    render_merged,
)
from pretraining_llm_tpu.resilience.faults import (
    InjectedFault,
    ServingFault,
    ServingFaultInjector,
    parse_serving_faults,
)

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")

# The offline analyzer doubles as the fleet-report checker: import it as
# a module so tests assert with EXACTLY the logic the CI gate runs.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_fleet", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _engine_factory(params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_blocks", 24)
    kw.setdefault("block_size", 8)
    kw.setdefault("steps_per_sched", 4)
    kw.setdefault("pipeline_depth", 2)

    def factory():
        return ServingEngine(params, CFG, temperature=0.0, **kw)

    return factory


def _undisturbed(params, prompts, n_new, **kw):
    """Reference outputs: one engine, no fleet, no faults. Greedy decode
    is bit-identical across batch/scheduling config, so this is THE
    answer any disturbed fleet run must reproduce."""
    eng = _engine_factory(params, **kw)()
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return {rids[rid]: toks for rid, toks in out.items()}


def _fleet(params, n=2, faults=None, bus=None, engine_kw=None, **router_kw):
    factory = _engine_factory(params, **(engine_kw or {}))
    reps = [
        Replica(i, factory, bus=bus, fault_injector=faults)
        for i in range(n)
    ]
    router_kw.setdefault("eject_backoff_s", 0.1)
    return Router(reps, bus=bus, **router_kw)


# -- fault-plan parsing -----------------------------------------------------


def test_parse_serving_faults():
    plan = parse_serving_faults(
        "replica_crash@req2:r0, slow_window@req5, reject_storm@req1:r1"
    )
    assert plan == [
        ServingFault("replica_crash", 2, 0),
        ServingFault("slow_window", 5, None),
        ServingFault("reject_storm", 1, 1),
    ]
    with pytest.raises(ValueError, match="empty serving fault plan"):
        parse_serving_faults("")
    with pytest.raises(ValueError, match="unknown serving fault"):
        parse_serving_faults("chaos@req1")
    with pytest.raises(ValueError, match="req"):
        parse_serving_faults("replica_crash@2")
    with pytest.raises(ValueError, match="replica"):
        parse_serving_faults("replica_crash@req2:rX")


# -- redrive bit-identity (satellite 4) -------------------------------------


@pytest.mark.parametrize("depth", [1, 2, 3])
@pytest.mark.parametrize("cache", [False, True], ids=["nocache", "cache"])
def test_redrive_bit_identity_after_crash(params, depth, cache):
    """Crash a replica with requests mid-decode: every request fails over
    to the survivor, resumes from its committed frontier, and its final
    greedy output is bit-identical to a run that never crashed — at every
    pipeline depth, prefix cache on and off."""
    prompts = _prompts(6)
    n_new = 8
    kw = dict(pipeline_depth=depth, prefix_cache=cache)
    ref = _undisturbed(params, prompts, n_new, **kw)

    faults = ServingFaultInjector("replica_crash@req2:r0")
    router = _fleet(params, faults=faults, engine_kw=kw)
    with router:
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], f"request {i} diverged after redrive"
    assert router.counters["redrives"] >= 1
    assert router.counters["ejects"] == 1
    assert sum(1 for _, _, inf in results if inf["redrives"] > 0) >= 1


def test_redrive_preserves_committed_frontier(params):
    """A redriven request does NOT regenerate tokens it already streamed:
    the committed frontier before the crash is a prefix of the final
    output (the continuation decodes only the remainder)."""
    prompts = _prompts(4)
    n_new = 10
    ref = _undisturbed(params, prompts, n_new)
    faults = ServingFaultInjector("replica_crash@req2:r0", slow_ticks=0)
    router = _fleet(params, faults=faults)
    with router:
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done"
        assert tokens == ref[i]
        assert len(tokens) == n_new


def test_redrive_lineage_joins_one_trace_tree(params):
    """With tracing on, a redriven request stays ONE lineage tree: a
    single root span owned by the router, one ``req.attempt`` child per
    placement attempt (the crashed attempt tagged ``redriven``, the
    survivor ``done``), a single terminal, and terminal bodies carrying
    replica + redrives alongside trace_id — checked with exactly the
    tree logic the CI gate runs (obs_report)."""
    from pretraining_llm_tpu.observability.spans import SpanRecorder
    from pretraining_llm_tpu.observability.tracing import Tracer

    prompts = _prompts(4)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new)
    recorder = SpanRecorder(max_events=20000)
    tracer = Tracer(recorder, sample=1.0, seed=0)
    faults = ServingFaultInjector("replica_crash@req2:r0")
    router = _fleet(params, faults=faults, tracer=tracer)
    with router:
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done"
        assert tokens == ref[i]
        # Satellite: terminal info names the serving replica and the
        # redrive count next to the trace_id (what the gateway returns).
        assert "trace_id" in info and "replica" in info
        assert info["replica"] in (0, 1)
        assert info["redrives"] >= 0
    assert any(info["redrives"] > 0 for _, _, info in results)

    trace = recorder.to_chrome_trace()
    groups = obs_report.group_request_spans(trace)
    assert len(groups) == len(prompts)
    for tid, spans in groups.items():
        assert obs_report.check_trace_tree(tid, spans) == []
    report = obs_report.build_fleet_trace_report(trace)
    assert report["problems"] == []
    assert report["n_requests"] == len(prompts)
    assert report["redriven_requests"] >= 1
    redriven = next(
        r for r in report["requests"] if (r["redrives"] or 0) > 0
    )
    outcomes = [a["outcome"] for a in redriven["attempts"]]
    assert outcomes[-1] == "done" and "redriven" in outcomes[:-1]
    # Attempt spans carry the redrive index they ran under — a
    # monotone lineage, ending at the redrive count the client saw.
    rd = [a["redrive"] for a in redriven["attempts"]]
    assert rd == sorted(rd) and rd[0] == 0 and rd[-1] == redriven["redrives"]
    assert abs(redriven["sum_error_s"]) <= 0.01 * redriven["e2e_s"] + 1e-9


def test_survivor_allocator_matches_undisturbed(params):
    """After the drill settles, the survivor's allocator must hold
    exactly the blocks an undisturbed engine would (all freed), and the
    relaunched replica's fresh engine starts with a full pool — a crash
    must not leak pages anywhere in the fleet."""
    prompts = _prompts(5)
    faults = ServingFaultInjector("replica_crash@req2:r0")
    router = _fleet(params, faults=faults)
    with router:
        reqs = [router.submit(p, 8) for p in prompts]
        for r in reqs:
            status, _, _ = r.result(timeout=120)
            assert status == "done"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(rep.accepting for rep in router.replicas):
                break
            time.sleep(0.05)
        for rep in router.replicas:
            assert rep.accepting, rep.debug_snapshot()
            # block 0 is reserved; everything else must be back.
            assert rep.engine.alloc.available == 24 - 1, rep.index
        assert router.replicas[0].generation == 2  # relaunched once


# -- drain / rolling restart ------------------------------------------------


def test_drain_redrives_inflight_and_restore(params):
    """Administrative drain mid-decode: the drained replica's in-flight
    requests fail over and finish bit-identical; the replica refuses new
    work until restore() brings it back with a fresh engine."""
    prompts = _prompts(4)
    n_new = 12
    ref = _undisturbed(params, prompts, n_new)
    router = _fleet(params)
    with router:
        # Slow both engines down so requests are reliably mid-decode.
        for rep in router.replicas:
            orig = rep.engine.pipeline_tick

            def slow(orig=orig):
                time.sleep(0.03)
                return orig()

            rep.engine.pipeline_tick = slow
        reqs = [router.submit(p, n_new) for p in prompts]
        time.sleep(0.08)  # let decode start
        victim = next(
            (rr.replica for rr in reqs if rr.replica is not None), 0
        )
        router.drain(victim)
        rep = router.replicas[victim]
        assert rep.state == "draining"
        with pytest.raises(ReplicaUnavailable):
            rep.submit([1, 2, 3], 4)
        results = [r.result(timeout=120) for r in reqs]
        for i, (status, tokens, _) in enumerate(results):
            assert status == "done"
            assert tokens == ref[i]
        router.restore(victim)
        assert rep.state == "active"
        assert rep.generation == 2
        status, tokens, _ = router.submit([1, 2, 3], 4).result(timeout=120)
        assert status == "done"


def test_rolling_restart_plan_shape():
    plan = rolling_restart_plan(3, start_s=1.0, step_s=0.5)
    assert [a.kind for a in plan] == ["drain", "restore"] * 3
    assert plan[0].at_s == 1.0 and plan[1].at_s == 1.5
    assert plan[4] == FleetAction(at_s=2.0, kind="drain", replica=2)
    with pytest.raises(ValueError, match="unknown fleet action"):
        FleetAction(at_s=0.0, kind="reboot", replica=0)
    with pytest.raises(ValueError, match="at_s"):
        FleetAction(at_s=-1.0, kind="kill", replica=0)


# -- watchdog: hang detection ----------------------------------------------


def test_hang_watchdog_ejects_and_redrives(params):
    """replica_hang wedges the loop thread inside one scheduler turn; the
    router's watchdog sees last_turn_age_s grow with requests active,
    ejects the replica, and redrives — clients never notice beyond
    latency."""
    prompts = _prompts(4)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new)
    faults = ServingFaultInjector("replica_hang@req2:r0")
    router = _fleet(
        params, faults=faults, wedged_after_s=0.3, health_interval_s=0.02
    )
    try:
        router.start()
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        for i, (status, tokens, _) in enumerate(results):
            assert status == "done"
            assert tokens == ref[i]
        assert router.counters["ejects"] >= 1
        assert router.counters["redrives"] >= 1
    finally:
        # The hung daemon thread cannot join; don't wait for it.
        router.stop(timeout=0.5)


# -- reject_storm spills to peers -------------------------------------------


def test_reject_storm_spills_to_peer(params):
    """A replica in an injected 429 storm refuses submissions; the router
    walks to the next candidate, so every request still completes."""
    prompts = _prompts(6)
    faults = ServingFaultInjector("reject_storm@req1:r0", storm_rejects=3)
    router = _fleet(params, faults=faults)
    with router:
        reqs = [router.submit(p, 6) for p in prompts]
        for r in reqs:
            status, _, _ = r.result(timeout=120)
            assert status == "done"
        # The storm consumed 3 rejects on replica 0; the spilled requests
        # landed on replica 1.
        assert router.replicas[1].submits >= 3


def test_slow_window_fault_completes(params):
    """slow_window stretches scheduler turns without killing anything:
    results stay bit-identical, no ejects with the watchdog off."""
    prompts = _prompts(3)
    ref = _undisturbed(params, prompts, 6)
    faults = ServingFaultInjector("slow_window@req1:r0", slow_ticks=2, slow_s=0.02)
    router = _fleet(params, faults=faults)
    with router:
        reqs = [router.submit(p, 6) for p in prompts]
        for i, r in enumerate(reqs):
            status, tokens, _ = r.result(timeout=120)
            assert status == "done"
            assert tokens == ref[i]
    assert router.counters["ejects"] == 0


# -- brownout ---------------------------------------------------------------


def test_brownout_sheds_low_priority(params):
    """With half the fleet down and brownout armed, priority-0 requests
    are shed with 429 while priority-1 requests still pass."""
    router = _fleet(
        params,
        brownout_min_healthy_frac=0.6,
        brownout_min_priority=1,
        health_interval_s=0.02,
    )
    with router:
        router.drain(1)  # healthy 1/2 < 0.6 -> brownout
        deadline = time.monotonic() + 5.0
        while not router.brownout_active and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.brownout_active
        with pytest.raises(RejectedBusy, match="brownout"):
            router.submit([1, 2, 3], 4, priority=0)
        status, _, _ = router.submit(
            [1, 2, 3], 4, priority=1
        ).result(timeout=120)
        assert status == "done"
        assert router.counters["brownout_shed"] == 1
        router.restore(1)
        while router.brownout_active and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not router.brownout_active
        status, _, _ = router.submit([1, 2, 3], 4, priority=0).result(timeout=120)
        assert status == "done"


# -- prefix affinity --------------------------------------------------------


def test_prefix_affinity_stable_and_spills(params):
    """Same prompt prefix -> same replica (rendezvous placement is a pure
    function of the digest); load imbalance past spill_margin overrides
    affinity instead of queueing behind a hot replica."""
    digest = prefix_digest([1, 2, 3, 4, 5, 6], 4)
    assert digest == prefix_digest([1, 2, 3, 4, 99, 99], 4)  # only the prefix
    assert digest != prefix_digest([9, 2, 3, 4, 5, 6], 4)

    router = _fleet(params, affinity_tokens=4, spill_margin=2)
    with router:
        hot = [7, 7, 7, 7]
        first = router.submit(hot + [1], 4)
        second = router.submit(hot + [2], 4)
        assert first.replica == second.replica  # affinity held
        for r in (first, second):
            assert r.result(timeout=120)[0] == "done"


# -- EngineLoop.stop timeout (satellite 1) ----------------------------------


def test_stop_timeout_fails_outstanding_requests(params):
    """stop(timeout=) expiring must not strand requests: outstanding ones
    get error terminals from the stopping thread, the timeout is surfaced
    as a RuntimeWarning AND the False return."""
    eng = _engine_factory(params)()
    started = threading.Event()

    def wedged_tick(*a, **kw):
        started.set()
        time.sleep(60.0)
        return False

    eng.pipeline_tick = wedged_tick
    loop = EngineLoop(eng)
    loop.start()
    req = loop.submit([1, 2, 3], 8)
    assert started.wait(10.0)
    with pytest.warns(RuntimeWarning, match="still alive"):
        clean = loop.stop(timeout=0.2)
    assert clean is False
    status, tokens, info = req.result(timeout=5.0)
    assert status == "error"
    assert "shutdown timeout" in info["reason"]


def test_stop_clean_returns_true(params):
    loop = EngineLoop(_engine_factory(params)())
    loop.start()
    req = loop.submit([1, 2, 3], 4)
    assert req.result(timeout=120)[0] == "done"
    assert loop.stop() is True


# -- /readyz vs /healthz (satellite 3) --------------------------------------


def _get(base, path, timeout=30):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_readyz_distinct_from_healthz(params):
    """A draining loop is alive (healthz 200) but must not receive new
    traffic (readyz 503) — the signal a rolling restart keys off."""
    loop = EngineLoop(_engine_factory(params)())
    gw = ServingGateway(loop, port=0)
    loop.start()
    gw.start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        assert _get(base, "/healthz")[0] == 200
        code, body = _get(base, "/readyz")
        assert code == 200 and body["status"] == "ready"
        loop.begin_drain()
        code, body = _get(base, "/readyz")
        assert code == 503 and body["status"] == "not-ready"
        assert body["draining"] is True
        assert _get(base, "/healthz")[0] == 200  # liveness unaffected
    finally:
        gw.stop()
        loop.stop()


def test_readyz_router_fleet(params):
    """Router readiness: ready while ANY replica accepts; draining the
    whole fleet flips it."""
    router = _fleet(params)
    gw = ServingGateway(router, port=0)
    router.start()
    gw.start()
    base = f"http://127.0.0.1:{gw.port}"
    try:
        code, body = _get(base, "/readyz")
        assert code == 200
        assert body["replicas"] == {"0": "active", "1": "active"}
        router.drain(0)
        assert _get(base, "/readyz")[0] == 200  # one survivor -> still ready
        router.drain(1)
        code, body = _get(base, "/readyz")
        assert code == 503
        # The fleet /metrics surface stays lintable through the gateway.
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert lint_exposition(text) == []
        assert 'replica="0"' in text and 'replica="1"' in text
    finally:
        gw.stop()
        router.stop()


# -- Retry-After jitter (satellite 2) ---------------------------------------


def test_retry_after_jitter_deterministic_and_bounded(params):
    loop = EngineLoop(_engine_factory(params)())
    a = ServingGateway(loop, port=0, retry_jitter_frac=0.5, retry_jitter_seed=7)
    b = ServingGateway(loop, port=0, retry_jitter_frac=0.5, retry_jitter_seed=7)
    c = ServingGateway(loop, port=0, retry_jitter_frac=0.5, retry_jitter_seed=8)
    seq_a = [a.retry_after_header(4.0) for _ in range(20)]
    seq_b = [b.retry_after_header(4.0) for _ in range(20)]
    seq_c = [c.retry_after_header(4.0) for _ in range(20)]
    assert seq_a == seq_b           # same seed -> same jitter sequence
    assert seq_a != seq_c           # different seed decorrelates
    for v in seq_a:
        n = int(v)                  # RFC 7231 delta-seconds: integral
        assert 4 <= n <= 6          # [base, base*(1+frac)], rounded up
    assert len(set(seq_a)) > 1      # it actually jitters
    with pytest.raises(ValueError, match="retry_jitter_frac"):
        ServingGateway(loop, port=0, retry_jitter_frac=1.5)
    # Zero jitter degrades to the exact base (ceil'd, min 1s).
    z = ServingGateway(loop, port=0, retry_jitter_frac=0.0)
    assert z.retry_after_header(0.2) == "1"
    assert z.retry_after_header(3.0) == "3"


# -- typed fleet metrics ----------------------------------------------------


def test_render_merged_one_vocabulary():
    fleet = MetricsRegistry("pllm_serving_")
    r0 = MetricsRegistry("pllm_serving_", const_labels={"replica": 0})
    r1 = MetricsRegistry("pllm_serving_", const_labels={"replica": 1})
    fleet.counter("redrives_total", "redrives").inc(2)
    for reg in (r0, r1):
        reg.counter("http_errors_total", "errors").inc(1)
        reg.gauge("queue_depth", "depth").set(3)
    text = render_merged([fleet, r0, r1], {"replicas_active": 2.0})
    assert lint_exposition(text) == []
    # One TYPE line per name even though two registries carry the series.
    assert text.count("# TYPE pllm_serving_http_errors_total counter") == 1
    assert 'pllm_serving_queue_depth{replica="0"} 3' in text
    assert 'pllm_serving_queue_depth{replica="1"} 3' in text
    assert "pllm_serving_replicas_active 2" in text
    # Same name, conflicting kinds across registries must fail loudly.
    bad = MetricsRegistry("pllm_serving_")
    bad.gauge("http_errors_total", "oops")
    with pytest.raises(ValueError, match="registered as"):
        render_merged([r0, bad], None)


def test_fleet_typed_metrics_after_drill(params):
    faults = ServingFaultInjector("replica_crash@req2:r0")
    registry = MetricsRegistry("pllm_serving_")
    router = _fleet(params, faults=faults, registry=registry)
    with router:
        reqs = [router.submit(p, 6) for p in _prompts(5)]
        for r in reqs:
            assert r.result(timeout=120)[0] == "done"
        text = router.render_metrics(router.metrics())
    assert lint_exposition(text) == []
    assert "pllm_serving_redrives_total" in text
    assert "pllm_serving_replica_ejects_total 1" in text
    assert 'pllm_serving_replica_state{replica="1"} 1' in text


# -- loadgen fleet fields ---------------------------------------------------


def test_loadspec_priority_rng_neutral():
    base = build_schedule(LoadSpec(n_requests=12, seed=11))
    off = build_schedule(LoadSpec(n_requests=12, seed=11, priority_hi_frac=0.0))
    assert off == base  # frac=0 consumes no rng: schedules byte-identical
    assert all(sr.priority == 0 for sr in base)
    on = build_schedule(
        LoadSpec(n_requests=12, seed=11, priority_hi_frac=0.5, priority_hi=2)
    )
    assert {sr.priority for sr in on} == {0, 2}
    # Request 0's prompt draws precede its priority draw: unchanged.
    assert on[0].prompt == base[0].prompt
    with pytest.raises(ValueError, match="priority_hi_frac"):
        LoadSpec(priority_hi_frac=1.5)


def test_frontend_config_fleet_validation():
    fc = FrontendConfig(replicas=3, serving_faults="replica_crash@req2:r0")
    assert fc.replicas == 3
    with pytest.raises(ValueError, match="replicas"):
        FrontendConfig(replicas=0)
    with pytest.raises(ValueError, match="spill_margin"):
        FrontendConfig(spill_margin=0)
    with pytest.raises(ValueError, match="eject_backoff_max_s"):
        FrontendConfig(eject_backoff_s=2.0, eject_backoff_max_s=1.0)
    with pytest.raises(ValueError, match="brownout_min_healthy_frac"):
        FrontendConfig(brownout_min_healthy_frac=2.0)
    with pytest.raises(ValueError, match="retry_jitter_frac"):
        FrontendConfig(retry_jitter_frac=-0.1)


# -- fleet observability: conservation + recovery (obs_report --fleet) ------


def test_fleet_report_conservation_and_recovery(params, tmp_path):
    """The crash drill's event stream must pass the CI fleet gate: every
    submit reaches a terminal, redrives join to known frids, the eject
    incident carries a measured recovery time — and REMOVING a terminal
    makes the strict gate fail (the gate actually detects loss)."""
    path = tmp_path / "events.jsonl"
    bus = EventBus(jsonl_path=str(path))
    faults = ServingFaultInjector("replica_crash@req2:r0", bus=bus)
    router = _fleet(params, faults=faults, bus=bus)
    with router:
        reqs = [router.submit(p, 8) for p in _prompts(6)]
        for r in reqs:
            assert r.result(timeout=120)[0] == "done"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(rep.accepting for rep in router.replicas):
                break
            time.sleep(0.05)
    bus.close()

    events = [json.loads(ln) for ln in path.read_text().splitlines()]
    report = obs_report.build_fleet_report(events)
    assert report["problems"] == []
    assert report["lost_requests"] == 0
    assert report["n_submitted"] == report["n_terminal"] == 6
    assert report["statuses"] == {"done": 6}
    assert report["redrive_cost"]["redrive_events"] >= 1
    ejected = [
        i for i in report["incidents"]
        if i["kind"] == "ejected" and i["recovery_s"] is not None
    ]
    assert ejected and ejected[0]["replica"] == 0
    assert ejected[0]["recovery_s"] > 0

    # Drop one terminal: the conservation check must catch the loss.
    term = next(e for e in events if e.get("event") == "fleet_req_terminal")
    broken = obs_report.build_fleet_report([e for e in events if e is not term])
    assert any("LOST" in p for p in broken["problems"])


def test_injected_crash_is_attributable(params, tmp_path):
    """fault_injected events carry the plan entry that fired, so a drill's
    outcome is attributable to its cause in the same JSONL."""
    path = tmp_path / "events.jsonl"
    bus = EventBus(jsonl_path=str(path))
    faults = ServingFaultInjector("replica_crash@req2:r0", bus=bus)
    router = _fleet(params, faults=faults, bus=bus)
    with router:
        reqs = [router.submit(p, 6) for p in _prompts(4)]
        for r in reqs:
            assert r.result(timeout=120)[0] == "done"
    bus.close()
    events = [json.loads(ln) for ln in path.read_text().splitlines()]
    fired = [e for e in events if e.get("event") == "fault_injected"]
    assert len(fired) == 1
    assert fired[0]["fault"] == "replica_crash"
    assert fired[0]["replica"] == 0
    assert fired[0]["req_n"] == 2


# -- router shutdown sweeps stragglers --------------------------------------


def test_router_stop_terminates_live_requests(params):
    """Stopping the fleet mid-decode must deliver SOME terminal to every
    live request — the belt-and-suspenders sweep, not a client hang."""
    router = _fleet(params)
    router.start()
    for rep in router.replicas:
        orig = rep.engine.pipeline_tick

        def slow(orig=orig):
            time.sleep(0.05)
            return orig()

        rep.engine.pipeline_tick = slow
    reqs = [router.submit(p, 50) for p in _prompts(4)]
    time.sleep(0.1)
    router.stop(timeout=5.0)
    for r in reqs:
        status, _, info = r.result(timeout=5.0)
        assert status in ("done", "error")
        if status == "error":
            assert "shutdown" in info["reason"]
