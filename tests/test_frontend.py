"""Online serving frontend: engine loop, cancellation/deadlines, admission,
HTTP/SSE gateway, and the SLO load generator.

The correctness bar mirrors the pipelined-scheduler tests: the ONLINE
path (requests arriving/cancelling/expiring mid-decode through the
EngineLoop) must emit greedy tokens BIT-IDENTICAL to the offline
``ServingEngine.run()`` — and cancelling a request mid-window must leave
every survivor's output identical to a run that never saw the victim,
with the victim's row and pool blocks back in the allocator.
"""

import dataclasses
import importlib.util
import json
import os
import socket
import struct
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import Config, FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.admission import (
    AdmissionController,
    RejectedBusy,
    RejectedInfeasible,
)
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.loadgen import (
    LoadSpec,
    RequestOutcome,
    LoadReport,
    build_schedule,
    run_engine_loop,
)
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import MetricsRegistry
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.tracing import Tracer

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")

# The offline analyzer doubles as the trace-tree checker: import it as a
# module so the tests assert with EXACTLY the logic the CI gate runs.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_frontend", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _reference_greedy(params, prompt, n_new):
    toks = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


def _engine(params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_blocks", 24)
    kw.setdefault("block_size", 8)
    kw.setdefault("steps_per_sched", 4)
    kw.setdefault("pipeline_depth", 2)
    return ServingEngine(params, CFG, temperature=0.0, **kw)


def _throttle(eng, delay=0.05):
    """Slow every scheduler turn down so 'mid-generation' is a state a
    test can reliably act in — a warm tiny model on CPU otherwise decodes
    an entire request in a few milliseconds and cancel/backpressure tests
    race the finish."""
    orig = eng.pipeline_tick

    def slow_tick():
        time.sleep(delay)
        return orig()

    eng.pipeline_tick = slow_tick


# -- submit-time validation (satellite 1) ----------------------------------


def test_submit_validation_rejects_clearly(params):
    eng = _engine(params)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([], 4)
    with pytest.raises(ValueError, match="must be >= 1"):
        eng.submit([1, 2], 0)
    with pytest.raises(ValueError, match="must be >= 1"):
        eng.submit([1, 2], -3)
    with pytest.raises(ValueError, match="integer"):
        eng.submit([1, 2], 2.5)  # silent truncation to 2 would be a lie
    with pytest.raises(ValueError, match="integer"):
        eng.submit([1, 2], "8")
    with pytest.raises(ValueError, match="integer token ids"):
        eng.submit([0.5, 1.5], 4)
    with pytest.raises(ValueError, match="token ids must be in"):
        eng.submit([0, CFG.vocab_size], 4)
    with pytest.raises(ValueError, match="token ids must be in"):
        eng.submit([-1, 3], 4)
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit([1] * 10, eng.max_seq)  # prompt + max_new > max_seq
    # Nothing was queued by any of the rejects.
    assert not eng.waiting and eng.stats["tokens"] == 0


def test_submit_validation_rejects_nested_prompt(params):
    """A nested-list prompt yields a 2-D integer array that used to slip
    through the dtype/range checks and explode later (after admission had
    already charged a slot); it must be a clear submit-time ValueError."""
    eng = _engine(params)
    with pytest.raises(ValueError, match="flat"):
        eng.submit([[1], [2]], 4)
    with pytest.raises(ValueError, match="flat"):
        eng.submit(np.array([[1, 2], [3, 4]]), 4)
    with pytest.raises(ValueError):
        eng.submit([[1], [2, 3]], 4)  # ragged: rejected, message numpy's
    assert not eng.waiting and not eng.req_timing


def test_submit_validation_pool_capacity(params):
    # A request larger than the whole pool can NEVER run: reject at submit.
    eng = _engine(params, n_blocks=3, block_size=8)  # 2 usable blocks
    with pytest.raises(ValueError, match="blocks"):
        eng.submit(list(range(17)), 8)


def test_validate_request_is_pure(params):
    eng = _engine(params)
    assert eng.validate_request([1, 2, 3], 5) == 5
    assert not eng.waiting and not eng.req_timing


# -- engine-level cancellation ---------------------------------------------


def test_cancel_waiting_request(params):
    eng = _engine(params)
    prompts = _prompts(5)
    rids = [eng.submit(p, 6) for p in prompts]
    victim = rids[3]  # more requests than rows: rid 3 starts out waiting
    assert eng.cancel(victim)
    out = eng.run()
    assert victim not in out
    assert set(out) == set(rids) - {victim}
    for rid in out:
        assert out[rid] == _reference_greedy(params, prompts[rids.index(rid)], 6)
    assert eng.alloc.available == 24 - 1  # block 0 reserved
    assert eng.stats["cancelled"] == 1
    assert not eng.cancel(victim)  # already gone


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_cancel_running_mid_window_survivors_bit_identical(params, depth):
    """Cancel a RUNNING request while dispatched windows are still in
    flight: the flush-before-free ordering must keep every survivor's
    output bit-identical to a run that never contained the victim, and
    the victim's pages must return to the allocator."""
    prompts = _prompts(5)
    n_new = 10
    eng = _engine(params, pipeline_depth=depth)
    rids = [eng.submit(p, n_new) for p in prompts]
    for _ in range(2):  # get rows mid-generation with windows in flight
        if eng.has_work() or eng._inflight:
            eng.pipeline_tick()
    victim = next(r.rid for r in eng.rows if r is not None)
    assert eng.cancel(victim) or victim in eng.finished
    cancelled_live = victim not in eng.finished
    while eng.has_work() or eng._inflight:
        eng.pipeline_tick()
    if cancelled_live:
        assert victim not in eng.finished
        assert eng.stats["cancelled"] == 1
    survivors = [r for r in rids if r != victim or not cancelled_live]
    assert set(eng.finished) == set(survivors)
    assert eng.alloc.available == 24 - 1

    peers = _engine(params, pipeline_depth=depth)
    peer_rids = {
        peers.submit(prompts[rids.index(r)], n_new): r for r in survivors
    }
    peer_out = peers.run()
    for prid, rid in peer_rids.items():
        assert eng.finished[rid] == peer_out[prid]
        assert eng.finished[rid] == _reference_greedy(
            params, prompts[rids.index(rid)], n_new
        )


def test_cancel_running_under_preemption_pressure(params):
    """Cancellation composed with the preemption path: a pool too small
    for all rows forces preempt/recompute churn; cancelling mid-churn must
    not corrupt survivors or leak blocks."""
    prompts = _prompts(4, lengths=(12, 14, 10, 13))
    n_new = 12
    eng = _engine(params, n_blocks=9, block_size=8, steps_per_sched=4)
    rids = [eng.submit(p, n_new) for p in prompts]
    for _ in range(3):
        if eng.has_work() or eng._inflight:
            eng.pipeline_tick()
    running = [r.rid for r in eng.rows if r is not None]
    victim = running[-1]
    was_live = eng.cancel(victim)
    while eng.has_work() or eng._inflight:
        eng.pipeline_tick()
    assert eng.alloc.available == 9 - 1
    for rid in rids:
        if rid == victim and was_live:
            assert rid not in eng.finished
            continue
        assert eng.finished[rid] == _reference_greedy(
            params, prompts[rids.index(rid)], n_new
        )


def test_timing_summary_lifecycle(params):
    eng = _engine(params)
    prompts = _prompts(3)
    rids = [eng.submit(p, 5) for p in prompts]
    eng.run()
    for rid in rids:
        t = eng.timing_summary(rid)
        assert set(t) == {"queue_wait_s", "ttft_s", "e2e_s"}
        assert 0 <= t["queue_wait_s"] <= t["ttft_s"] <= t["e2e_s"]
    assert eng.timing_summary(10_000) == {}


# -- EngineLoop: online == offline -----------------------------------------


def test_engine_loop_stream_identity(params):
    """Requests submitted THROUGH THE LOOP (arriving while earlier ones
    decode) produce exactly the offline engine's greedy tokens, and the
    per-token stream concatenates to the final output."""
    prompts = _prompts(5)
    n_new = 8
    offline = _engine(params)
    off_rids = [offline.submit(p, n_new) for p in prompts]
    off_out = offline.run()

    eng = _engine(params)
    with EngineLoop(eng) as loop:
        reqs = [loop.submit(p, n_new) for p in prompts]
        streamed = []
        for req in reqs:
            toks = []
            for ev in req.events(timeout=300):
                if ev[0] == "token":
                    toks.append(ev[1])
                else:
                    assert ev[1] == "done", ev
            streamed.append(toks)
    for req, toks, orid in zip(reqs, streamed, off_rids):
        assert req.status == "done"
        assert req.tokens == off_out[orid]
        assert toks == req.tokens  # stream == final, token for token
        assert req.info["n_tokens"] == n_new
        assert 0 <= req.info["queue_wait_s"] <= req.info["ttft_s"]
        assert req.info["ttft_s"] <= req.info["e2e_s"]
    assert eng.alloc.available == 24 - 1
    assert loop.counters["completed"] == len(prompts)
    assert loop.counters["tokens_streamed"] == n_new * len(prompts)
    # Terminal bookkeeping drained the per-request engine state.
    assert not eng.req_timing and not eng.finished


def test_engine_loop_mid_decode_admission(params):
    """A request submitted while another is mid-generation joins at a
    window boundary and still matches the reference."""
    first, second = _prompts(2)
    eng = _engine(params)
    _throttle(eng, 0.02)
    with EngineLoop(eng) as loop:
        r1 = loop.submit(first, 24)
        # Wait until generation is demonstrably underway...
        for ev in r1.events(timeout=300):
            break
        # ...then inject the second request mid-decode.
        r2 = loop.submit(second, 6)
        s2, t2, _ = r2.result(timeout=300)
        s1, t1, _ = r1.result(timeout=300)
    assert (s1, s2) == ("done", "done")
    assert t1 == _reference_greedy(params, first, 24)
    assert t2 == _reference_greedy(params, second, 6)


def test_engine_loop_cancel_mid_generation(params):
    eng = _engine(params)
    _throttle(eng)
    bus = EventBus()
    seen = []
    bus.subscribe(lambda rec: seen.append(rec["event"]))
    with EngineLoop(eng, bus=bus) as loop:
        req = loop.submit(_prompts(1)[0], 48)
        got_first = next(iter(req.events(timeout=300)))
        assert got_first[0] == "token"
        loop.cancel(req)
        status, tokens, info = req.result(timeout=300)
    assert status == "cancelled"
    assert 1 <= len(tokens) < 48  # committed tokens stay delivered
    assert eng.alloc.available == 24 - 1  # pool fully reclaimed
    assert all(r is None for r in eng.rows)
    assert loop.counters["cancelled"] == 1
    assert "req_submit" in seen and "req_cancelled" in seen


def test_engine_loop_deadline_expiry_frees_blocks(params):
    eng = _engine(params)
    bus = EventBus()
    seen = []
    bus.subscribe(lambda rec: seen.append(rec["event"]))
    with EngineLoop(eng, bus=bus) as loop:
        req = loop.submit(_prompts(1)[0], 48, deadline_s=5.0)
        # Wait until generation is demonstrably mid-flight, then jump the
        # loop's deadline clock past the deadline — deterministic expiry
        # regardless of how fast the warm engine decodes.
        first = next(iter(req.events(timeout=300)))
        assert first[0] == "token"
        loop._clock = lambda: time.monotonic() + 100.0
        status, tokens, info = req.result(timeout=300)
    assert status == "expired"
    assert 1 <= len(tokens) < 48  # committed tokens stay delivered
    assert eng.alloc.available == 24 - 1
    assert all(r is None for r in eng.rows)
    assert loop.counters["expired"] == 1
    assert "req_expired" in seen


def test_engine_loop_shutdown_fails_inflight(params):
    eng = _engine(params)
    loop = EngineLoop(eng).start()
    req = loop.submit(_prompts(1)[0], 48)
    loop.stop()
    status, _, info = req.result(timeout=10)
    assert status == "error" and info.get("reason") == "shutdown"
    assert eng.alloc.available == 24 - 1
    with pytest.raises(RuntimeError):
        loop.submit([1, 2], 4)


def test_engine_loop_submit_failure_releases_ticket(params):
    """A failure AFTER admission but before the request reaches the inbox
    must hand the ticket back — otherwise each such request permanently
    burns a queue-depth slot and the service wedges into all-429."""
    eng = _engine(params)
    adm = AdmissionController(max_queue_depth=1)

    class _BoomBus:
        def emit(self, *a, **k):
            raise RuntimeError("bus exploded")

    with EngineLoop(eng, admission=adm, bus=_BoomBus()) as loop:
        with pytest.raises(RuntimeError, match="bus exploded"):
            loop.submit([1, 2, 3], 4)
        assert adm.live == 0 and adm.outstanding_tokens == 0
        loop.bus = None  # the slot is usable again
        assert loop.submit([1, 2, 3], 4).result(timeout=300)[0] == "done"


@pytest.mark.filterwarnings(
    # The loop re-raises the engine failure after delivering terminals, so
    # the thread dies LOUDLY (threading.excepthook) — that is the point.
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_engine_loop_engine_failure_terminates_requests(params):
    """If pipeline_tick raises, the loop thread must not die silently:
    every outstanding request gets an error terminal (callers blocked in
    result() wake up), tickets are released, and new submits raise
    instead of enqueueing into a dead loop."""
    eng = _engine(params)
    adm = AdmissionController(max_queue_depth=8)

    def boom():
        raise RuntimeError("device on fire")

    eng.pipeline_tick = boom
    loop = EngineLoop(eng, admission=adm).start()
    req = loop.submit(_prompts(1)[0], 8)
    status, _, info = req.result(timeout=30)
    assert status == "error"
    assert "engine failure" in info["reason"]
    assert "device on fire" in info["reason"]
    assert adm.live == 0 and adm.outstanding_tokens == 0
    with pytest.raises(RuntimeError):
        loop.submit([1, 2], 4)
    loop.stop()


# -- admission controller ---------------------------------------------------


def test_admission_depth_limit():
    adm = AdmissionController(max_queue_depth=2, retry_after_s=3.0)
    t1 = adm.try_admit(4, 4, None)
    t2 = adm.try_admit(4, 4, None)
    with pytest.raises(RejectedBusy) as exc:
        adm.try_admit(4, 4, None)
    assert exc.value.retry_after_s == 3.0
    adm.release(t1)
    adm.try_admit(4, 4, None)  # freed capacity readmits
    adm.release(t2)
    adm.release(t2)  # idempotent
    assert adm.live == 1
    assert adm.stats["rejected_busy"] == 1


def test_admission_token_budget():
    adm = AdmissionController(max_queue_depth=100, max_outstanding_tokens=100)
    adm.try_admit(50, 40, None)  # 90 outstanding
    with pytest.raises(RejectedBusy, match="token budget"):
        adm.try_admit(10, 10, None)  # 90 + 20 > 100
    adm.try_admit(5, 5, None)  # 90 + 10 fits exactly
    assert adm.outstanding_tokens == 100


def test_admission_deadline_shedding():
    adm = AdmissionController(max_queue_depth=100)
    with pytest.raises(RejectedInfeasible):
        adm.try_admit(4, 8, deadline_s=0.0)
    # No TPOT estimate yet: optimistic, admits any positive deadline.
    t = adm.try_admit(4, 8, deadline_s=0.001)
    adm.release(t, tpot_s=0.1)  # teaches ~0.1 s/token
    with pytest.raises(RejectedInfeasible):
        adm.try_admit(4, 100, deadline_s=1.0)  # needs ~10s
    adm.try_admit(4, 100, deadline_s=60.0)
    assert adm.stats["rejected_infeasible"] == 2
    assert adm.snapshot()["tpot_ewma_s"] == pytest.approx(0.1)


def test_engine_loop_applies_admission(params):
    eng = _engine(params)
    adm = AdmissionController(max_queue_depth=1)
    with EngineLoop(eng, admission=adm) as loop:
        req = loop.submit(_prompts(1)[0], 16)
        with pytest.raises(RejectedBusy):
            loop.submit([1, 2, 3], 4)
        req.result(timeout=300)
        # Terminal released the ticket: capacity is back.
        r2 = loop.submit([1, 2, 3], 4)
        assert r2.result(timeout=300)[0] == "done"
    assert adm.live == 0 and adm.outstanding_tokens == 0


# -- HTTP gateway -----------------------------------------------------------


def _post(base, payload, timeout=300):
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class _Gateway:
    def __init__(self, params, adm=None, loop_kw=None, **gw_kw):
        self.eng = _engine(params)
        self.loop = EngineLoop(self.eng, admission=adm, **(loop_kw or {}))
        self.gw = ServingGateway(self.loop, port=0, **gw_kw)

    def __enter__(self):
        self.loop.start()
        self.gw.start()
        self.base = f"http://127.0.0.1:{self.gw.port}"
        return self

    def __exit__(self, *exc):
        self.gw.stop()
        self.loop.stop()


def test_gateway_healthz_generate_and_metrics(params):
    ref = _reference_greedy(params, [1, 2, 3], 6)
    with _Gateway(params) as g:
        with urllib.request.urlopen(f"{g.base}/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"
        status, body = _post(g.base, {"prompt": [1, 2, 3], "max_new_tokens": 6})
        assert status == 200
        assert body["status"] == "done"
        assert body["tokens"] == ref  # HTTP path == reference greedy
        assert body["n_tokens"] == 6
        assert body["ttft_s"] <= body["e2e_s"]
        with urllib.request.urlopen(f"{g.base}/metrics", timeout=30) as r:
            text = r.read().decode()
    assert "# TYPE pllm_serving_completed gauge" in text
    assert "pllm_serving_completed 1" in text.replace(".0", "")
    assert "pllm_serving_submitted" in text
    assert "pllm_serving_http_requests_total" in text
    assert "pllm_serving_engine_tokens" in text


def test_gateway_sse_streaming(params):
    ref = _reference_greedy(params, [4, 5, 6], 7)
    with _Gateway(params) as g:
        req = urllib.request.Request(
            f"{g.base}/v1/generate",
            data=json.dumps(
                {"prompt": [4, 5, 6], "max_new_tokens": 7, "stream": True}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        toks, final, done_marker = [], None, False
        with urllib.request.urlopen(req, timeout=300) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            for raw in resp:
                line = raw.decode().strip()
                if not line.startswith("data: "):
                    continue
                if line == "data: [DONE]":
                    done_marker = True
                    continue
                ev = json.loads(line[len("data: "):])
                if ev.get("done"):
                    final = ev
                else:
                    assert ev["index"] == len(toks)
                    toks.append(ev["token"])
    assert toks == ref
    assert done_marker
    assert final["status"] == "done" and final["n_tokens"] == 7


def test_gateway_validation_400s(params):
    with _Gateway(params) as g:
        def expect_400(payload, match):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(g.base, payload)
            assert exc.value.code == 400
            assert match in json.loads(exc.value.read())["error"]

        expect_400({"max_new_tokens": 4}, "missing 'prompt'")
        expect_400({"prompt": [1]}, "missing 'max_new_tokens'")
        expect_400({"prompt": [], "max_new_tokens": 4}, "empty prompt")
        expect_400({"prompt": [1], "max_new_tokens": 0}, ">= 1")
        expect_400({"prompt": [1], "max_new_tokens": 2.5}, "integer")
        expect_400({"prompt": [1], "max_new_tokens": 4, "max_tokens": 4},
                   "unknown request keys")
        expect_400({"prompt": [CFG.vocab_size], "max_new_tokens": 4},
                   "token ids must be in")
        expect_400({"prompt": "text", "max_new_tokens": 4}, "tokenizer")
        expect_400({"prompt": [1], "max_new_tokens": 4, "deadline_s": -1},
                   "deadline_s")
        # Malformed JSON body.
        req = urllib.request.Request(
            f"{g.base}/v1/generate", data=b"{not json",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 400
        # Unknown route.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{g.base}/nope", timeout=30)
        assert exc.value.code == 404


def test_gateway_nested_prompt_400_no_admission_leak(params):
    """Regression: [[1],[2]] used to pass validation, charge an admission
    slot, then blow up uncaught in submit — wedging a depth-1 service
    into permanent 429. It must be a 400 with no slot consumed."""
    adm = AdmissionController(max_queue_depth=1)
    with _Gateway(params, adm=adm) as g:
        for _ in range(3):  # each leaked slot would wedge depth=1
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(g.base, {"prompt": [[1], [2]], "max_new_tokens": 4})
            assert exc.value.code == 400
            assert "flat" in json.loads(exc.value.read())["error"]
        assert adm.live == 0
        status, body = _post(g.base, {"prompt": [1, 2], "max_new_tokens": 4})
        assert status == 200 and body["status"] == "done"


def _raw_http_exchange(port, request_bytes):
    """Send raw bytes on a fresh connection; return (head, drained_to_eof)
    where head is everything received and drained_to_eof says the server
    closed the connection after responding."""
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    try:
        s.sendall(request_bytes)
        s.settimeout(10)
        buf = b""
        while True:
            try:
                chunk = s.recv(4096)
            except socket.timeout:
                return buf, False
            if not chunk:
                return buf, True
            buf += chunk
    finally:
        s.close()


def test_gateway_unread_body_closes_connection(params):
    """Keep-alive framing: error responses sent without reading the POST
    body must close the connection — otherwise the next request on the
    socket is parsed out of the leftover body bytes."""
    body = json.dumps({"prompt": [1], "max_new_tokens": 4}).encode()
    with _Gateway(params) as g:
        # POST to an unknown route: 404 with the body never read.
        raw = (
            b"POST /nope HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        head, closed = _raw_http_exchange(g.gw.port, raw)
        assert head.startswith(b"HTTP/1.1 404")
        assert b"connection: close" in head.lower()
        assert closed  # leftover body bytes can't poison a next request
        # Content-Length over the cap: 400 before any body byte is read.
        raw = (
            b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: 999999999\r\n\r\n"
        )
        head, closed = _raw_http_exchange(g.gw.port, raw)
        assert head.startswith(b"HTTP/1.1 400")
        assert b"connection: close" in head.lower()
        assert closed
        # The server itself is still healthy.
        status, _ = _post(g.base, {"prompt": [1, 2], "max_new_tokens": 4})
        assert status == 200


def test_gateway_full_response_disconnect_counts_499(params):
    """Non-streaming path: a client that RSTs while the handler is blocked
    on the result must not kill the handler thread with a traceback — the
    failed write is caught and the response accounted as a 499."""
    gobj = _Gateway(params)
    _throttle(gobj.eng)
    with gobj as g:
        body = json.dumps({"prompt": [7, 7], "max_new_tokens": 24}).encode()
        s = socket.create_connection(("127.0.0.1", g.gw.port), timeout=60)
        s.sendall(
            b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        time.sleep(0.2)  # let the handler block on result()
        # RST on close so the server's eventual write fails immediately.
        s.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
        s.close()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if g.gw.http_counters.get("http_responses_499", 0) >= 1:
                break
            time.sleep(0.05)
        assert g.gw.http_counters.get("http_responses_499", 0) == 1
        assert g.gw.http_counters.get("http_responses_200", 0) == 0
        # The server survives to serve the next client.
        status, _ = _post(g.base, {"prompt": [1, 2], "max_new_tokens": 4})
        assert status == 200
    assert g.eng.alloc.available == 24 - 1


def test_gateway_backpressure_429(params):
    adm = AdmissionController(max_queue_depth=1, retry_after_s=2.0)
    gobj = _Gateway(params, adm=adm)
    _throttle(gobj.eng)
    with gobj as g:
        # Occupy the single admission slot with a long request...
        occupier = g.loop.submit(_prompts(1)[0], 32)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(g.base, {"prompt": [1, 2], "max_new_tokens": 4})
        assert exc.value.code == 429
        assert exc.value.headers["Retry-After"] == "2"
        assert "overloaded" in json.loads(exc.value.read())["error"]
        occupier.result(timeout=300)
        status, body = _post(g.base, {"prompt": [1, 2], "max_new_tokens": 4})
        assert status == 200 and body["status"] == "done"


def test_gateway_client_disconnect_cancels(params):
    gobj = _Gateway(params)
    _throttle(gobj.eng)
    with gobj as g:
        body = json.dumps(
            {"prompt": [9, 9, 9], "max_new_tokens": 48, "stream": True}
        ).encode()
        s = socket.create_connection(("127.0.0.1", g.gw.port), timeout=60)
        s.sendall(
            b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        buf = b""
        while b"data: " not in buf:  # first committed token reached us
            chunk = s.recv(4096)
            assert chunk, f"server closed early: {buf!r}"
            buf += chunk
        s.close()  # client walks away mid-stream
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if (
                g.loop.counters["cancelled"] + g.loop.counters["completed"] >= 1
                and g.eng.alloc.available == 24 - 1
            ):
                break
            time.sleep(0.05)
        assert g.eng.alloc.available == 24 - 1  # pages reclaimed
        assert g.loop.counters["cancelled"] == 1
    assert g.gw.http_counters.get("http_responses_499", 0) == 1


# -- tracing + typed metrics through the serving path -----------------------


def _traced_loop_kw(seed=7):
    recorder = SpanRecorder()
    return recorder, {
        "tracer": Tracer(recorder, sample=1.0, seed=seed),
        "registry": MetricsRegistry("pllm_serving_"),
    }


def test_gateway_traceparent_and_typed_metrics(params):
    caller_trace = "0af7651916cd43dd8448eb211c80319c"
    caller_span = "b7ad6b7169203331"
    recorder, loop_kw = _traced_loop_kw()
    with _Gateway(params, loop_kw=loop_kw) as g:
        req = urllib.request.Request(
            f"{g.base}/v1/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4}).encode(),
            headers={
                "Content-Type": "application/json",
                "traceparent": f"00-{caller_trace}-{caller_span}-01",
            },
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            body = json.loads(resp.read())
        assert body["status"] == "done"
        # The gateway joined the caller's trace: same trace id end to end.
        assert body["trace_id"] == caller_trace

        # Caller said unsampled (flags 00): honored — no trace minted.
        req2 = urllib.request.Request(
            f"{g.base}/v1/generate",
            data=json.dumps({"prompt": [4, 5], "max_new_tokens": 4}).encode(),
            headers={
                "Content-Type": "application/json",
                "traceparent": f"00-{'c' * 32}-{'d' * 16}-00",
            },
        )
        with urllib.request.urlopen(req2, timeout=300) as resp:
            body2 = json.loads(resp.read())
        assert body2["status"] == "done"
        assert "trace_id" not in body2

        with urllib.request.urlopen(f"{g.base}/metrics", timeout=30) as r:
            text = r.read().decode()
    # Typed exposition: lint-clean, real counters/histograms, and the
    # histogram count matches the number of terminal requests.
    assert lint_exposition(text) == []
    assert 'pllm_serving_requests_terminal_total{status="done"} 2.0' in text
    assert "pllm_serving_e2e_seconds_count 2.0" in text
    assert "# TYPE pllm_serving_ttft_seconds histogram" in text
    assert "# TYPE pllm_serving_http_requests_total counter" in text

    # Exactly one trace (the unsampled request recorded nothing), complete,
    # with the root parented under the caller's span.
    trace = recorder.to_chrome_trace()
    groups = obs_report.group_request_spans(trace)
    assert set(groups) == {caller_trace}
    assert obs_report.check_trace_tree(caller_trace, groups[caller_trace]) == []
    root = [s for s in groups[caller_trace] if s["name"] == "req.request"]
    assert root[0]["args"]["parent_span_id"] == caller_span


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_trace_trees_complete_for_every_terminal(params, depth):
    """Every terminal path — done, cancelled, expired, error (shutdown
    mid-flight) and a gateway-style rejection — leaves a complete span
    tree under one trace_id, at every pipeline depth."""
    recorder, loop_kw = _traced_loop_kw(seed=depth)
    eng = _engine(params, pipeline_depth=depth)
    _throttle(eng, 0.02)
    adm = AdmissionController(max_queue_depth=2, shed_infeasible=False)
    with EngineLoop(eng, admission=adm, **loop_kw) as loop:
        r_cancel = loop.submit(_prompts(1)[0], 48)
        r_expire = loop.submit(_prompts(2)[1], 48, deadline_s=5.0)
        with pytest.raises(RejectedBusy):
            loop.submit([1, 2, 3], 4)  # queue full: rejected terminal
        first = next(iter(r_cancel.events(timeout=300)))
        assert first[0] == "token"
        loop.cancel(r_cancel)
        assert r_cancel.result(timeout=300)[0] == "cancelled"
        loop._clock = lambda: time.monotonic() + 100.0
        assert r_expire.result(timeout=300)[0] == "expired"
        r_done = loop.submit([7, 8, 9], 6)
        assert r_done.result(timeout=300)[0] == "done"
        # Left in flight on purpose: the context exit's stop() must fail
        # it with an error terminal AND a complete trace.
        r_err = loop.submit(_prompts(3)[2], 48)
        first = next(iter(r_err.events(timeout=300)))
        assert first[0] == "token"
    assert r_err.result(timeout=30)[0] == "error"
    metrics_text = loop_kw["registry"].render(extra_gauges=loop.metrics())

    trace = recorder.to_chrome_trace()
    groups = obs_report.group_request_spans(trace)
    statuses = {}
    for tid, spans in groups.items():
        assert obs_report.check_trace_tree(tid, spans) == [], tid
        root = next(s for s in spans if s["name"] == "req.request")
        statuses[root["args"]["status"]] = tid
    assert set(statuses) == {
        "done", "cancelled", "expired", "error", "rejected"
    }

    # The done request's waterfall decomposes e2e into segments that sum.
    wf = obs_report.request_waterfall(
        statuses["done"], groups[statuses["done"]]
    )
    assert wf["e2e_s"] > 0
    assert abs(wf["sum_error_s"]) <= max(1e-6, 0.01 * wf["e2e_s"])
    assert wf["n_windows"] >= 1

    # Typed metrics agree with the trace: one terminal per status (the
    # rejected request never reached the loop's terminal path).
    assert lint_exposition(metrics_text) == []
    for status in ("done", "cancelled", "expired", "error"):
        assert (
            f'pllm_serving_requests_terminal_total{{status="{status}"}} 1.0'
            in metrics_text
        )
    assert "pllm_serving_e2e_seconds_count 4.0" in metrics_text


def test_healthz_staleness_503(params):
    with pytest.raises(ValueError, match="healthz_stale_after_s"):
        ServingGateway(
            EngineLoop(_engine(params)), port=0, healthz_stale_after_s=-0.5
        )
    with _Gateway(params, healthz_stale_after_s=5.0) as g:
        with urllib.request.urlopen(f"{g.base}/healthz", timeout=30) as r:
            body = json.loads(r.read())
            assert body["status"] == "ok"
            assert body["engine_loop_last_turn_age_s"] < 5.0
        # A wedged loop thread stops advancing _last_turn; simulate by
        # shadowing the age probe rather than actually wedging the thread.
        g.loop.last_turn_age_s = lambda: 10.0
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{g.base}/healthz", timeout=30)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "stale"
    # Default (0) disables the check: liveness reported, never enforced.
    with _Gateway(params) as g:
        g.loop.last_turn_age_s = lambda: 999.0
        with urllib.request.urlopen(f"{g.base}/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"


def test_tracing_and_metrics_add_no_device_syncs(params, monkeypatch):
    """Histogram recording rides the reap's EXISTING host transfers: an
    instrumented run must pull exactly as many device arrays to host as an
    uninstrumented one (np.asarray on a jax.Array is the sync point)."""
    prompts = _prompts(4)

    def run(instrument):
        eng = _engine(params)
        reg = None
        if instrument:
            reg = MetricsRegistry("pllm_serving_")
            eng.window_hist = reg.histogram(
                "window_seconds", "decode window wall seconds"
            )
            eng.host_blocked_hist = reg.histogram(
                "host_blocked_seconds", "host blocked awaiting a window"
            )
        for p in prompts:
            eng.submit(p, 6)
        real = np.asarray
        pulls = [0]

        def spy(a, *args, **kw):
            if isinstance(a, jax.Array):
                pulls[0] += 1
            return real(a, *args, **kw)

        monkeypatch.setattr(np, "asarray", spy)
        try:
            out = eng.run(pipeline=True)
        finally:
            monkeypatch.undo()
        return out, pulls[0], eng.stats["windows_reaped"], reg

    out_plain, pulls_plain, windows_plain, _ = run(False)
    out_inst, pulls_inst, windows_inst, reg = run(True)
    assert out_inst == out_plain
    assert windows_inst == windows_plain
    assert pulls_inst == pulls_plain  # zero extra device syncs
    hist = reg.histogram("window_seconds", "decode window wall seconds")
    assert hist.count == windows_inst  # every reaped window observed


# -- load generator ---------------------------------------------------------


def test_build_schedule_deterministic():
    spec = LoadSpec(n_requests=16, mode="open", rate_rps=50.0, seed=7)
    a, b = build_schedule(spec), build_schedule(spec)
    assert a == b  # same seed -> byte-identical workload
    c = build_schedule(dataclasses.replace(spec, seed=8))
    assert a != c
    assert [sr.index for sr in a] == list(range(16))
    arrivals = [sr.arrival_s for sr in a]
    assert arrivals == sorted(arrivals) and arrivals[0] > 0
    for sr in a:
        assert 1 <= len(sr.prompt) and all(
            0 <= t < spec.vocab_size for t in sr.prompt
        )
        assert spec.max_new_min <= sr.max_new <= spec.max_new_max


def test_build_schedule_closed_mode():
    spec = LoadSpec(n_requests=5, mode="closed", concurrency=2, seed=3)
    sched = build_schedule(spec)
    assert all(sr.arrival_s == 0.0 for sr in sched)


def test_load_spec_validation():
    with pytest.raises(ValueError, match="mode"):
        LoadSpec(mode="sideways")
    with pytest.raises(ValueError, match="rate_rps"):
        LoadSpec(mode="open", rate_rps=0.0)
    with pytest.raises(ValueError, match="prompt length"):
        LoadSpec(prompt_len_min=9, prompt_len_max=4)


def test_load_report_summary_and_goodput():
    spec = LoadSpec(n_requests=4, slo_ttft_s=0.5, slo_e2e_s=2.0)
    outcomes = [
        RequestOutcome(0, "done", 8, ttft_s=0.1, tpot_s=0.01, e2e_s=1.0),
        RequestOutcome(1, "done", 8, ttft_s=0.9, tpot_s=0.01, e2e_s=1.0),  # TTFT miss
        RequestOutcome(2, "done", 8, ttft_s=0.1, tpot_s=0.01, e2e_s=3.0),  # e2e miss
        RequestOutcome(3, "rejected_busy"),
    ]
    rep = LoadReport(spec=spec, wall_s=2.0, outcomes=outcomes)
    s = rep.summary()
    assert s["counts"] == {"done": 3, "rejected_busy": 1}
    assert s["goodput_rps"] == pytest.approx(0.5)  # 1 SLO-ok req / 2s
    assert s["slo_attainment"] == pytest.approx(0.25)
    assert s["ttft"]["p50"] == pytest.approx(0.1)
    assert s["throughput_tok_s"] == pytest.approx(12.0)


def test_loadgen_against_engine_loop(params):
    eng = _engine(params)
    spec = LoadSpec(
        n_requests=4, mode="closed", concurrency=2,
        vocab_size=CFG.vocab_size, prompt_len_min=3, prompt_len_max=8,
        max_new_min=4, max_new_max=6, seed=11,
    )
    with EngineLoop(eng) as loop:
        report = run_engine_loop(loop, spec)
    s = report.summary()
    assert s["counts"] == {"done": 4}
    assert s["slo_attainment"] == 1.0  # no SLO bounds -> every done counts
    assert s["ttft"]["p50"] > 0 and s["e2e"]["p99"] >= s["e2e"]["p50"]
    # The workload itself is reproducible even though latencies are not.
    assert build_schedule(spec) == build_schedule(spec)


# -- config wiring ----------------------------------------------------------


def test_frontend_config_roundtrip_and_overrides():
    cfg = Config()
    assert cfg.frontend.max_queue_depth == 64
    cfg2 = cfg.with_overrides({
        "frontend.port": 0,
        "frontend.max_queue_depth": 8,
        "frontend.default_deadline_s": 2.5,
    })
    assert cfg2.frontend.port == 0
    assert cfg2.frontend.max_queue_depth == 8
    back = Config.from_json(cfg2.to_json())
    assert back.frontend == cfg2.frontend
    # Back-compat: configs serialized before the gateway existed.
    raw = json.loads(cfg.to_json())
    del raw["frontend"]
    assert Config.from_json(json.dumps(raw)).frontend == FrontendConfig()
    with pytest.raises(KeyError):
        cfg.with_overrides({"frontend.nope": 1})
    with pytest.raises(ValueError):
        FrontendConfig(max_queue_depth=0)
    with pytest.raises(ValueError):
        FrontendConfig(port=70000)
